#!/bin/sh
# Mint self-signed serving material + a bearer token for the REST/webhook
# surfaces at render time — the analog of the reference chart's
# secret-webhook-cert.yaml (whose data a controller injects at runtime;
# here openssl does it up front, keeping the render hermetic).
#
# Usage: sh deploy/gen_certs.sh [values.env]
# Writes deploy/certs/{tls.crt,tls.key,token} and appends the base64
# values render.sh substitutes into secret-webhook-cert.yaml /
# webhooks.yaml to deploy/certs/certs.env. Re-run to rotate.
set -e
dir="$(dirname "$0")"
values="${1:-$dir/values.env}"
set -a; . "$values"; set +a
mkdir -p "$dir/certs"

openssl req -x509 -newkey rsa:2048 -nodes -days 365 \
  -keyout "$dir/certs/tls.key" -out "$dir/certs/tls.crt" \
  -subj "/CN=${NAME}.${NAMESPACE}.svc" \
  -addext "subjectAltName=DNS:${NAME}.${NAMESPACE}.svc,DNS:${NAME}.${NAMESPACE}.svc.cluster.local,IP:127.0.0.1" \
  2>/dev/null

# 256-bit bearer token for --api-token-file
openssl rand -hex 32 > "$dir/certs/token"
chmod 600 "$dir/certs/tls.key" "$dir/certs/token"

b64() { base64 < "$1" | tr -d '\n'; }
{
  # the identity this cert names — render.sh re-mints when values.env
  # changes NAME/NAMESPACE so a stale CN can't break webhook TLS
  echo "CERT_CN=${NAME}.${NAMESPACE}.svc"
  echo "TLS_CRT_B64=$(b64 "$dir/certs/tls.crt")"
  echo "TLS_KEY_B64=$(b64 "$dir/certs/tls.key")"
  echo "API_TOKEN_B64=$(b64 "$dir/certs/token")"
  # self-signed: the cert IS the CA bundle the webhook config trusts
  echo "CA_BUNDLE_B64=$(b64 "$dir/certs/tls.crt")"
} > "$dir/certs/certs.env"
echo "wrote $dir/certs/{tls.crt,tls.key,token,certs.env}"
