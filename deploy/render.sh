#!/bin/sh
# Render deploy/templates/*.yaml with deploy/values.env into deploy/rendered/
# (the minimal Helm-template analog). Usage: sh deploy/render.sh [values.env]
# Uses envsubst when present, else a python fallback (same ${VAR} syntax).
set -e
dir="$(dirname "$0")"
values="${1:-$dir/values.env}"
set -a; . "$values"
# serving cert/token material: auto-mint on first render (a render
# without real material would produce a crashlooping deployment — the
# container flags, HTTPS probes, and webhook caBundle all expect it),
# and re-mint when NAME/NAMESPACE changed since the cert was cut (a
# stale CN would fail the kube-apiserver's webhook TLS verification)
want_cn="${NAME}.${NAMESPACE}.svc"
have_cn="$(grep '^CERT_CN=' "$dir/certs/certs.env" 2>/dev/null | cut -d= -f2)"
if [ "$have_cn" != "$want_cn" ]; then
  sh "$dir/gen_certs.sh" "$values"
fi
. "$dir/certs/certs.env"
set +a
mkdir -p "$dir/rendered"
for f in "$dir"/templates/*.yaml; do
  out="$dir/rendered/$(basename "$f")"
  if command -v envsubst >/dev/null 2>&1; then
    envsubst < "$f" > "$out"
  else
    python3 -c 'import os,sys; sys.stdout.write(os.path.expandvars(sys.stdin.read()))' < "$f" > "$out"
  fi
done
cp "$dir"/crds/*.yaml "$dir/rendered/"
echo "rendered $(ls "$dir/rendered" | wc -l) manifests to $dir/rendered/"
