"""deflake — repeat the test suite until it fails.

Analog of the reference's flake hunter (`make deflake`, Makefile:66-73:
ginkgo --race --until-it-fails over randomized spec order). Python has
no -race, so the lever here is repetition under varied hash seeds and
reversed file order, which shakes out ordering assumptions, shared-state
leaks between tests, and timing-sensitive threading bugs.

Usage: python tools/deflake.py [-n MAX_RUNS] [pytest args...]
Exits non-zero on the first failing run, echoing its seed/order so the
failure reproduces.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_once(i: int, pytest_args: list) -> int:
    env = dict(os.environ)
    # never seed 0: PYTHONHASHSEED=0 DISABLES hash randomization, the
    # opposite of this tool's lever (valid seeds are 0..2^32-1)
    env["PYTHONHASHSEED"] = str((i * 7919 + 1) % 4294967296)
    order = ["-p", "no:cacheprovider"]
    args = [sys.executable, "-m", "pytest", "-q", *order, *pytest_args]
    reversed_order = False
    if i % 2 == 1 and not any(a.startswith("-") for a in pytest_args):
        # reversed file order every other run: spots inter-file state
        # leaks. Only when the args are pure paths — an option's VALUE
        # can itself be a path ('--ignore tests/x.py') and reordering
        # around options silently changes what runs. Directory args
        # expand to their test files so the reversal has an effect.
        explicit = [a for a in pytest_args
                    if (REPO / a).exists() or Path(a).exists()]
        files: list = []
        for a in explicit or ["tests"]:
            p = (REPO / a) if (REPO / a).exists() else Path(a)
            files += sorted(p.glob("test_*.py")) if p.is_dir() else [p]
        if len(files) > 1:
            args = [a for a in args if a not in explicit]
            args += [str(t) for t in sorted(files, reverse=True)]
            reversed_order = True
    print(f"--- run {i} (PYTHONHASHSEED={env['PYTHONHASHSEED']}, "
          f"{'reversed' if reversed_order else 'default'} order)", flush=True)
    return subprocess.call(args, cwd=str(REPO), env=env)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--max-runs", type=int, default=5)
    args, pytest_args = p.parse_known_args(argv)
    t0 = time.time()
    for i in range(args.max_runs):
        rc = run_once(i, pytest_args or ["tests/"])
        if rc != 0:
            print(f"deflake: FAILED on run {i} (rc={rc}) after "
                  f"{time.time() - t0:.0f}s — reproduce with the seed/order "
                  f"above", flush=True)
            return rc
    print(f"deflake: {args.max_runs} clean runs in {time.time() - t0:.0f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
