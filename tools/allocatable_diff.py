#!/usr/bin/env python
"""allocatable-diff: predicted vs reported node allocatable.

Analog of reference tools/allocatable-diff/main.go — walks every instance
type the lattice models, computes the framework's predicted capacity /
allocatable (lattice/overhead.py math: VM overhead, kube+system reserved,
eviction threshold, ENI-limited pods), and diffs against reported values
when given (a CSV of node-status allocatable, or live nodes in a cluster
state). The reference uses the diff to validate VM_MEMORY_OVERHEAD_PERCENT
against real EC2 nodes; this does the same for the lattice formulas.

Usage:
  python tools/allocatable_diff.py --out-file allocatable-diff.csv \
      [--overhead-percent 0.075] [--reported reported.csv]

reported.csv columns: instance_type,cpu_m,memory_mib
"""

from __future__ import annotations

import argparse
import csv
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-file", default="allocatable-diff.csv")
    p.add_argument("--overhead-percent", type=float, default=0.075,
                   help="VM memory overhead used for the prediction")
    p.add_argument("--reported", default=None,
                   help="CSV of reported allocatable "
                        "(instance_type,cpu_m,memory_mib)")
    p.add_argument("--catalog", default=None,
                   help="'real' (bundled reference catalog) or a "
                        "real-data JSON path (lattice/realdata.py schema); "
                        "default: the synthetic catalog")
    p.add_argument("--against-reference", action="store_true",
                   help="diff against the reference's own published "
                        "allocatable (the refAllocatable block the importer "
                        "preserves from instance-types.md) instead of a "
                        "reported CSV; implies --catalog real")
    args = p.parse_args(argv)
    if args.against_reference and not args.catalog:
        args.catalog = "real"

    from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice

    if args.catalog:
        from karpenter_provider_aws_tpu.lattice.realdata import load_catalog
        specs = load_catalog(None if args.catalog == "real" else args.catalog)
    else:
        specs = build_catalog()
    lattice = build_lattice(
        specs, vm_memory_overhead_percent=args.overhead_percent)
    cpu_ax = RESOURCE_AXES.index("cpu")
    mem_ax = RESOURCE_AXES.index("memory")
    pods_ax = RESOURCE_AXES.index("pods")

    reported = {}
    if args.reported:
        with open(args.reported) as f:
            for row in csv.DictReader(f):
                reported[row["instance_type"]] = (
                    float(row["cpu_m"]), float(row["memory_mib"]))
    elif args.against_reference:
        import json
        from karpenter_provider_aws_tpu.lattice.realdata import DEFAULT_PATH
        path = (DEFAULT_PATH if args.catalog == "real" else args.catalog)
        doc = json.loads(Path(path).read_text())
        for t in doc["types"]:
            ra = t.get("refAllocatable")
            if ra and ra.get("cpuMilli"):
                reported[t["name"]] = (float(ra["cpuMilli"]),
                                       float(ra["memoryMi"]))

    rows = []
    for i, name in enumerate(lattice.names):
        cap, alloc = lattice.capacity[i], lattice.alloc[i]
        row = {
            "instance_type": name,
            "capacity_cpu_m": f"{cap[cpu_ax]:.0f}",
            "capacity_memory_mib": f"{cap[mem_ax]:.0f}",
            "allocatable_cpu_m": f"{alloc[cpu_ax]:.0f}",
            "allocatable_memory_mib": f"{alloc[mem_ax]:.0f}",
            "max_pods": f"{alloc[pods_ax]:.0f}",
        }
        if name in reported:
            rcpu, rmem = reported[name]
            row["reported_cpu_m"] = f"{rcpu:.0f}"
            row["reported_memory_mib"] = f"{rmem:.0f}"
            row["cpu_diff_m"] = f"{alloc[cpu_ax] - rcpu:.0f}"
            row["memory_diff_mib"] = f"{alloc[mem_ax] - rmem:.0f}"
        rows.append(row)

    fields = list(rows[0]) if not reported else list(
        max(rows, key=len))
    with open(args.out_file, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {len(rows)} instance types to {args.out_file}")
    if reported:
        import numpy as np
        diffs = [float(r["memory_diff_mib"]) for r in rows
                 if "memory_diff_mib" in r]
        if diffs:
            print(f"memory diff MiB: mean {np.mean(diffs):.1f} "
                  f"max |{np.max(np.abs(diffs)):.1f}|")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
