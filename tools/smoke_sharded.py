#!/usr/bin/env python
"""CI smoke for the mesh-promoted sharded production path (ci.sh gate).

Boots a real Operator on a FORCED 8-device virtual CPU mesh (the same
XLA host-platform sizing ``__graft_entry__.dryrun_multichip`` and the
test suite use — a virtual mesh must be forced, auto stays
single-device on cpu), drives a seed wave plus small-churn reconcile
passes, and asserts the promotion actually holds end to end:

1. the mesh ENGAGED: the operator's planned mesh reaches the solver
   (``stats()["mesh_devices"] > 1``) and sharded solves carried passes
   (``mesh_solves`` > 0) — a mesh silently falling back to the
   single-device path would otherwise read as a vacuous green;
2. the DELTA path composes with the mesh: steady-state churn passes ride
   ``solve_delta`` on the mesh (``delta_solves`` > 0). (Resident-entry
   HIT evidence lives in the bench's delta-on-mesh row, not here: this
   smoke's fused buffers fit ONE delta block, and a 1-block change
   legitimately re-uploads whole — the >half-changed heuristic);
3. parity: on sampled churn passes the mesh-produced plan matches a
   SINGLE-DEVICE referee solve of the same cluster inputs — identical
   new-node multiset and cost (the ≤2% envelope holds exactly here:
   small waves fully dissolve into the merge refinement);
4. the surfaces report: the shard-imbalance stat is sane and the claim
   provenance annotation carries the mesh device count.

Fast by design: small-family lattice, ~100 pods — a couple of minutes
of (mostly shard_map compile) time, not a soak.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# BEFORE jax initializes: force the 8-device virtual CPU mesh
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

MESH_DEVICES = 8
CHURN_PASSES = 12


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.solver import Solver, build_problem
    from karpenter_provider_aws_tpu.utils.clock import FakeClock
    import random

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    op = Operator(options=Options(registration_delay=1.0,
                                  mesh=str(MESH_DEVICES)),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock)
    # the single-device referee: its OWN solver so the comparison can
    # never ride the mesh it referees
    referee = Solver(lattice)
    rng = random.Random(12)
    shapes = [{"cpu": "250m", "memory": "512Mi"},
              {"cpu": "500m", "memory": "1Gi"},
              {"cpu": "1", "memory": "2Gi"}]
    failures = []

    if op.solver.mesh_devices != MESH_DEVICES:
        failures.append(f"planned mesh did not reach the solver: "
                        f"mesh_devices={op.solver.mesh_devices}")

    # full pass: a 48-pod wave, settle to capacity
    for i in range(48):
        op.cluster.add_pod(Pod(name=f"seed-{i}",
                               requests=shapes[i % len(shapes)]))
    op.settle(max_rounds=30)
    if op.cluster.pending_pods():
        failures.append(f"seed wave did not settle: "
                        f"{len(op.cluster.pending_pods())} pending")

    serial = 0
    parity_checked = 0
    for pass_i in range(CHURN_PASSES):
        # small churn: 2-4 new pods arrive; 1-2 bound pods leave
        for _ in range(rng.randint(2, 4)):
            serial += 1
            op.cluster.add_pod(Pod(name=f"churn-{serial}",
                                   requests=shapes[serial % len(shapes)]))
        bound = [p.name for p in op.cluster.snapshot_pods()
                 if p.node_name is not None]
        for name in rng.sample(bound, min(len(bound), rng.randint(1, 2))):
            op.cluster.delete_pod(name)

        referee_problem = None
        if pass_i % 4 == 3:
            # capture the referee problem BEFORE the pass mutates state
            referee_problem = build_problem(
                op.cluster.pending_pods(), list(op.node_pools.values()),
                op.solver.lattice,
                existing=op.cluster.existing_bins(op.solver.lattice),
                daemonset_pods=op.cluster.daemonset_pods(),
                bound_pods=op.cluster.bound_pods())
        result = op.provisioner.provision_once()
        if referee_problem is not None and result.plan is not None:
            plan = result.plan
            if plan.mesh_devices != MESH_DEVICES:
                failures.append(f"pass {pass_i}: plan did not ride the "
                                f"mesh (mesh_devices={plan.mesh_devices})")
            ref = referee.solve(referee_problem)
            got = sorted((n.instance_type, n.zone, len(n.pods))
                         for n in plan.new_nodes)
            want = sorted((n.instance_type, n.zone, len(n.pods))
                          for n in ref.new_nodes)
            if got != want:
                failures.append(
                    f"pass {pass_i}: mesh plan diverged from the "
                    f"single-device referee ({got} vs {want})")
            if abs(plan.new_node_cost - ref.new_node_cost) > 1e-6:
                failures.append(
                    f"pass {pass_i}: cost {plan.new_node_cost} != "
                    f"referee {ref.new_node_cost}")
            parity_checked += 1
        # let launches register so later passes see the new capacity
        op.settle(max_rounds=10)

    st = op.solver.stats()
    if st.get("mesh_devices", 0) <= 1:
        failures.append(f"mesh not engaged in stats: {st.get('mesh_devices')}")
    if st.get("mesh_solves", 0) == 0:
        failures.append("no sharded solve carried a pass (mesh_solves=0)")
    if st.get("delta_solves", 0) == 0:
        failures.append("delta path never engaged ON THE MESH "
                        "(delta_solves=0) — last gate reason: "
                        f"{op.provisioner.inc_builder.last_reason!r}")
    imb = st.get("mesh_shard_imbalance", 0.0)
    if not (imb == 0.0 or imb >= 1.0):
        failures.append(f"nonsensical shard imbalance {imb}")
    if parity_checked == 0:
        failures.append("no parity pass executed (harness bug)")
    claims = [c for c in op.cluster.snapshot_claims()]
    mesh_anns = [c.annotations.get(wk.ANNOTATION_SOLVER_MESH_DEVICES)
                 for c in claims]
    if claims and str(MESH_DEVICES) not in mesh_anns:
        failures.append(
            f"no claim carries the mesh provenance annotation: {mesh_anns}")

    if failures:
        print("sharded smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"sharded smoke: OK (mesh_devices={st['mesh_devices']}, "
          f"mesh_solves={st['mesh_solves']}, "
          f"delta_solves={st['delta_solves']}, "
          f"resident_problem_hits={st['resident_problem_hits']}, "
          f"imbalance={st['mesh_shard_imbalance']}, "
          f"parity passes={parity_checked})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
