#!/usr/bin/env python
"""kpctl — the kubectl analog for the framework's REST apiserver.

The reference's operational UX is kubectl against its CRDs (the entire
website getting-started flow drives `kubectl apply/get/delete`); this is
the same surface against the control plane served by
``karpenter-tpu-controller --api-port`` (kube/httpserver.py routes):

    kpctl get KIND [NAME] [-o json|yaml|wide]   k8s-style tables
    kpctl apply -f FILE                      create-or-update from YAML/JSON
    kpctl delete KIND NAME [--force]
    kpctl watch KIND [--resource-version N]  streamed events
    kpctl evict POD [--force]
    kpctl describe KIND NAME                 object + its recorded events
    kpctl api-resources                      served kinds (discovery)

Connection flags mirror kubectl's: --server (or KPCTL_SERVER), bearer
auth via --token/--token-file, TLS via --cacert (self-signed material
from deploy/gen_certs.sh) or --insecure-skip-tls-verify.

Files for apply hold one or many documents (YAML stream or JSON list),
each ``{"kind": <plural>, "spec": {...}}`` in the serde wire schema —
`kpctl apply` is how the cross-process e2e drives provisioning
(tests/test_crossprocess_e2e.py).
"""

from __future__ import annotations

import argparse
import json
import os
import ssl
import sys
import urllib.error
import urllib.request


class Client:
    def __init__(self, server: str, token: str = None, cacert: str = None,
                 insecure: bool = False):
        self.server = server.rstrip("/")
        self.token = token
        if cacert:
            self.ctx = ssl.create_default_context(cafile=cacert)
        elif insecure:
            self.ctx = ssl.create_default_context()
            self.ctx.check_hostname = False
            self.ctx.verify_mode = ssl.CERT_NONE
        else:
            self.ctx = None

    def request(self, method: str, path: str, doc=None, stream=False,
                raw=False):
        # Accept-Encoding: gzip on non-streaming requests — the series
        # payloads a `kpctl top`/`profile` session polls are large
        # (600-sample rings x subsystems) and the server compresses them
        # ~20x (kube/httpserver.py maybe_gzip)
        r = urllib.request.Request(
            f"{self.server}{path}", method=method,
            data=None if doc is None else json.dumps(doc).encode(),
            headers={"Content-Type": "application/json",
                     **({} if stream else {"Accept-Encoding": "gzip"}),
                     **({"Authorization": f"Bearer {self.token}"}
                        if self.token else {})})
        resp = urllib.request.urlopen(r, timeout=None if stream else 30,
                                      context=self.ctx)
        st = _parse_server_time(resp.headers.get("X-Server-Time"))
        if st is not None:
            global _SERVER_NOW
            _SERVER_NOW = st
        if stream:
            return resp
        with resp:
            body = resp.read()
            if resp.headers.get("Content-Encoding") == "gzip":
                import gzip
                body = gzip.decompress(body)
            if raw:
                return body
            return json.loads(body or b"{}")


def _parse_server_time(st):
    """X-Server-Time → float epoch seconds, tolerating BOTH wire forms:
    the current plain numeric ('1234.567890') and the legacy repr() a
    pre-fix server emits under a numpy-scalar clock ('np.float64(1234.5)'
    on numpy>=2) — an old control plane must not break age rendering."""
    if st is None:
        return None
    try:
        return float(st)
    except ValueError:
        import re
        m = re.search(r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?", st)
        return float(m.group(0)) if m else None


# the reference clock for AGE/LAST SEEN columns: the SERVER's clock as
# reported by its last response (the `X-Server-Time` header on every
# route, plus the `serverTime` field in list bodies), so ages render
# correctly even when the server runs a simulated clock or the client's
# wall clock is skewed — including single-object `get KIND NAME`. Falls
# back to local time against pre-serverTime servers.
_SERVER_NOW = None


def _age(created, now=None):
    if not created:
        return "<none>"
    import time
    if now is None:
        now = _SERVER_NOW if _SERVER_NOW is not None else time.time()
    d = max(now - float(created), 0)
    if d < 120:
        return f"{int(d)}s"
    if d < 7200:
        return f"{int(d / 60)}m"
    return f"{int(d / 3600)}h"


def _cores(v):
    """Normalize a CPU quantity to cores: '12000m' → '12', '500m' → '0.5',
    '48' stays '48'. The usage/limit pair then reads in ONE unit instead
    of mixing millicores (solver-side accounting) with cores (YAML)."""
    s = str(v)
    if not s or s == "-":
        return s or "-"
    try:
        n = float(s[:-1]) / 1000.0 if s.endswith("m") else float(s)
    except ValueError:
        return s
    return f"{n:g}"


def _mem(v):
    """Normalize a memory quantity to a common suffix (Gi when it divides
    cleanly, else Mi): '2048Mi' → '2Gi', '1.5Gi' → '1536Mi'."""
    s = str(v)
    if not s or s == "-":
        return s or "-"
    suffixes = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
                "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}
    num, mult = s, 1
    for suf, m in suffixes.items():
        if s.endswith(suf):
            num, mult = s[: -len(suf)], m
            break
    try:
        b = float(num) * mult
    except ValueError:
        return s
    gi = b / 2**30
    if gi >= 1 and float(gi).is_integer():
        return f"{gi:g}Gi"
    return f"{b / 2**20:g}Mi"


def _np_status(o):
    """A NodePool's live resource usage: the envelope's controller-owned
    status sub-map (spec/status split); falls back to the legacy in-spec
    location for objects written by an older server."""
    return ((o.get("status") or {}).get("resources")
            or o["spec"].get("statusResources", {}))


# per-kind table columns: (header, spec-path extractor)
_COLUMNS = {
    "nodeclaims": (
        ("NAME", lambda o: o["metadata"]["name"]),
        ("TYPE", lambda o: o["spec"].get("instanceType") or "<pending>"),
        ("ZONE", lambda o: o["spec"].get("zone") or ""),
        ("CAPACITY", lambda o: o["spec"].get("capacityType") or ""),
        ("PHASE", lambda o: o["spec"].get("phase", "")),
        ("NODEPOOL", lambda o: o["spec"].get("nodePool", "")),
    ),
    "nodes": (
        ("NAME", lambda o: o["metadata"]["name"]),
        ("READY", lambda o: str(bool(o["spec"].get("ready"))).lower()),
        ("TYPE", lambda o: o["spec"].get("labels", {}).get(
            "node.kubernetes.io/instance-type", "")),
        ("ZONE", lambda o: o["spec"].get("labels", {}).get(
            "topology.kubernetes.io/zone", "")),
    ),
    "pods": (
        ("NAME", lambda o: o["metadata"]["name"]),
        ("NODE", lambda o: o["spec"].get("nodeName") or "<pending>"),
        ("CPU", lambda o: o["spec"].get("requests", {}).get("cpu", "")),
        ("MEMORY", lambda o: o["spec"].get("requests", {}).get("memory", "")),
    ),
    "nodepools": (
        ("NAME", lambda o: o["metadata"]["name"]),
        ("WEIGHT", lambda o: str(o["spec"].get("weight", 0))),
        # live usage vs ceiling (the controller-owned status.resources —
        # the envelope's status sub-map, never the user spec; "-" =
        # unlimited axis), both sides normalized to one unit (cores /
        # common memory suffix) so "12000m/48" never renders as two
        # different scales
        ("CPU", lambda o: "{}/{}".format(
            _cores(_np_status(o).get("cpu", "0")),
            _cores(o["spec"].get("limits", {}).get("cpu", "-")))),
        ("MEMORY", lambda o: "{}/{}".format(
            _mem(_np_status(o).get("memory", "0")),
            _mem(o["spec"].get("limits", {}).get("memory", "-")))),
    ),
    "events": (
        ("LAST SEEN", lambda o: _age(o["spec"].get("time"))),
        ("TYPE", lambda o: o["spec"].get("type", "")),
        ("REASON", lambda o: o["spec"].get("reason", "")),
        ("OBJECT", lambda o: "{}/{}".format(
            o["spec"].get("objectKind", ""), o["spec"].get("objectName", ""))),
        ("MESSAGE", lambda o: o["spec"].get("message", "")),
    ),
}
_DEFAULT_COLUMNS = (
    ("NAME", lambda o: o["metadata"]["name"]),
    ("RV", lambda o: str(o["metadata"]["resourceVersion"])),
)


def _print_rows(rows, indent: str = "") -> None:
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print(indent
              + "   ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


def print_table(kind: str, objs, wide: bool = False) -> None:
    cols = list(_COLUMNS.get(kind, _DEFAULT_COLUMNS))
    if wide:
        cols += [
            ("AGE", lambda o: _age(
                o["metadata"].get("creationTimestamp"))),
            ("RV", lambda o: str(o["metadata"]["resourceVersion"])),
        ]
    rows = [[h for h, _ in cols]]
    for o in objs:
        rows.append([f(o) or "" for _, f in cols])
    _print_rows(rows)


def load_documents(path):
    """YAML stream or JSON (object or list) → [{'kind','spec'}, ...]."""
    raw = sys.stdin.read() if path == "-" else open(path).read()
    try:
        docs = json.loads(raw)
        docs = docs if isinstance(docs, list) else [docs]
    except ValueError:
        import yaml
        docs = [d for d in yaml.safe_load_all(raw) if d]
    for d in docs:
        if "kind" not in d or "spec" not in d:
            raise SystemExit(
                f"each document needs kind+spec (got {sorted(d)})")
    return docs


def _list(c: Client, kind: str):
    """List a kind and adopt the server's clock for age rendering."""
    global _SERVER_NOW
    doc = c.request("GET", f"/apis/{kind}")
    if "serverTime" in doc:
        _SERVER_NOW = doc["serverTime"]
    return doc["items"]


def cmd_get(c: Client, args) -> int:
    if args.name:
        obj = c.request("GET", f"/apis/{args.kind}/{args.name}")
        objs = [obj]
    else:
        objs = _list(c, args.kind)
    payload = objs if args.name is None else objs[0]
    if args.output == "json":
        print(json.dumps(payload, indent=2))
    elif args.output == "yaml":
        import yaml
        print(yaml.safe_dump(payload, sort_keys=False), end="")
    else:
        print_table(args.kind, objs, wide=args.output == "wide")
    return 0


def cmd_apply(c: Client, args) -> int:
    for d in load_documents(args.filename):
        kind, spec = d["kind"], d["spec"]
        name = spec.get("name", "<unnamed>")
        try:
            c.request("POST", f"/apis/{kind}", spec)
            print(f"{kind}/{name} created")
        except urllib.error.HTTPError as e:
            if e.code != 409:
                raise
            # exists: kubectl-apply semantics — replace the spec at the
            # server's current RV
            cur = c.request("GET", f"/apis/{kind}/{name}")
            cur["spec"] = spec
            c.request("PUT", f"/apis/{kind}/{name}", cur)
            print(f"{kind}/{name} configured")
    return 0


def cmd_delete(c: Client, args) -> int:
    force = "?force=1" if args.force else ""
    c.request("DELETE", f"/apis/{args.kind}/{args.name}{force}")
    print(f"{args.kind}/{args.name} deleted")
    return 0


def cmd_watch(c: Client, args) -> int:
    rv = args.resource_version
    if rv is None:
        rv = c.request("GET", f"/apis/{args.kind}")["resourceVersion"]
    resp = c.request(
        "GET", f"/apis/{args.kind}?watch=1&resourceVersion={rv}",
        stream=True)
    for line in resp:
        ev = json.loads(line)
        if ev["type"] == "HEARTBEAT":
            continue
        if ev["type"] == "BOOKMARK":
            # RV checkpoint, no object payload — remember the resume
            # point silently (docs/reference/watch.md)
            rv = ev.get("resourceVersion", rv)
            continue
        if ev["type"] == "ERROR":
            # the server dropped this watcher (410-mid-stream: queue
            # overrun or history expiry) — report and stop; re-running
            # `kpctl watch` relists, like a reflector
            print(f"ERROR\t{ev.get('code', '')} {ev.get('reason', '')}: "
                  f"{ev.get('message', '')} (re-run to relist)",
                  flush=True)
            return 1
        name = ev["object"]["metadata"]["name"]
        print(f"{ev['type']}\t{args.kind}/{name}\trv={ev['resourceVersion']}",
              flush=True)
        if args.once:
            return 0
    return 0


def cmd_api_resources(c: Client, args) -> int:
    """kubectl api-resources analog: the kinds the server serves."""
    for k in c.request("GET", "/apis")["kinds"]:
        print(k)
    return 0


def cmd_describe(c: Client, args) -> int:
    """kubectl-describe analog: the object plus its recorded events
    (the `events` kind the control plane mirrors in API mode)."""
    obj = c.request("GET", f"/apis/{args.kind}/{args.name}")
    # fetch events FIRST: the list response carries serverTime, so the
    # Age lines below render on the server's clock, not ours
    try:
        events = _list(c, "events")
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise   # auth/server failure must not read as "no events"
        events = []   # pre-events server: describe still works
    md = obj["metadata"]
    print(f"Name:             {md['name']}")
    print(f"Kind:             {args.kind}")
    print(f"UID:              {md.get('uid', '')}")
    print(f"ResourceVersion:  {md['resourceVersion']}")
    if md.get("creationTimestamp"):
        print(f"Age:              {_age(md['creationTimestamp'])}")
    if md.get("deletionTimestamp"):
        print(f"Deleting:         since {_age(md['deletionTimestamp'])} ago")
    if md.get("finalizers"):
        print(f"Finalizers:       {', '.join(md['finalizers'])}")
    print("Spec:")
    for line in json.dumps(obj["spec"], indent=2).splitlines()[1:-1]:
        print(f" {line}")
    _print_solver_provenance(obj)

    def _matches(spec) -> bool:
        # kubectl matches involvedObject kind+name; objectName alone
        # would mis-attribute (a Node shares its NodeClaim's name)
        ok = spec.get("objectKind", "").lower()
        return (spec.get("objectName") == args.name
                and ok and args.kind in (ok + "s", ok + "es"))

    if args.kind == "pods":
        _print_pod_reasons(c, args.name)
    mine = [e["spec"] for e in events if _matches(e["spec"])]
    print("Events:")
    if not mine:
        print("  <none>")
        return 0
    rows = [["TYPE", "REASON", "AGE", "MESSAGE"]]
    rows += [[e.get("type", ""), e.get("reason", ""),
              _age(e.get("time")), e.get("message", "")] for e in mine]
    _print_rows(rows, indent="  ")
    return 0


def _print_pod_reasons(c: Client, name: str) -> None:
    """The Reasons block of `kpctl describe pod`: the pod's current
    structured reason code + last elimination summary from the
    decision-audit ring (docs/reference/explain.md). Quiet against a
    pre-explain server or an empty ring — describe must keep working."""
    try:
        doc = c.request("GET", f"/debug/explain?pod={name}")
    except (urllib.error.HTTPError, urllib.error.URLError):
        return
    if not isinstance(doc, dict) or doc.get("found") is False \
            or doc.get("enabled") is False or "outcome" not in doc:
        return
    print("Reasons:")
    if doc["outcome"] == "scheduled":
        print(f"  Outcome:        scheduled -> {doc.get('node', '?')} "
              f"(pass {doc.get('pass', '?')})")
        return
    print(f"  Outcome:        {doc['outcome']} (pass {doc.get('pass', '?')})")
    print(f"  Code:           {doc.get('code', '')}")
    print(f"  Reason:         {doc.get('reason', '')}")
    g = doc.get("group")
    if g:
        blame = g.get("blame")
        elim = next((s for s in reversed(g.get("stages", []))
                     if s.get("eliminated")), None)
        if blame and elim is not None:
            ex = elim.get("examples") or []
            print(f"  Eliminated by:  {blame}: {elim['eliminated']} "
                  f"offerings" + (f" (e.g. {ex[0]})" if ex else ""))
        print(f"  Last summary:   group {g.get('label', '?')} — "
              f"{g.get('remaining', 0)} offerings remained "
              f"(kpctl explain pod {name})")


_SOLVER_ANN = "karpenter.sh/"   # apis/wellknown.py KARPENTER_PREFIX


def _print_solver_provenance(obj) -> None:
    """The solver-provenance block of `kpctl describe nodeclaims`: the
    annotations the provisioner stamped on the claim (apis/wellknown.py)
    so an operator sees WHY this claim's solve was slow or degraded —
    path taken, degradation reason, per-stage ms, and the trace id to
    pull from the flight recorder (`kpctl trace show <id>`)."""
    ann = (obj.get("spec", {}).get("annotations")
           or obj.get("metadata", {}).get("annotations") or {})
    path = ann.get(_SOLVER_ANN + "solver-path")
    if path is None:
        return
    print("Solver:")
    print(f"  Path:           {path}")
    pipelined = ann.get(_SOLVER_ANN + "solver-pipelined")
    if pipelined is not None:
        print(f"  Pipelined:      {pipelined}")
    waves = ann.get(_SOLVER_ANN + "solver-waves")
    if waves is not None:
        print(f"  Waves:          {waves}")
    mesh = ann.get(_SOLVER_ANN + "solver-mesh-devices")
    if mesh is not None:
        print(f"  Mesh:           {mesh} devices (pod-axis sharded)")
    reason = ann.get(_SOLVER_ANN + "solver-degraded-reason")
    print(f"  Degraded:       {reason if reason else 'false'}")
    stage_ms = ann.get(_SOLVER_ANN + "solver-stage-ms")
    if stage_ms:
        try:
            stages = json.loads(stage_ms)
            rendered = "  ".join(f"{k}={v:g}ms" for k, v in stages.items())
        except ValueError:
            rendered = stage_ms
        print(f"  Stages:         {rendered}")
    tp = ann.get(_SOLVER_ANN + "traceparent")
    if tp:
        parts = tp.split("-")
        if len(parts) == 4:
            print(f"  Trace:          {parts[1]}  "
                  "(kpctl trace show <id>)")


def cmd_trace(c: Client, args) -> int:
    """The flight recorder's CLI surface (docs/reference/tracing.md):

        kpctl trace list           retained + ring traces, newest first
        kpctl trace show ID        the span tree, durations + attrs
        kpctl trace export ID      Chrome trace-event JSON (Perfetto /
                                   chrome://tracing, loadable next to an
                                   xprof device trace) to -o or stdout
    """
    if args.action in ("show", "export") and not args.id:
        raise SystemExit(f"kpctl trace {args.action} needs a trace id "
                         "(see `kpctl trace list`)")
    if args.action == "list":
        doc = c.request("GET", "/debug/traces")
        rows = [["TRACE", "ROOT", "SVC", "SPANS", "DURATION", "RETAINED",
                 "AGE"]]
        for t in doc.get("traces", []):
            rows.append([
                t["traceId"], t["root"], ",".join(t.get("svc", [])),
                str(t["spans"]), f"{t['durationMs']:.1f}ms",
                t.get("retained") or "-", _age(t.get("start"))])
        if len(rows) == 1:
            print("No traces retained.")
            stats = doc.get("stats", {})
            if stats:
                print(f"(started={stats.get('started', 0)} "
                      f"completed={stats.get('completed', 0)} "
                      f"retained={stats.get('retained', 0)})")
            return 0
        _print_rows(rows)
        return 0
    if args.action == "show":
        doc = c.request("GET", f"/debug/traces/{args.id}")
        spans = doc.get("spans", [])
        by_parent = {}
        by_id = {s["spanId"]: s for s in spans}
        for s in spans:
            pid = s.get("parentId")
            key = pid if pid in by_id else None   # remote/absent parent → root
            by_parent.setdefault(key, []).append(s)

        def walk(parent, depth):
            for s in sorted(by_parent.get(parent, []),
                            key=lambda x: x["start"]):
                attrs = {k: v for k, v in s.get("attrs", {}).items()
                         if k not in ("discard",)}
                extra = ("  " + " ".join(f"{k}={v}" for k, v in attrs.items())
                         if attrs else "")
                mark = " !" if s.get("status") == "error" else ""
                print(f"{'  ' * depth}{s['name']}  "
                      f"[{s.get('svc', '?')}] {s['durationMs']:.2f}ms"
                      f"{mark}{extra}")
                walk(s["spanId"], depth + 1)

        print(f"Trace:  {args.id}  ({len(spans)} spans)")
        walk(None, 0)
        return 0
    if args.action == "export":
        doc = c.request("GET", f"/debug/traces/{args.id}?format=chrome")
        text = json.dumps(doc, indent=1)
        if args.output_file:
            with open(args.output_file, "w") as f:
                f.write(text)
            print(f"wrote {len(doc.get('traceEvents', []))} events to "
                  f"{args.output_file}")
        else:
            print(text)
        return 0
    raise SystemExit(f"unknown trace action {args.action!r}")


def _fmt_ms(ms) -> str:
    try:
        return f"{float(ms):.1f}ms"
    except (TypeError, ValueError):
        return "-"


def _render_top(doc, server: str):
    """One frame of `kpctl top` from a /debug/vars document. Providers
    the control plane hasn't registered (direct mode has no watch hub;
    tracing may be off) simply drop their row's details."""
    p = doc.get("providers", {})

    def g(provider, key, default=0):
        return p.get(provider, {}).get(key, default)

    lines = [f"kpctl top — {server}   uptime "
             f"{doc.get('uptimeSeconds', 0):.0f}s   "
             f"providers {len(p)}", ""]
    lines.append(
        f"CLUSTER   nodes {g('cluster', 'nodes'):g}   "
        f"pods {g('cluster', 'pods'):g} "
        f"({g('cluster', 'pods_pending'):g} pending, "
        f"{g('cluster', 'pods_nominated'):g} nominated)   "
        f"claims {g('cluster', 'claims'):g} "
        f"({g('cluster', 'claims_deleting'):g} deleting)")
    degraded = sum(v for k, v in p.get("solver", {}).items()
                   if k.startswith("degraded_") and isinstance(v, (int, float)))
    lines.append(
        f"SOLVER    passes {g('provisioner', 'passes'):g}   "
        f"last {_fmt_ms(g('provisioner', 'last_pass_solve_ms', None))} "
        f"({g('provisioner', 'last_pass_pods'):g} pods)   "
        f"pipeline {'on' if g('solver', 'pipeline') else 'off'}   "
        f"mesh {g('solver', 'mesh_devices', 1):g}dev "
        f"({g('solver', 'mesh_solves'):g} sharded)   "
        f"async {g('solver', 'async_solves'):g}   "
        f"delta {g('solver', 'delta_solves'):g} "
        f"({g('solver', 'delta_dirty_groups'):g} dirty grp)   "
        f"micro {g('solver', 'micro_solves'):g} "
        f"({g('solver', 'micro_last_legs'):g} legs/pass, "
        f"{g('solver', 'micro_skipped_syncs'):g} skipped syncs)   "
        f"degraded {degraded:g}")
    # the solver failover pool (docs/reference/solver-pool.md): endpoint
    # health, breaker states, failovers. Absent without --solver-address.
    if "solver_pool" in p:
        sp_ = p["solver_pool"]
        n_ep = sp_.get("endpoints", 0)
        states = []
        if isinstance(n_ep, (int, float)):
            for i in range(int(n_ep)):
                st = sp_.get(f"ep{i}_state")
                states.append({0: "closed", 1: "half-open",
                               2: "open"}.get(st, "?"))
        lines.append(
            f"POOL      {n_ep:g} endpoints "
            f"({sp_.get('healthy', 0):g} healthy)   "
            f"delegated {sp_.get('delegated_solves', 0):g}   "
            f"failovers {sp_.get('failovers', 0):g}   "
            f"local {sp_.get('local_solves', 0):g}   "
            f"breakers " + (",".join(states) or "-"))
    # the operator-handoff surface (docs/reference/handoff.md): role +
    # fence token, replication stream progress, fenced-write rejections.
    # Absent until wire_handoff() attached an elector to the operator.
    if "handoff" in p:
        ho = p["handoff"]
        role = "leader" if ho.get("leader") else "standby"
        seg = [f"LEADER    {role} (fence {ho.get('fence', 0):g}, "
               f"{ho.get('transitions', 0):g} transitions)   "
               f"fenced writes {ho.get('fenced_rejections', 0):g}   "
               f"leases swept {ho.get('leases_swept', 0):g}"]
        if "replica_anchor" in ho:
            rebuilds = (ho.get("replica_stale_anchor_rebuilds", 0)
                        + ho.get("replica_version_mismatch_rebuilds", 0))
            seg.append(
                f"HANDOFF   anchor {ho.get('replica_anchor', -1):g}   "
                f"snapshots {ho.get('replica_snapshots', 0):g}   "
                f"deltas {ho.get('replica_deltas', 0):g} "
                f"({ho.get('replica_delta_pods', 0):g} pods)   "
                f"rebuilds {rebuilds:g}   "
                f"prebuilds {ho.get('replica_prebuilds', 0):g}")
        elif "source_deltas" in ho:
            seg.append(
                f"HANDOFF   serving   "
                f"snapshots {ho.get('source_snapshots', 0):g}   "
                f"deltas {ho.get('source_deltas', 0):g} "
                f"({ho.get('source_full_answers', 0):g} full answers)")
        lines.extend(seg)
    rh, rm = g("solver", "resident_hits"), g("solver", "resident_misses")
    hitpct = 100.0 * rh / (rh + rm) if (rh + rm) else 0.0
    ph = g("solver", "resident_problem_hits")
    pm = g("solver", "resident_problem_misses")
    lines.append(
        f"CACHES    resident {hitpct:.0f}% hit ({rh:g}/{rh + rm:g})   "
        f"problem {ph:g}/{ph + pm:g}   "
        f"ICE {g('ice_cache', 'live'):g}   "
        f"est-cache {g('solver', 'est_cache_entries'):g}")
    lines.append(
        f"BATCH     window {g('provisioner', 'batch_pending'):g} pods "
        f"({g('provisioner', 'batch_age_seconds'):g}s)   "
        f"cloud drains {g('cloud_batcher', 'launch_batches'):g} launch / "
        f"{g('cloud_batcher', 'terminate_batches'):g} terminate")
    writer = p.get("writer", {})
    # numeric values only: a provider that errored reports {"error": str}
    # and must drop its row's details, not crash the view
    top_verbs = sorted(((k, v) for k, v in writer.items()
                        if isinstance(v, (int, float))),
                       key=lambda kv: -kv[1])[:4]
    lines.append("WRITER    " + ("   ".join(f"{k} {v:g}"
                                            for k, v in top_verbs)
                                 or "(no writes yet)"))
    if "watch_hub" in p:
        # deepest-queue + drop readouts fold from the headroom registry's
        # reading of the same probe when the observatory is live (one
        # source of truth); the hub's own stats remain the fallback
        hrp = p.get("headroom", {})
        deepest = (hrp["api_watch_queues_depth"]
                   if isinstance(hrp.get("api_watch_queues_depth"),
                                 (int, float))
                   else g("watch_hub", "watch_deepest"))
        wdrops = (hrp["api_watch_queues_drops"]
                  if isinstance(hrp.get("api_watch_queues_drops"),
                                (int, float))
                  else g("watch_hub", "watch_drops"))
        lines.append(
            f"WATCHES   {g('watch_hub', 'watchers'):g} watchers   "
            f"queue {g('watch_hub', 'watch_queue_depth'):g} "
            f"(deepest {deepest:g}, "
            f"hw {g('watch_hub', 'watch_max_depth'):g})   "
            f"delivered {g('watch_hub', 'events_emitted'):g}   "
            f"bulk {g('watch_hub', 'bulk_ops'):g}   "
            f"drops {wdrops:g}")
    lines.append(
        f"EVENTS    {g('events', 'published'):g} published "
        f"({g('events', 'warnings'):g} warnings)")
    # the decision-audit ring (docs/reference/explain.md): last pass's
    # unschedulable count + the top cumulative reason codes
    ex = p.get("explain", {})
    if isinstance(ex.get("passes"), (int, float)):
        top_reasons = sorted(
            ((k[len("reason_"):].replace("_", "-"), v)
             for k, v in ex.items()
             if k.startswith("reason_") and isinstance(v, (int, float))),
            key=lambda kv: -kv[1])[:3]
        lines.append(
            f"EXPLAIN   passes {ex.get('passes', 0):g} "
            f"(ring {ex.get('ring', 0):g})   "
            f"last unschedulable {ex.get('last_unschedulable', 0):g}   "
            + ("reasons " + "  ".join(f"{k} {v:g}" for k, v in top_reasons)
               if top_reasons else "no unschedulable reasons recorded"))
    # the vmapped consolidation engine (docs/reference/consolidation.md):
    # batched dispatch/cache/fallback counters, accepted savings, and the
    # top skip codes ("why was this node NOT consolidated")
    co = p.get("consolidation", {})
    if isinstance(co.get("vmapped_whatifs"), (int, float)):
        top_skips = sorted(
            ((k[len("skip_"):].replace("_", "-"), v)
             for k, v in co.items()
             if k.startswith("skip_") and isinstance(v, (int, float))),
            key=lambda kv: -kv[1])[:3]
        lines.append(
            f"CONSOLIDATION dispatches {co.get('vmapped_whatifs', 0):g} "
            f"({co.get('batched_candidates', 0):g} sets)   "
            f"cached {co.get('fp_unchanged', 0):g}   "
            f"host {co.get('host_fallbacks', 0):g}   "
            f"accepted {co.get('accepted', 0):g} "
            f"({co.get('nodes_consolidated', 0):g} nodes, "
            f"${co.get('savings_per_hour', 0):.2f}/hr saved)   "
            f"referee {co.get('referee_rejects', 0):g}/"
            f"{co.get('referee_checks', 0):g} rejects"
            + ("   skips " + "  ".join(f"{k} {v:g}" for k, v in top_skips)
               if top_skips else ""))
    if "weather" in p:
        w = p["weather"]
        lines.append(
            f"WEATHER   {w.get('scenario', '?')} tick {w.get('ticks', 0):g}  "
            f" storms {w.get('storms_active', 0):g} active   "
            f"ICE {w.get('ice_pools', 0):g} pools   "
            f"spot x{w.get('spot_mult_mean', 1.0):.2f} "
            f"(max x{w.get('spot_mult_max', 1.0):.2f})   "
            f"msgs {w.get('messages_sent', 0):g} "
            f"({w.get('junk_sent', 0):g} junk)")
    if "interruption" in p:
        intr = p["interruption"]
        kinds = "   ".join(
            f"{k[len('received_'):].replace('_', '-')} {v:g}"
            for k, v in sorted(intr.items())
            if k.startswith("received_") and isinstance(v, (int, float)))
        lines.append(
            f"INTERRUPT queue {intr.get('queue_depth', 0):g}   "
            + (kinds or "(no messages)")
            + (f"   handler-errors {intr.get('handler_errors', 0):g}"
               if intr.get("handler_errors") else ""))
    # top-3 contended locks by wait p99 (the contention provider's
    # flattened `<lock>_wait_p99_ms` keys; introspect/contention.py)
    cont = p.get("contention", {})
    ranked = sorted(
        ((k[:-len("_wait_p99_ms")], v, cont.get(
            k[:-len("_wait_p99_ms")] + "_contended", 0))
         for k, v in cont.items()
         if k.endswith("_wait_p99_ms") and isinstance(v, (int, float))
         and v > 0),
        key=lambda t: -t[1])[:3]
    if cont:
        # LOCKORDER cell: the acquisition-order witness's edge/cycle
        # counts (introspect/contention.py; /debug/pprof/lockorder).
        # Numeric values only — a provider reporting the registry's
        # {"error"} shape drops the cell, not the view
        lo = p.get("lockorder", {})
        lo_cell = ""
        if isinstance(lo.get("edges"), (int, float)) \
                and isinstance(lo.get("cycles"), (int, float)):
            cyc = lo["cycles"]
            lo_cell = (f"   LOCKORDER {lo['edges']:g} edges / "
                       f"{cyc:g} cycles"
                       + (" !!DEADLOCK RISK" if cyc else ""))
        lines.append("CONTENTION " + ("   ".join(
            f"{name} p99 {_fmt_ms(p99)} ({int(n):d}x)"
            for name, p99, n in ranked) or "(no contended locks)")
            + lo_cell)
    # measured-vs-modeled device attribution (solver/costmodel.py)
    dev = p.get("device", {})
    if dev.get("last_compute_ms"):
        lines.append(
            f"DEVICE    compute {_fmt_ms(dev.get('last_compute_ms'))} "
            f"(model {_fmt_ms(dev.get('last_model_ms'))}, "
            f"{dev.get('last_vs_model', 0):.2f}x)   "
            f"shapes {dev.get('shapes', 0):g}   "
            f"hbm {dev.get('bytes_in_use', 0) / 2**20:.0f}MiB")
    prof = p.get("profiler", {})
    if prof.get("enabled"):
        lines.append(
            f"PROFILER  {prof.get('samples', 0):g} samples @ "
            f"{prof.get('hz', 0):g}Hz   "
            f"{prof.get('unique_stacks', 0):g} stacks   "
            f"overhead {prof.get('overhead_pct', 0):.1f}%")
    slo = p.get("slo", {})
    lines.append(
        f"SLO       latency burn {slo.get('latency_burn', 0):.2f} "
        f"(p50 {_fmt_ms(slo.get('latency_p50_ms'))} / "
        f"{slo.get('latency_budget_ms', 200):g}ms)   "
        f"cost burn {slo.get('cost_burn', 0):.2f} "
        f"(ratio {slo.get('cost_ratio_p50', 0):.4f})   "
        f"captures {p.get('burn_captures', {}).get('retained', 0):g}")
    # the saturation observatory (docs/reference/headroom.md): resource
    # count, the first-to-break forecast, and saturation-episode totals.
    # Numeric guard: an errored provider must drop the cell, not the view
    hrs = p.get("headroom", {})
    if isinstance(hrs.get("resources"), (int, float)):
        tte = hrs.get("min_tte_seconds", -1.0)
        first = hrs.get("first_to_break") or ""
        fcast = (f"first-to-break {first} in {tte:g}s"
                 if first and isinstance(tte, (int, float)) and tte >= 0
                 else "no exhaustion forecast")
        lines.append(
            f"HEADROOM  {hrs.get('resources', 0):g} resources   {fcast}   "
            f"saturated {hrs.get('saturated', 0):g}   "
            f"episodes {hrs.get('episodes', 0):g}   "
            f"probe-errors {hrs.get('probe_errors', 0):g}")
    fr = p.get("flight_recorder", {})
    if fr.get("enabled", True) is not False:
        lines.append(
            f"TRACES    started {fr.get('started', 0):g}   "
            f"retained {fr.get('retained', 0):g}")
    return lines


def cmd_top(c: Client, args) -> int:
    """Live terminal view of /debug/vars (docs/reference/introspection.md):
    nodes / pending pods / solver cadence / queue depths / cache hit
    rates, refreshed in place. ``--once`` prints a single frame (tests,
    scripting, piping)."""
    import time
    while True:
        # Ctrl-C can land mid-request just as easily as mid-sleep: the
        # whole iteration exits cleanly, never a traceback over the
        # cleared screen
        try:
            doc = c.request("GET", "/debug/vars")
            frame = "\n".join(_render_top(doc, c.server))
            if args.once:
                print(frame)
                return 0
            # clear + home, then one frame — flicker-free enough for a
            # status view without a curses dependency
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _load_folded(path) -> dict:
    """A collapsed-stack file → {folded_stack: count} (comment lines and
    blanks skipped)."""
    out = {}
    raw = sys.stdin.read() if path == "-" else open(path).read()
    for line in raw.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, count = line.rpartition(" ")
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def cmd_profile(c, args) -> int:
    """The sampling profiler's CLI (docs/reference/profiling.md):

        kpctl profile capture [-o FILE] [--format folded|chrome|json]
                                  snapshot the live profile — folded
                                  collapsed stacks (flamegraph.pl /
                                  speedscope input) or Chrome trace JSON
        kpctl profile top [-n N]  top frames by inclusive samples
        kpctl profile diff A B    frame-level delta of two folded files
                                  (before/after a fix; local, no server)
    """
    if args.action in ("capture", "top") and args.files:
        # stray positionals would be silently ignored — a user who
        # forgot `-o` must not get "exit 0, no file written"
        raise SystemExit(
            f"kpctl profile {args.action} takes no positional arguments "
            f"(got {args.files}); use -o FILE for capture output")
    if args.action == "capture":
        fmt = args.format
        path = ("/debug/pprof/profile" if fmt == "folded"
                else f"/debug/pprof/profile?format={fmt}")
        body = c.request("GET", path, raw=True)
        # the disabled marker differs by form: folded is a comment line,
        # chrome/json serve {"enabled": false} — both must exit 1, never
        # write a useless stub file
        disabled = body.startswith(b"# profiler disabled")
        if not disabled and fmt != "folded":
            try:
                doc = json.loads(body)
                disabled = (isinstance(doc, dict)
                            and doc.get("enabled") is False)
            except ValueError:
                pass
        if disabled:
            print("profiler is not running (start the control plane "
                  "with --profile)", file=sys.stderr)
            return 1
        if args.output_file:
            with open(args.output_file, "wb") as f:
                f.write(body)
            n = len(body.splitlines()) if fmt == "folded" else len(body)
            unit = "stacks" if fmt == "folded" else "bytes"
            print(f"wrote {n} {unit} to {args.output_file}")
        else:
            sys.stdout.write(body.decode())
        return 0
    if args.action == "top":
        doc = c.request("GET",
                        f"/debug/pprof/profile?format=json&n={args.n}")
        if not doc.get("enabled", True):
            print("profiler is not running (start the control plane "
                  "with --profile)", file=sys.stderr)
            return 1
        # % of all sampled THREAD-STACKS (a frame on every thread of an
        # N-thread process tops out at 100%, not N x 100%)
        total = max(doc.get("stack_samples", doc.get("samples", 0)), 1)
        rows = [["FRAME", "INCL", "SELF", "INCL%"]]
        for fr in doc.get("top", [])[: args.n]:
            rows.append([fr["frame"], str(fr["inclusive"]),
                         str(fr["self"]),
                         f"{100.0 * fr['inclusive'] / total:.1f}%"])
        print(f"profile: {doc.get('samples', 0)} samples @ "
              f"{doc.get('hz', 0):g}Hz, {doc.get('unique_stacks', 0)} "
              f"unique stacks, overhead {doc.get('overhead_pct', 0):.2f}%")
        _print_rows(rows)
        return 0
    if args.action == "diff":
        if len(args.files) != 2:
            raise SystemExit("kpctl profile diff needs exactly two "
                             "folded files (before after)")
        a, b = (_load_folded(p) for p in args.files)
        # per-frame inclusive deltas (a frame's count = sum of stacks
        # containing it, deduped per stack like the server's top())
        def incl(folded):
            out = {}
            for stack, n in folded.items():
                for fr in set(stack.split(";")[1:]):
                    out[fr] = out.get(fr, 0) + n
            return out
        ia, ib = incl(a), incl(b)
        deltas = sorted(((ib.get(f, 0) - ia.get(f, 0), f)
                         for f in set(ia) | set(ib)),
                        key=lambda t: -abs(t[0]))
        rows = [["DELTA", "BEFORE", "AFTER", "FRAME"]]
        for d, f in deltas[: args.n]:
            if d == 0:
                continue
            rows.append([f"{d:+d}", str(ia.get(f, 0)), str(ib.get(f, 0)), f])
        if len(rows) == 1:
            print("no frame-level differences")
            return 0
        _print_rows(rows)
        return 0
    raise SystemExit(f"unknown profile action {args.action!r}")


def cmd_soak(c, args) -> int:
    """Summarize a soak/monitor time-series artifact — a LOCAL file, no
    server needed. Reads both plain ``.json`` and gzipped ``.json.gz``
    forms (debug.load_timeseries sniffs the magic, not the suffix)."""
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from karpenter_provider_aws_tpu.debug import load_timeseries
    doc = load_timeseries(args.path)
    summ = doc.get("summary", {})
    samples = doc.get("samples", [])
    print(f"soak artifact {args.path}")
    print(f"  samples {len(samples)}   wall "
          f"{summ.get('wall_seconds', 0):g}s")
    print(f"  peak nodes {summ.get('peak_nodes', 0):g}   "
          f"peak pending {summ.get('peak_pending_pods', 0):g}   "
          f"peak cost/hr {summ.get('peak_cost_per_hour', 0):g}")
    if "peak_latency_burn" in summ:
        print(f"  peak latency burn {summ['peak_latency_burn']:g}   "
              f"peak cost burn {summ.get('peak_cost_burn', 0):g}")
    if "peak_lock_wait_ms" in summ:
        # the contention provider's series envelope (debug.Monitor):
        # the worst lock wait the run ever saw, next to the burn peaks
        print(f"  peak lock wait {summ['peak_lock_wait_ms']:g}ms "
              f"({summ.get('peak_lock_wait_lock', '?')})")
    caps = (summ.get("final", {}).get("subsystems", {})
            .get("burn_captures", {}))
    if caps.get("total"):
        print(f"  burn captures {caps.get('total', 0):g} "
              f"(retained {caps.get('retained', 0):g}, "
              f"last {caps.get('last_reason', '?')})")
    final = summ.get("final", {})
    slo = final.get("subsystems", {}).get("slo", {})
    if slo:
        print(f"  final latency burn {slo.get('latency_burn', 0):g} "
              f"(p50 {slo.get('latency_p50_ms', 0):g}ms)   "
              f"warmup dropped {slo.get('warmup_dropped', 0):g}")
    solver = final.get("subsystems", {}).get("solver", {})
    if solver:
        print(f"  final delta solves {solver.get('delta_solves', 0):g}   "
              f"resident-problem hits "
              f"{solver.get('resident_problem_hits', 0):g}")
    return 0


def cmd_lockorder(c: Client, args) -> int:
    """Dump the lock-order witness (docs/reference/linting.md): the
    acquisition-order graph InstrumentedLock records, every edge's
    hold count, and any cycles — each cycle printed with ALL member
    edges' witness stacks (the code paths that can deadlock)."""
    doc = c.request("GET", "/debug/pprof/lockorder")
    if not isinstance(doc, dict) or "edges" not in doc:
        # tolerate the registry's {"error"} provider shape (and any
        # other malformed body) like the WRITER-row fix
        print(f"lockorder: unavailable ({doc.get('error', 'bad response')})"
              if isinstance(doc, dict) else "lockorder: bad response")
        return 1
    edges = doc.get("edges", {})
    cycles = doc.get("cycles", [])
    print(f"lockorder: {len(edges)} edges, {len(cycles)} cycles"
          f"{'' if doc.get('enabled', True) else '   (accounting DISABLED)'}")
    for name in sorted(edges):
        e = edges[name]
        count = e.get("count", 0) if isinstance(e, dict) else 0
        print(f"  {name}   ({count:g}x)")
        if args.stacks and isinstance(e, dict):
            for fr in e.get("stack", []):
                print(f"      {fr}")
    for cyc in cycles:
        locks = cyc.get("locks", []) if isinstance(cyc, dict) else []
        print(f"CYCLE (potential deadlock): {' -> '.join(locks)} -> "
              f"{locks[0] if locks else '?'}")
        for m in (cyc.get("edges", []) if isinstance(cyc, dict) else []):
            print(f"  witness {m.get('edge')}   ({m.get('count', 0):g}x)")
            for fr in m.get("stack", []):
                print(f"      {fr}")
    return 1 if cycles else 0


def _fmt_tte(tte) -> str:
    """Seconds-to-exhaustion cell: None = nothing forecast to break."""
    if not isinstance(tte, (int, float)):
        return "-"
    if tte >= 3600:
        return f"{tte / 3600:.1f}h"
    if tte >= 60:
        return f"{tte / 60:.1f}m"
    return f"{tte:.1f}s"


def _render_headroom(doc: dict) -> int:
    """The ranked first-to-break table from one /debug/headroom doc."""
    if not isinstance(doc, dict) or doc.get("enabled") is False \
            or "resources" not in doc:
        # tolerate the provider-less shape (operator still constructing)
        # and the registry's {"error"} shape like the lockorder command
        msg = (doc.get("message") or doc.get("error") or "bad response") \
            if isinstance(doc, dict) else "bad response"
        print(f"headroom: unavailable ({msg})")
        return 1
    rows = [["RESOURCE", "KIND", "DEPTH", "CAP", "OCC%", "HIGHWATER",
             "DROPS", "FILL/s", "EXHAUSTION"]]
    for r in doc["resources"]:
        if r.get("error"):
            rows.append([r.get("resource", "?"), "error", "-", "-", "-",
                         "-", "-", "-", str(r["error"])[:40]])
            continue
        cap = r.get("capacity", 0)
        rows.append([
            r.get("resource", "?"), r.get("kind", "queue"),
            f"{r.get('depth', 0):g}",
            f"{cap:g}" if cap else "inf",
            f"{100 * r.get('occupancy', 0):.0f}" if cap else "-",
            f"{r.get('highwater', 0):g}",
            f"{r.get('drops', 0):g}",
            f"{r.get('fill_rate', 0):.3g}",
            _fmt_tte(r.get("seconds_to_exhaustion")),
        ])
    print(f"headroom: {len(doc['resources'])} resources   "
          f"high-water fraction {doc.get('high_water_fraction', 0.9):g}   "
          f"probe errors {doc.get('probe_errors', 0):g}")
    _print_rows(rows)
    return 0


def cmd_headroom(c: Client, args) -> int:
    """The saturation observatory (docs/reference/headroom.md): every
    registered bounded resource's occupancy, monotonic high water,
    drop count, EWMA fill rate, and time-to-exhaustion forecast,
    ranked first-to-break. ``--watch`` refreshes in place."""
    import time
    while True:
        try:
            doc = c.request("GET", "/debug/headroom")
            if not args.watch:
                return _render_headroom(doc)
            sys.stdout.write("\x1b[2J\x1b[H")
            _render_headroom(doc)
            sys.stdout.flush()
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _render_waterfall(g: dict, indent: str = "  ") -> None:
    """One group's elimination waterfall (the /debug/explain group doc):
    stage rows down to 'eliminated by ice: N offerings (...)'."""
    print(f"{indent}Group:   {g.get('label', '?')}   "
          f"({g.get('pods', 0)} pods, {g.get('poolsOk', 0)}/"
          f"{g.get('poolsTotal', 0)} nodepools compatible)")
    for n in g.get("notes", []):
        print(f"{indent}Note:    {n}")
    rows = [["STAGE", "REMAINING", "ELIMINATED", ""]]
    for s in g.get("stages", []):
        ex = s.get("examples") or []
        detail = ""
        if s.get("eliminated"):
            detail = (f"eliminated by {s['stage']}: "
                      f"{s['eliminated']} offerings")
            if ex:
                detail += f" (e.g. {', '.join(ex)})"
        rows.append([s["stage"], str(s["remaining"]),
                     str(s.get("eliminated", 0)) if s["stage"] != "offered"
                     else "-", detail])
    _print_rows(rows, indent=indent)


def _render_rationale(r: dict, indent: str = "  ") -> None:
    line = (f"{r.get('instanceType', '?')}/{r.get('zone', '?')}/"
            f"{r.get('capacityType', '?')} at "
            f"${r.get('pricePerHour', 0):g}/hr for {r.get('pods', 0)} "
            f"pod(s), {r.get('flexibleTypes', 0)} flexible types")
    print(f"{indent}Chosen:    {line}")
    if "runnerUpType" in r:
        print(f"{indent}Runner-up: {r['runnerUpType']} at "
              f"${r.get('runnerUpPricePerHour', 0):g}/hr "
              f"({r.get('runnerUpPriceDelta', 0):+g}/hr)")


def cmd_explain(c: Client, args) -> int:
    """The decision-explainability surface (docs/reference/explain.md):

        kpctl explain pod NAME        why is this pod pending — the
                                      per-stage elimination waterfall
                                      (or where it was placed, and why)
        kpctl explain nodeclaim NAME  the claim's placement rationale
                                      (chosen offering, runner-up,
                                      price delta)
        kpctl explain node NAME       why was this node NOT consolidated
                                      — the engine's latest coded skip
                                      (solver/taxonomy.py) for the node
        kpctl explain pass [ID]       one pass's full decision audit
                                      (default: the newest pass)
    """
    if args.what in ("pod", "nodeclaim", "node") and not args.name:
        raise SystemExit(f"kpctl explain {args.what} needs a name")
    if args.what == "pod":
        doc = c.request("GET", f"/debug/explain?pod={args.name}")
        if doc.get("found") is False or doc.get("enabled") is False:
            print(doc.get("message", f"pod {args.name!r} not found in "
                                     "the decision-audit ring"))
            return 1
        print(f"Pod:     {doc.get('pod')}   (pass {doc.get('pass', '?')}"
              + (f", trace {doc['traceId']}" if doc.get("traceId") else "")
              + ")")
        if doc.get("outcome") == "scheduled":
            print(f"Outcome: scheduled -> {doc.get('node', '?')}")
            if doc.get("rationale"):
                _render_rationale(doc["rationale"])
            return 0
        print(f"Outcome: {doc.get('outcome')}")
        print(f"Reason:  {doc.get('reason', '')}")
        if doc.get("group"):
            _render_waterfall(doc["group"])
        return 0
    if args.what == "nodeclaim":
        doc = c.request("GET", f"/debug/explain?nodeclaim={args.name}")
        if doc.get("found") is False or doc.get("enabled") is False:
            print(doc.get("message", f"nodeclaim {args.name!r} not found "
                                     "in the decision-audit ring"))
            return 1
        print(f"NodeClaim: {doc.get('nodeclaim')}   "
              f"(pass {doc.get('pass', '?')}"
              + (f", trace {doc['traceId']}" if doc.get("traceId") else "")
              + ")")
        _render_rationale(doc.get("rationale", {}))
        return 0
    if args.what == "node":
        doc = c.request("GET", f"/debug/explain?node={args.name}")
        if doc.get("found") is False or doc.get("enabled") is False:
            print(doc.get("message", f"node {args.name!r} has no recorded "
                                     "skip decision"))
            return 1
        print(f"Node:    {doc.get('node')}")
        print(f"Skip:    {doc.get('code', '?')}"
              + (f"   (x{doc['count']:g} this episode)"
                 if doc.get("count", 0) > 1 else ""))
        if doc.get("detail"):
            print(f"Detail:  {doc['detail']}")
        print(f"At:      t={doc.get('t', 0)}s")
        return 0
    # pass
    q = f"?pass={args.name}" if args.name else ""
    doc = c.request("GET", f"/debug/explain{q}")
    if not args.name:
        passes = doc.get("passes", [])
        if not passes:
            print("No passes recorded in the decision-audit ring.")
            return 1
        doc = c.request("GET", f"/debug/explain?pass={passes[-1]['pass']}")
    if doc.get("found") is False or doc.get("enabled") is False:
        print(f"pass {args.name!r} not in the decision-audit ring")
        return 1
    print(f"Pass:          {doc.get('pass')}"
          + (f"   trace {doc['traceId']}" if doc.get("traceId") else ""))
    print(f"Pods:          {doc.get('pods', 0)}   "
          f"groups {doc.get('groups', 0)}   "
          f"unschedulable {doc.get('unschedulable', 0)}   "
          f"placements {doc.get('placements', 0)}")
    if doc.get("degradedReason"):
        print(f"Degraded:      {doc['degradedReason']}")
    if doc.get("note"):
        print(f"Note:          {doc['note']}")
    reasons = doc.get("reasons", {})
    if reasons:
        print("Reasons:       " + "   ".join(
            f"{k} {v}" for k, v in sorted(reasons.items())))
    elim = doc.get("eliminations", {})
    if elim:
        print("Eliminations:  " + "   ".join(
            f"{k} {v}" for k, v in sorted(elim.items())))
    shown = 0
    for g in doc.get("groupDetails", []):
        if g.get("unplaced") or g.get("dropped") or shown < 3:
            print(f"-- {'UNPLACED ' if g.get('unplaced') else ''}"
                  f"{'(dropped at build) ' if g.get('dropped') else ''}"
                  f"code={g.get('code', '') or '-'} "
                  f"placed={g.get('placed', 0)} "
                  f"unplaced={g.get('unplaced', 0)}")
            _render_waterfall(g)
            shown += 1
    claims = doc.get("claims", {})
    for name, r in sorted(claims.items()):
        print(f"-- NodeClaim {name}")
        _render_rationale(r)
    return 0


def cmd_evict(c: Client, args) -> int:
    force = "?force=1" if args.force else ""
    try:
        c.request("POST", f"/apis/pods/{args.name}/eviction{force}")
    except urllib.error.HTTPError as e:
        if e.code == 429:
            print(f"pod/{args.name} eviction blocked by a "
                  "PodDisruptionBudget", file=sys.stderr)
            return 1
        raise
    print(f"pod/{args.name} evicted")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kpctl", description=__doc__)
    p.add_argument("--server", default=os.environ.get("KPCTL_SERVER"),
                   help="API base URL, e.g. https://127.0.0.1:8443 "
                        "(env KPCTL_SERVER)")
    p.add_argument("--token", default=os.environ.get("KPCTL_TOKEN"))
    p.add_argument("--token-file", default=None)
    p.add_argument("--cacert", default=None,
                   help="PEM bundle to trust (deploy/certs/tls.crt)")
    p.add_argument("--insecure-skip-tls-verify", action="store_true")
    sub = p.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output",
                   choices=("table", "wide", "json", "yaml"),
                   default="table")
    g.set_defaults(fn=cmd_get)

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True,
                   help="YAML/JSON file of {kind, spec} documents "
                        "('-' = stdin)")
    a.set_defaults(fn=cmd_apply)

    d = sub.add_parser("delete")
    d.add_argument("kind")
    d.add_argument("name")
    d.add_argument("--force", action="store_true")
    d.set_defaults(fn=cmd_delete)

    w = sub.add_parser("watch")
    w.add_argument("kind")
    w.add_argument("--resource-version", type=int, default=None)
    w.add_argument("--once", action="store_true",
                   help="exit after the first event (scripting)")
    w.set_defaults(fn=cmd_watch)

    e = sub.add_parser("evict")
    e.add_argument("name")
    e.add_argument("--force", action="store_true")
    e.set_defaults(fn=cmd_evict)

    ds = sub.add_parser("describe")
    ds.add_argument("kind")
    ds.add_argument("name")
    ds.set_defaults(fn=cmd_describe)

    ar = sub.add_parser("api-resources")
    ar.set_defaults(fn=cmd_api_resources)

    tp = sub.add_parser(
        "top", help="live subsystem view against /debug/vars "
                    "(docs/reference/introspection.md)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit (scripting/tests)")
    tp.set_defaults(fn=cmd_top)

    tr = sub.add_parser(
        "trace", help="flight-recorder traces (requires --trace on the "
                      "control plane; docs/reference/tracing.md)")
    tr.add_argument("action", nargs="?", default="list",
                    choices=("list", "show", "export"))
    tr.add_argument("id", nargs="?", default=None,
                    help="trace id (show/export)")
    tr.add_argument("-o", "--output-file", default=None,
                    help="export: write Chrome trace-event JSON here "
                         "(default stdout)")
    tr.set_defaults(fn=cmd_trace)

    lo = sub.add_parser(
        "lockorder", help="dump the lock acquisition-order witness graph "
                          "(/debug/pprof/lockorder; docs/reference/"
                          "linting.md) — edges, cycles, witness stacks")
    lo.add_argument("--stacks", action="store_true",
                    help="also print each edge's first-witness stack "
                         "(cycle edges always print theirs)")
    lo.set_defaults(fn=cmd_lockorder)

    hrp = sub.add_parser(
        "headroom", help="ranked first-to-break table of every bounded "
                         "resource (/debug/headroom; docs/reference/"
                         "headroom.md) — occupancy, fill rate, "
                         "time-to-exhaustion forecast")
    hrp.add_argument("--watch", action="store_true",
                     help="refresh the table in place until Ctrl-C")
    hrp.add_argument("--interval", type=float, default=2.0,
                     help="watch refresh period in seconds (default 2)")
    hrp.set_defaults(fn=cmd_headroom)

    exp = sub.add_parser(
        "explain", help="why was this decision made — per-pod elimination "
                        "waterfall, claim placement rationale, pass audit "
                        "(/debug/explain; docs/reference/explain.md)")
    exp.add_argument("what", choices=("pod", "nodeclaim", "node", "pass"))
    exp.add_argument("name", nargs="?", default=None,
                     help="pod/nodeclaim name, or pass id (default: "
                          "newest pass)")
    exp.set_defaults(fn=cmd_explain)

    sk = sub.add_parser(
        "soak", help="summarize a soak time-series artifact (local file, "
                     ".json or .json.gz — no server needed)")
    sk.add_argument("path")
    sk.set_defaults(fn=cmd_soak, local=True)

    pf = sub.add_parser(
        "profile", help="sampling-profiler surface (requires --profile on "
                        "the control plane; docs/reference/profiling.md)")
    pf.add_argument("action", choices=("capture", "top", "diff"))
    pf.add_argument("files", nargs="*", default=[],
                    help="diff: two folded files (before after)")
    pf.add_argument("-o", "--output-file", default=None,
                    help="capture: write here instead of stdout")
    pf.add_argument("--format", choices=("folded", "chrome", "json"),
                    default="folded",
                    help="capture format: folded collapsed stacks "
                         "(flamegraph.pl/speedscope), Chrome trace JSON "
                         "(Perfetto), or the top-frames JSON")
    pf.add_argument("-n", type=int, default=25,
                    help="top/diff: rows to show")
    pf.set_defaults(fn=cmd_profile)

    args = p.parse_args(argv)
    if getattr(args, "verb", "") == "profile" and args.action == "diff":
        args.local = True   # diff compares two local files, no server
    c = None
    if not getattr(args, "local", False):
        if not args.server:
            raise SystemExit("--server (or KPCTL_SERVER) is required")
        token = args.token
        if args.token_file:
            token = open(args.token_file).read().strip()
        c = Client(args.server, token=token, cacert=args.cacert,
                   insecure=args.insecure_skip_tls_verify)
    try:
        rc = args.fn(c, args)
        # flush INSIDE the try: for outputs under the pipe buffer the
        # EPIPE only fires at flush time, and an interpreter-shutdown
        # flush would bypass the handler below ("Exception ignored"
        # noise, exit 120)
        sys.stdout.flush()
        return rc
    except urllib.error.HTTPError as err:
        try:
            doc = json.loads(err.read())
            msg = doc.get("message", "")
        except Exception:
            msg = ""
        print(f"error: {err.code} {msg}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # downstream closed early (`kpctl get -o json | head`): exit
        # quietly like kubectl, with the conventional 128+SIGPIPE code.
        # stdout is already broken — devnull it so interpreter shutdown
        # doesn't print a second traceback flushing the dead buffer
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 141


if __name__ == "__main__":
    raise SystemExit(main())
