#!/usr/bin/env python
"""Headroom-registry overhead bench (docs/reference/headroom.md).

Runs the SAME operator churn loop twice — once with the saturation
observatory's full probe set wired (the production default: every
bounded queue/ring registered, observed and rendered into the
karpenter_headroom_* families on each gauge pass) and once with every
probe unregistered — and records the end-to-end per-pass p50 delta.
The timed window is provision_once + emit_gauges, because the gauge
pass is where the registry actually runs (Operator.emit_gauges calls
observe() and re-renders the six families). Acceptance bar: < 1% e2e
p50 regression, the same bound every observability layer before it
carried (PROF_r08, EXPLAIN_r11).

    python tools/bench_headroom.py [--pods 4000] [--passes 30] \
           [--out HEADROOM_r20_overhead.json]

Both runs share one process and warm JAX compile caches; the measured
window starts AFTER a warmup pass, and the probes-ON run goes FIRST so
any residual warm-up cost lands on the observatory's side (overhead
reads as an upper bound, the PROF_r08 discipline).
"""

from __future__ import annotations

import argparse
import gc
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_loop(probes: bool, n_pods: int, n_passes: int) -> dict:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    clock = FakeClock()
    op = Operator(options=Options(registration_delay=0.5),
                  lattice=build_lattice(), cloud=FakeCloud(clock),
                  clock=clock)
    n_probes = len(op.headroom.names())
    if not probes:
        # the OFF side: an empty registry — observe()/table() sweep
        # nothing, the gauge families render zero rows
        for name in list(op.headroom.names()):
            op.headroom.unregister_probe(name)
    serial = 0
    for _ in range(n_pods):
        serial += 1
        op.cluster.add_pod(Pod(name=f"b{serial}",
                               requests={"cpu": "250m", "memory": "512Mi"}))
    # warmup: the first pass pays compile + cold caches on both sides
    op.provisioner.provision_once()
    op.emit_gauges()
    clock.step(1.0)
    times = []
    for _ in range(n_passes):
        # ~1% churn per pass: the steady-state shape a gauge-cadence
        # probe sweep actually rides in production
        for _ in range(max(n_pods // 100, 1)):
            serial += 1
            op.cluster.add_pod(Pod(name=f"b{serial}",
                                   requests={"cpu": "250m",
                                             "memory": "512Mi"}))
        gc.collect()
        t0 = time.perf_counter()
        op.provisioner.provision_once()
        op.emit_gauges()
        times.append(time.perf_counter() - t0)
        clock.step(1.0)
    times.sort()
    return {
        "probes": n_probes if probes else 0,
        "passes": n_passes,
        "e2e_p50_ms": round(times[len(times) // 2] * 1000.0, 3),
        "e2e_p90_ms": round(times[int(len(times) * 0.9)] * 1000.0, 3),
        "resources": len(op.headroom.table()),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=4000)
    ap.add_argument("--passes", type=int, default=30)
    ap.add_argument("--out", default="HEADROOM_r20_overhead.json")
    args = ap.parse_args()

    on = run_loop(True, args.pods, args.passes)
    off = run_loop(False, args.pods, args.passes)
    delta_pct = (100.0 * (on["e2e_p50_ms"] - off["e2e_p50_ms"])
                 / max(off["e2e_p50_ms"], 1e-9))
    doc = {
        "bench": "headroom_registry_overhead",
        "pods": args.pods,
        "probes_on": on, "probes_off": off,
        "e2e_p50_delta_pct": round(delta_pct, 3),
        "bound_pct": 1.0,
        "within_bound": delta_pct < 1.0,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"headroom overhead: on={on['e2e_p50_ms']}ms "
          f"({on['probes']} probes) off={off['e2e_p50_ms']}ms "
          f"delta={delta_pct:+.2f}% (bound <1%) -> {args.out}")
    return 0 if doc["within_bound"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
