#!/usr/bin/env python
"""CI smoke for the saturation observatory (ci.sh headroom gate).

Boots an API-mode Operator with a deliberately TINY watch queue bound,
parks an idle watcher on the pods feed, and churns writes so the idle
queue fills — then asserts the observatory tells the future, not just
the past (docs/reference/headroom.md):

1. BEFORE the first overflow, ``/debug/headroom`` over LIVE HTTP ranks
   ``api_watch_queues`` first-to-break with a finite time-to-exhaustion
   and zero drops — the forecaster names the tightened resource while
   the run is still green,
2. crossing the high-water fraction fires the burn-capture machinery
   EXACTLY ONCE for the episode (reason ``headroom-api_watch_queues``
   at ``/debug/pprof/captures``), no capture storm while the queue sits
   pinned at its bound,
3. after the overflow, the same probe reports the drops (reusing the
   apiserver's own ``watch_drops`` counter) and the monotonic high
   water holds at the bound,
4. ``kpctl headroom`` renders the ranked table against the live server
   (exit 0), and degrades to ``headroom: unavailable`` (exit 1, no
   traceback) when no registry is published — the error-shape contract
   every kpctl surface follows.

Fast by design: small-family lattice, FakeClock, a few hundred writes.
"""

from __future__ import annotations

import contextlib
import io
import json
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BOUND = 64


def main() -> int:
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.kube import FakeAPIServer
    from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                    build_lattice)
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    failures = []
    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    api = FakeAPIServer()
    op = Operator(options=Options(registration_delay=0.5,
                                  api_watch_queue_bound=BOUND),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                  api_server=api)

    # the deliberately idle watcher: subscribed, never drained — the
    # tightened bound is ITS queue
    idle = api.watch("pods")

    server = start_server(op, 0)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"

    def fetch(path):
        return json.loads(urllib.request.urlopen(base + path,
                                                 timeout=10).read())

    def churn_round(serial):
        for i in range(4):
            api.create("pods", {"name": f"churn-{serial}-{i}"})
        op.emit_gauges()        # observe() rides every gauge pass
        clock.step(1.0)

    try:
        serial = 0
        # ---- phase 1: fill to ~half the bound, NO overflow yet --------
        while len(idle._events) < BOUND // 2:
            churn_round(serial)
            serial += 1
        if api.watch_drops != 0:
            failures.append("premise broke: overflow before the forecast "
                            "assertion")
        doc = fetch("/debug/headroom")
        rows = doc.get("resources") or []
        first = rows[0] if rows else {}
        if first.get("resource") != "api_watch_queues":
            failures.append(
                "forecaster did not rank the tightened watch queue "
                f"first-to-break BEFORE its overflow: "
                f"{[r['resource'] for r in rows[:3]]}")
        if first.get("seconds_to_exhaustion") is None:
            failures.append("first-to-break row carries no finite "
                            "time-to-exhaustion while filling")
        if first.get("drops", 0) != 0:
            failures.append("the prediction-before-overflow gate saw "
                            f"drops={first.get('drops')} — too late")
        # ---- phase 2: drive through high water into overflow ----------
        while api.watch_drops == 0:
            churn_round(serial)
            serial += 1
            if serial > 200:
                failures.append("watch queue never overflowed — churn "
                                "premise broke")
                break
        op.emit_gauges()
        caps = fetch("/debug/pprof/captures").get("captures", [])
        hw_caps = [c for c in caps
                   if c.get("reason") == "headroom-api_watch_queues"]
        if len(hw_caps) != 1:
            failures.append(f"expected EXACTLY one high-water capture for "
                            f"the episode, got {len(hw_caps)} "
                            f"(reasons: {[c.get('reason') for c in caps]})")
        elif hw_caps[0].get("occupancy", 0.0) < 0.9:
            failures.append(f"capture fired below the high-water fraction: "
                            f"{hw_caps[0].get('occupancy')}")
        row = next((r for r in fetch("/debug/headroom")["resources"]
                    if r["resource"] == "api_watch_queues"), {})
        if row.get("drops", 0) <= 0:
            failures.append("after overflow the probe does not report the "
                            "apiserver's watch_drops counter")
        if row.get("highwater", 0) < BOUND:
            failures.append(f"monotonic high water below the bound after "
                            f"overflow: {row.get('highwater')}")
        st = fetch("/debug/vars").get("providers", {}).get("headroom", {})
        if st.get("episodes", 0) != 1:
            failures.append(f"headroom provider episodes != 1: "
                            f"{st.get('episodes')}")

        # ---- kpctl headroom against the live server --------------------
        sys.path.insert(0, str(Path(__file__).resolve().parent))
        import kpctl
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = kpctl.main(["--server", base, "headroom"])
        rendered = out.getvalue()
        if rc != 0:
            failures.append(f"kpctl headroom exited {rc}")
        if "api_watch_queues" not in rendered:
            failures.append("kpctl headroom did not render the watch "
                            f"queue row:\n{rendered}")
        # error-shape safety: no registry published -> graceful message
        saved = introspect.headroom_registry()
        try:
            introspect.set_headroom(None)
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = kpctl.main(["--server", base, "headroom"])
            if rc == 0 or "headroom: unavailable" not in out.getvalue():
                failures.append("kpctl headroom did not degrade to the "
                                "unavailable message without a registry: "
                                f"rc={rc} out={out.getvalue()!r}")
        finally:
            introspect.set_headroom(saved)
    finally:
        server.shutdown()
        api.stop_watch(idle)

    if failures:
        print("headroom smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"headroom smoke: OK (api_watch_queues ranked first-to-break "
          f"{first['seconds_to_exhaustion']:.0f}s out with 0 drops, then "
          f"overflowed to drops={row['drops']:g} hw={row['highwater']:g}; "
          f"1 capture for the episode; kpctl headroom renders + degrades)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
