#!/usr/bin/env python
"""CI smoke for the steady-state delta-solve path (ci.sh churn gate).

Boots a real Operator (direct mode, FakeClock), drives one full
provisioning pass, then 20 small-churn reconcile passes — a few pods
arrive and a few bind away each pass, the exact steady-state shape the
incremental builder + delta solve exist for — and asserts:

1. the delta path ENGAGED: ``karpenter_solver_delta_solves_total`` /
   ``Solver.pipeline_stats["delta_solves"]`` moved past zero, and the
   builder took the incremental path for churn passes (a delta gate
   silently failing open to full rebuilds would otherwise read as a
   vacuous green),
2. parity: on sampled churn passes the provisioner's plan matches a
   from-scratch ``build_problem`` + ``solve`` referee of the SAME
   cluster inputs — identical new-node multiset and cost,
3. the cluster converges (every churned pod scheduled or bound).

Fast by design: small-family lattice, ~120 pods — seconds, not a soak.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.solver import build_problem
    from karpenter_provider_aws_tpu.utils.clock import FakeClock
    import random

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                  cloud=FakeCloud(clock), clock=clock)
    rng = random.Random(7)
    shapes = [{"cpu": "250m", "memory": "512Mi"},
              {"cpu": "500m", "memory": "1Gi"},
              {"cpu": "1", "memory": "2Gi"}]
    failures = []

    # full pass: a 60-pod wave, settle to capacity
    for i in range(60):
        op.cluster.add_pod(Pod(name=f"seed-{i}",
                               requests=shapes[i % len(shapes)]))
    op.settle(max_rounds=30)
    if op.cluster.pending_pods():
        failures.append(f"seed wave did not settle: "
                        f"{len(op.cluster.pending_pods())} pending")

    serial = 0
    parity_checked = 0
    for pass_i in range(20):
        # small churn: 2-4 new pods arrive; 1-2 bound pods leave
        for _ in range(rng.randint(2, 4)):
            serial += 1
            op.cluster.add_pod(Pod(name=f"churn-{serial}",
                                   requests=shapes[serial % len(shapes)]))
        bound = [p.name for p in op.cluster.snapshot_pods()
                 if p.node_name is not None]
        for name in rng.sample(bound, min(len(bound), rng.randint(1, 2))):
            op.cluster.delete_pod(name)

        referee_inputs = None
        if pass_i % 5 == 4:
            # capture the referee problem BEFORE the pass mutates state
            pending = op.cluster.pending_pods()
            referee_inputs = build_problem(
                pending, list(op.node_pools.values()), op.solver.lattice,
                existing=op.cluster.existing_bins(op.solver.lattice),
                daemonset_pods=op.cluster.daemonset_pods(),
                bound_pods=op.cluster.bound_pods())
        result = op.provisioner.provision_once()
        if referee_inputs is not None and result.plan is not None:
            ref = op.solver.solve(referee_inputs)
            plan = result.plan
            got = sorted((n.instance_type, n.zone, len(n.pods))
                         for n in plan.new_nodes)
            want = sorted((n.instance_type, n.zone, len(n.pods))
                          for n in ref.new_nodes)
            if got != want:
                failures.append(
                    f"pass {pass_i}: plan diverged from full-rebuild "
                    f"referee ({got} vs {want})")
            if abs(plan.new_node_cost - ref.new_node_cost) > 1e-6:
                failures.append(
                    f"pass {pass_i}: cost {plan.new_node_cost} != "
                    f"referee {ref.new_node_cost}")
            parity_checked += 1
        # let launches register so later passes see the new capacity
        op.settle(max_rounds=10)

    deltas = op.solver.pipeline_stats.get("delta_solves", 0)
    inc = op.provisioner.inc_builder.incremental_builds
    if deltas == 0:
        failures.append("delta-solve path never engaged (delta_solves=0) — "
                        f"last gate reason: "
                        f"{op.provisioner.inc_builder.last_reason!r}")
    if inc == 0:
        failures.append("incremental builder never took the delta path")
    if parity_checked == 0:
        failures.append("no parity pass executed (harness bug)")
    if failures:
        print("delta smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"delta smoke: OK (delta_solves={deltas}, "
          f"incremental_builds={inc}, "
          f"parity passes={parity_checked}, "
          f"resident_problem_hits="
          f"{op.solver.pipeline_stats['resident_problem_hits']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
