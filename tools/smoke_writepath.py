#!/usr/bin/env python
"""CI smoke for the API-stratum write path (ci.sh writepath gate).

What this gate asserts (docs/reference/watch.md):

1. an API-mode operator boots and a churn burst drives writes through
   ``ApiWriter`` — and the COALESCED path actually engaged: the
   apiserver's bulk counters and the writer's ``bulk_binds`` count moved
   past zero (a batching seam silently falling back to per-pod verbs
   would otherwise read as a vacuous green),
2. zero per-watcher envelope copies were made delivering the burst's
   watch events (``fanout_envelope_copies`` — the shared-frozen-event
   design's pin),
3. the watch-fed mirror CONVERGES to the server's truth after the burst
   (same pod set, same bound assignments — snapshot-free delivery must
   not lose or corrupt events),
4. the live ``/metrics`` scrape carries the new ``karpenter_api_*``
   write/fan-out series with sane values and lints clean
   (metrics.lint_exposition).

Fast by design: small-family lattice, a few hundred pods, seconds.
"""

from __future__ import annotations

import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.metrics import lint_exposition
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    api_server = FakeAPIServer()
    client = KubeClient(api_server)
    op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                  cloud=FakeCloud(clock), clock=clock, api_server=api_server)
    failures = []

    # churn burst through the protocol: a seed wave to build capacity,
    # then a second wave that lands on EXISTING nodes — the provisioning
    # pass's existing-capacity binds are the coalesced-write hot path
    errs = client.create_pods([
        Pod(name=f"seed-{i}", requests={"cpu": "250m", "memory": "256Mi"})
        for i in range(120)])
    if any(errs):
        failures.append(f"bulk seed creates failed: {errs}")
    op.settle(max_rounds=30)
    client.create_pods([
        Pod(name=f"wave-{i}", requests={"cpu": "250m", "memory": "256Mi"})
        for i in range(120)])
    op.settle(max_rounds=30)
    op.run_once()   # final gauge pass renders the karpenter_api_* series

    if op.cluster.pending_pods():
        failures.append(f"churn burst did not converge: "
                        f"{len(op.cluster.pending_pods())} pods pending")

    # 1. the coalesced write path engaged
    if api_server.bulk_calls == 0:
        failures.append("bulk verb never engaged (bulk_calls == 0)")
    wstats = op.writer.stats()
    if not wstats.get("bulk_binds"):
        failures.append(f"ApiWriter.bind_pods never batched: {wstats}")
    if not wstats.get("bind_pod"):
        failures.append("no pod ever bound through the writer seam")

    # 2. snapshot-free fan-out: zero per-watcher envelope copies
    astats = api_server.stats()
    if astats["fanout_envelope_copies"] != 0:
        failures.append(f"fan-out made envelope copies: "
                        f"{astats['fanout_envelope_copies']}")
    if astats["events_emitted"] == 0:
        failures.append("watch hub delivered no events during the burst")

    # 3. watch-fed mirror converged to the server's truth
    server_pods = {o["metadata"]["name"]: o["spec"].get("nodeName")
                   for o in api_server._store["pods"].values()}
    mirror_pods = {p.name: p.node_name for p in op.cluster.pods.values()}
    if server_pods != mirror_pods:
        only_s = set(server_pods) - set(mirror_pods)
        only_m = set(mirror_pods) - set(server_pods)
        diff = {n for n in set(server_pods) & set(mirror_pods)
                if server_pods[n] != mirror_pods[n]}
        failures.append(f"mirror diverged from server: server-only "
                        f"{sorted(only_s)[:3]} mirror-only "
                        f"{sorted(only_m)[:3]} bind-diff {sorted(diff)[:3]}")

    # 4. live /metrics carries the karpenter_api_* series and lints clean
    server = start_server(op, 0)
    try:
        base = f"http://127.0.0.1:{server.server_address[1]}"
        scrape = urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10).read().decode()
        problems = lint_exposition(scrape)
        if problems:
            failures.append(f"/metrics lint: {problems[:5]}")
        for series, minimum in (("karpenter_api_bulk_ops", 1.0),
                                ("karpenter_api_watch_events_delivered", 1.0),
                                ("karpenter_api_watchers", 1.0)):
            val = None
            for line in scrape.splitlines():
                if line.startswith(series + " "):
                    val = float(line.split()[-1])
            if val is None:
                failures.append(f"/metrics: series {series} missing")
            elif val < minimum:
                failures.append(f"/metrics: {series}={val} < {minimum}")
        for line in scrape.splitlines():
            if line.startswith("karpenter_api_fanout_envelope_copies "):
                if float(line.split()[-1]) != 0.0:
                    failures.append(f"/metrics: fan-out copies nonzero: "
                                    f"{line}")
    finally:
        server.shutdown()

    if failures:
        print("smoke_writepath: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"smoke_writepath: OK "
          f"(bulk_calls={api_server.bulk_calls}, "
          f"bulk_ops={api_server.bulk_ops}, "
          f"bulk_binds={wstats.get('bulk_binds')}, "
          f"events_delivered={astats['events_emitted']}, "
          f"watchers={astats['watchers']}, "
          f"fanout_copies=0, mirror converged over "
          f"{len(server_pods)} pods)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
