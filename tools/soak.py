"""soak — wall-clock chaos soak of the threaded control plane.

The committed analog of the reference's long-running e2e chaos suite
(test/suites/chaos + the scale deprovisioning matrix run against a real
cluster for hours): every controller on its own thread
(operator/runtime.ControllerRuntime), real time, and a churn driver that
injects the full fault surface — pod waves, heavy deletion (consolidation
pressure), spot interruption messages, transient API errors, and ICE'd
capacity pools.

Exit criteria (after churn stops, the control plane must converge):
- zero pending pods,
- zero leaked instances (checked AFTER the GC grace window — an instance
  the GC hasn't been entitled to reap yet is not a leak),
- zero orphaned node leases.

Usage: python tools/soak.py [--minutes 5] [--seed 0] [--out soak_timeseries.json.gz]
Exits non-zero if any invariant fails (and prints a full control-plane
dump). A 6-minute run churns ~20k pods. The run records a time-series
artifact (pending/nodes/claims/cost per second — the reference's
monitor.go + Timestream metrics-pipeline analog, debug.Monitor).

``--fault-schedule`` drives the SOLVER degradation ladder
(docs/concepts/degradation.md) mid-soak, on top of the cloud chaos:
a comma-separated list of ``SECONDS:ACTION`` entries applied once the
run clock passes each mark. Actions: ``device-error[=N]`` (inject N
device failures, default 3), ``g-limit=N`` (fake group-bucket ceiling
→ wave-split), ``b-limit=N`` (fake bin-table ceiling → host-FFD
fallback), ``clear`` (drop all injected ceilings). Example:
``--fault-schedule 30:device-error,60:g-limit=64,120:clear``. Faults
are always cleared before convergence, and the run prints the
solver's degraded counters so a soak can assert the ladder actually
fired.

``--pipeline`` exercises the overlapped solve path
(docs/concepts/performance.md "Pipelining & the tunnel link") under the
same sustained churn: the pipelined path is forced on, and the run
FAILS unless it actually engaged — the solver's async-dispatch counter
and the resident-input cache's hit/shipped counters are printed and
asserted non-vacuous, so "pipelined soak passed" can never mean "soak
quietly ran sequential".

``--weather <scenario|file>`` drives the adversarial weather simulator
(weather/; docs/reference/weather.md) over the run: a seed-deterministic
spot-market walk repriced into the lattice every tick, ICE spells
holding offerings out of capacity, correlated interruption storms (all
four EventBridge schemas + junk bodies), and device weather through the
solver's FaultInjector — composable with ``--fault-schedule``. The run
then GATES on the paper's bars holding *while degraded*: sustained
latency burn < 1.0 and cost burn <= 1.0 (i.e. <=2% vs the FFD referee),
the ladder demonstrably engaged, interruptions demonstrably handled,
and the recorded weather timeline byte-identical to a same-seed replay.
The verdict + timeline land in a ``WEATHER_*.json.gz`` artifact
(``--weather-out``).

``--consol-out PATH`` arms the CONSOLIDATION verdict
(docs/reference/consolidation.md): the default pool gets
WhenUnderutilized consolidation (``--consolidate-after``), the deletion
waves carve out underutilized nodes, and the run GATES on the vmapped
engine demonstrably carrying the search — accepted removals with
cumulative savings, batched dispatches (>1 candidate set per device
call), fingerprint-unchanged candidates served from the zero-leg probe
cache, and every accept refereed against the host FFD oracle. The
savings-per-hour trajectory (per-sample ``consolidation`` provider
series) lands in a ``CONSOL_*.json.gz`` artifact. With a weather
scenario attached, consolidation additionally rides the advisory: a
scripted spot-crash regime must record HOLDS during the crash window
(``consolidation-weather-hold`` counted > 0) and savings RESUMING after
it clears — zero activity alone never passes.

``--solver-pool N`` composes CONTROL-PLANE weather with all of the
above: N chaos-capable solver sidecars are spawned in-process on unix
sockets and the operator runs against them as a failover pool
(parallel/pool.py SolverPool). Scenario ``SidecarOutage`` elements (the
``blackout`` scenario) kill/hang/junk the endpoints mid-run; the run
then gates on failovers > 0, zero solve-error passes, the local rung
engaging ONLY under a scripted full blackout, and every breaker closed
again after the outage window (docs/reference/solver-pool.md).

``--standby`` spawns a live WARM STANDBY operator (state/replication.py
+ operator/leaderelection.py; docs/reference/handoff.md): a second
Operator sharing the clock/cloud/lattice/queue, its mirror fed by
snapshot + journal-delta streaming over a unix-socket replication
server, pre-building every delta through IncrementalProblemBuilder,
its controllers leadership-gated behind a fence-carrying FileLeaseStore
lease. Scenario ``OperatorKill`` elements (the ``handoff`` scenario)
crash-stop or hang the ACTIVE operator mid-storm; the run then gates on
the standby promoting within the lease window, carrying its first
provisioning pass promptly, the fence token rotating, no duplicate
provider IDs across the handoff, and the usual weather bars (burn,
replay-identical timeline) holding ACROSS the cutover.

Every soak ends with the SATURATION verdict (introspect/headroom.py;
docs/reference/headroom.md): the final first-to-break table prints,
and any queue-kind resource whose monotonic high water reached its
capacity must be explained by the weather scenario or a deliberately
tightened bound, or the run fails. ``--api-watch-queue-bound N`` arms
the prediction drill on top: one deliberately idle pods watcher is
parked so its queue fills at the churn event rate, and the run GATES
on the forecaster ranking ``api_watch_queues`` first-to-break BEFORE
its first overflow — the observatory must predict the break, not
narrate it. ``--headroom-out`` records the ranked table, the
per-sample saturation trajectory, and the forecast-vs-overflow
timestamps in a ``HEADROOM_*.json.gz`` artifact.
"""

from __future__ import annotations

import argparse
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.controllers.garbagecollection import LEAK_GRACE_SECONDS
from karpenter_provider_aws_tpu.errors import NotFoundError
from karpenter_provider_aws_tpu.interruption.messages import spot_interruption
from karpenter_provider_aws_tpu.interruption.queue import FakeQueue
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.operator.runtime import (ControllerRuntime,
                                                         operator_specs)
from karpenter_provider_aws_tpu.solver import FaultInjector


def parse_fault_schedule(spec: str):
    """'30:device-error,60:g-limit=64' → sorted [(30.0, 'device-error',
    None), (60.0, 'g-limit', 64)]."""
    out = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        at, _, action = entry.partition(":")
        if not _:
            raise SystemExit(f"fault entry {entry!r}: want SECONDS:ACTION")
        name, _, val = action.partition("=")
        name = name.strip()
        if name not in ("device-error", "g-limit", "b-limit", "clear"):
            raise SystemExit(f"unknown fault action {name!r}")
        if name in ("g-limit", "b-limit") and not val:
            raise SystemExit(f"fault action {name} needs =N")
        out.append((float(at), name, int(val) if val else None))
    return sorted(out)


def full_blackout_scripted(scenario, n_endpoints: int) -> bool:
    """True when the scenario's SidecarOutage windows cover EVERY pool
    endpoint at some instant — the only condition under which the local
    solve rung is allowed to engage (degraded_reason=pool-exhausted)."""
    if n_endpoints <= 0 or not scenario.sidecar_outages:
        return False
    edges = sorted({o.at for o in scenario.sidecar_outages}
                   | {o.at + o.duration for o in scenario.sidecar_outages})
    for a, b in zip(edges, edges[1:]):
        mid = (a + b) / 2.0
        out = {o.endpoint for o in scenario.sidecar_outages
               if o.at <= mid < o.at + o.duration}
        if set(range(n_endpoints)) <= out:
            return True
    return False


class OperatorHandle:
    """The weather simulator's operator-chaos seam (weather/simulator.py
    ``operators=``): kill = crash-stop the runtime WITHOUT releasing the
    lease (a crashed process never runs its shutdown path — the standby
    must wait out the lease), hang = freeze every controller thread
    including the election tick (the zombie-leader mode: resume releases
    the queued writes straight into the write fence)."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.killed_at = None

    def kill(self) -> None:
        self.killed_at = time.monotonic()
        self.runtime.crash_stop()

    def restart(self) -> None:
        pass   # a dead leader staying dead is the acceptance shape

    def set_hang(self, hung: bool) -> None:
        if hung:
            self.killed_at = time.monotonic()
            self.runtime.pause()
        else:
            self.runtime.resume()


def apply_fault(solver, name: str, val):
    """Apply one schedule entry to the solver's (possibly new) injector.
    Mutations take the injector's own lock: the operator thread is
    consuming device_errors concurrently via take_device_error."""
    if name == "clear":
        solver.inject_faults(None)
        return
    inj = solver.faults or FaultInjector()
    with inj._lock:
        if name == "device-error":
            inj.device_errors += val if val is not None else 3
        elif name == "g-limit":
            inj.g_limit = val
        elif name == "b-limit":
            inj.b_limit = val
    solver.inject_faults(inj)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=5.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--families", default="m5,c5,r5,t3")
    ap.add_argument("--out", default="soak_timeseries.json.gz",
                    help="time-series artifact path ('' disables; a .gz "
                         "suffix gzips — SOAK_r06-scale runs are ~18k "
                         "lines plain; debug.load_timeseries reads both)")
    ap.add_argument("--api-mode", action="store_true",
                    help="drive ALL churn through the fake apiserver "
                         "(watch/list protocol + ApiWriter controllers); "
                         "adds a server-vs-mirror agreement invariant")
    ap.add_argument("--watchers", type=int, default=0,
                    help="extra pods-watch subscribers (API mode only), "
                         "drained by consumer threads — models a fleet "
                         "of dashboards/controllers watching the same "
                         "churn. With snapshot-free fan-out their load "
                         "lands on the delivery layer (watch_event), "
                         "NOT on the api_server store locks; a dropped "
                         "(overrun) watcher re-subscribes like a 410'd "
                         "reflector")
    ap.add_argument("--churn-scale", type=int, default=1,
                    help="multiply pod churn wave sizes (create waves "
                         "become 1-15 x SCALE pods, delete waves up to "
                         "30 x SCALE). In API mode scaled waves ship as "
                         "BULK protocol writes (client.create_pods / "
                         "delete_pods). The recorded SOAK_r08 run: "
                         "--api-mode --churn-scale 45 --minutes 4 "
                         "--watchers 8 --warm-start with a populated "
                         "--compile-cache-dir; the exit report prints "
                         "the contention ranking so the api_server "
                         "lock's rank is part of the recorded verdict")
    ap.add_argument("--fault-schedule", default="",
                    help="SECONDS:ACTION[,...] solver fault injections "
                         "(device-error[=N], g-limit=N, b-limit=N, clear)")
    ap.add_argument("--weather", default="",
                    help="adversarial weather scenario: a named scenario "
                         "(calm, squall, spot-crash, ice-age, storm-front) "
                         "or a path to a scenario JSON file "
                         "(docs/reference/weather.md). Composes with "
                         "--fault-schedule; gates the run on the SLO bars "
                         "holding while the ladder is engaged")
    ap.add_argument("--weather-seed", type=int, default=None,
                    help="weather RNG seed (default: --seed); two runs "
                         "with the same scenario+seed record identical "
                         "weather timelines")
    ap.add_argument("--weather-out", default="",
                    help="weather artifact path (default "
                         "WEATHER_<scenario>.json.gz; '' means default)")
    ap.add_argument("--consol-out", default="",
                    help="consolidation artifact path (CONSOL_*.json.gz):"
                         " set, the run FAILS unless the vmapped "
                         "consolidation engine demonstrably engaged — "
                         "accepted removals, >1 candidate set per "
                         "dispatch, zero-leg cache hits, every accept "
                         "refereed — and the savings-per-hour trajectory "
                         "is recorded (docs/reference/consolidation.md)")
    ap.add_argument("--consolidate-after", type=float, default=None,
                    help="enable WhenUnderutilized consolidation on the "
                         "default pool after N seconds of eligibility "
                         "(default: 5 when --consol-out or a spot-crash "
                         "weather scenario is attached, else Never; "
                         "0 forces Never)")
    ap.add_argument("--solver-pool", type=int, default=0,
                    help="spawn N in-process chaos-capable solver "
                         "sidecars on unix sockets and run the operator "
                         "against them as a failover pool "
                         "(parallel/pool.py SolverPool; docs/reference/"
                         "solver-pool.md). Weather SidecarOutage "
                         "elements (the 'blackout' scenario) drive "
                         "kill/hang/junk against these endpoints; the "
                         "run then GATES on failovers > 0, the pool "
                         "recovering (every breaker closed at exit), "
                         "zero solve-error passes, and the local rung "
                         "engaging only under a scripted full blackout")
    ap.add_argument("--standby", action="store_true",
                    help="spawn a live warm-standby operator behind a "
                         "fence-carrying FileLeaseStore lease, fed by "
                         "snapshot + journal-delta replication "
                         "(docs/reference/handoff.md). Requires a "
                         "--weather scenario with OperatorKill elements "
                         "(the 'handoff' scenario) — the run gates on "
                         "the standby promoting within the lease window "
                         "and carrying passes across the cutover")
    ap.add_argument("--solver-solve-deadline", type=float, default=5.0,
                    help="solve RPC deadline against pool endpoints "
                         "(seconds; --solver-pool only). 5 s bounds a "
                         "hung endpoint's cost per pass in a wall-clock "
                         "soak while leaving room for a cold bucket "
                         "compile (run --warm-start with a populated "
                         "--compile-cache-dir to take compiles out of "
                         "the run entirely)")
    ap.add_argument("--compile-cache-dir", default="",
                    help="persistent XLA compile cache directory "
                         "(solver/solve.py enable_persistent_compile_cache)"
                         ": a SECOND soak boot against the same dir pays "
                         "no fresh compile — the cold-start burn-spike "
                         "acceptance evidence")
    ap.add_argument("--warm-start", action="store_true",
                    help="AOT-compile the warm bucket ladder on a "
                         "background thread at boot and hold the SLO "
                         "warmup window open until it finishes — the "
                         "cold-compile first pass then cannot spike the "
                         "latency burn (peak burn printed at exit)")
    ap.add_argument("--pipeline", action="store_true",
                    help="exercise the overlapped solve path "
                         "(docs/concepts/performance.md 'Pipelining & the "
                         "tunnel link') under sustained load: force the "
                         "pipelined path on and FAIL the soak if it never "
                         "engaged (async solves / resident-cache counters)")
    ap.add_argument("--mesh", default="",
                    help="device mesh for the sharded production path "
                         "(docs/reference/sharding.md; '' = auto, an "
                         "integer forces an N-way mesh — needs the "
                         "virtual-CPU XLA sizing in the environment, as "
                         "tools/smoke_sharded.py sets up). Set, the soak "
                         "FAILS unless sharded solves actually carried "
                         "passes (mesh_solves > 0)")
    ap.add_argument("--api-watch-queue-bound", type=int, default=0,
                    help="tighten the per-watcher watch queue bound "
                         "(API mode; 0 = the Options default, 8192). "
                         "Set, the soak parks ONE deliberately idle "
                         "pods watcher whose queue fills at the churn "
                         "event rate, and the exit verdict GATES on the "
                         "headroom forecaster ranking api_watch_queues "
                         "first-to-break BEFORE its first overflow "
                         "(docs/reference/headroom.md) — the "
                         "observatory must predict the break, not "
                         "narrate it")
    ap.add_argument("--headroom-out", default="",
                    help="headroom artifact path (HEADROOM_*.json.gz): "
                         "the final ranked first-to-break table, the "
                         "per-sample saturation trajectory, and the "
                         "forecast-vs-overflow timestamps. The "
                         "no-unexplained-saturation verdict itself "
                         "gates EVERY soak, artifact or not")
    args = ap.parse_args(argv)
    fault_schedule = parse_fault_schedule(args.fault_schedule)

    fams = tuple(args.families.split(","))
    lattice = build_lattice([s for s in build_catalog() if s.family in fams])
    q = FakeQueue("soak-q")
    api_server = client = None
    if args.api_mode:
        from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
        from karpenter_provider_aws_tpu.kube.apiserver import NotFoundError as KubeNotFound
        api_server = FakeAPIServer()
        client = KubeClient(api_server)
    chaos_sidecars = []
    solver_address = ""
    if args.solver_pool:
        # N chaos-capable sidecars in THIS process (own Solver each,
        # shared jit cache) — the weather simulator's SidecarOutage seam
        # and the pool's failover ladder run against real gRPC endpoints
        import tempfile
        from karpenter_provider_aws_tpu.parallel.sidecar import ChaosSidecar
        from karpenter_provider_aws_tpu.solver import Solver as _Solver
        pool_dir = tempfile.mkdtemp(prefix="soak-pool-")
        for n in range(args.solver_pool):
            sc = ChaosSidecar(_Solver(lattice),
                              f"unix:{pool_dir}/sidecar{n}.sock").start()
            chaos_sidecars.append(sc)
        solver_address = ",".join(s.address for s in chaos_sidecars)
        print(f"soak: solver pool of {args.solver_pool} sidecars "
              f"({solver_address})")
    opt_extra = {}
    if args.api_watch_queue_bound:
        opt_extra["api_watch_queue_bound"] = args.api_watch_queue_bound
    op = Operator(options=Options(registration_delay=0.2,
                                  batch_idle_duration=0.05,
                                  batch_max_duration=0.5,
                                  interruption_queue="soak-q",
                                  spot_to_spot_consolidation=True,
                                  mesh=args.mesh,
                                  **opt_extra,
                                  solver_address=solver_address,
                                  solver_solve_deadline=(
                                      args.solver_solve_deadline
                                      if args.solver_pool else 0.0),
                                  compile_cache_dir=args.compile_cache_dir),
                  lattice=lattice, interruption_queue=q,
                  api_server=api_server)
    if args.pipeline:
        op.solver.set_pipeline(True)
    if args.warm_start:
        # the SLO warmup window stays open until the AOT ladder lands:
        # cold-compile passes are boot cost, not burn signal
        op.slo.begin_warmup()
        op.solver.warmup(node_pools_count=len(op.node_pools),
                         background=True,
                         aot=bool(args.compile_cache_dir),
                         on_done=op.slo.end_warmup)
    # ---- warm standby (--standby): a second operator behind the lease --
    op_a = op
    op_b = replica = elector_a = elector_b = None
    repl_server = repl_client = handle_a = None
    if args.standby:
        if not args.weather:
            print("soak: --standby without a --weather scenario scripting "
                  "operator kills would be vacuous (nothing ever kills "
                  "the leader)")
            return 1
        import tempfile as _tempfile
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            FileLeaseStore, LeaderElector)
        from karpenter_provider_aws_tpu.state.replication import (
            ReplicationClient, ReplicationService, ReplicationSource,
            StandbyReplica, serve_replication)
        handoff_dir = _tempfile.mkdtemp(prefix="soak-handoff-")
        repl_src = ReplicationSource(op.cluster)
        repl_server = serve_replication(ReplicationService(repl_src),
                                        f"unix:{handoff_dir}/repl.sock")
        lease_store = FileLeaseStore(f"{handoff_dir}/lease.json")
        # the standby shares the WORLD (clock, cloud, queue, lattice) but
        # owns its mirror: state arrives ONLY over the replication stream
        op_b = Operator(options=Options(registration_delay=0.2,
                                        batch_idle_duration=0.05,
                                        batch_max_duration=0.5,
                                        spot_to_spot_consolidation=True),
                        lattice=lattice, cloud=op.cloud, clock=op.clock,
                        interruption_queue=q)
        repl_client = ReplicationClient(f"unix:{handoff_dir}/repl.sock")
        replica = StandbyReplica(
            op_b.cluster, repl_client,
            prebuild=lambda: op_b.provisioner.warm_build())
        elector_a = LeaderElector(lease_store, "op-a", clock=op.clock)
        elector_b = LeaderElector(lease_store, "op-b", clock=op.clock,
                                  promotion_gate=replica.promotion_ready)
        # introspection is a process-global replace-by-name registry:
        # op_b's construction just claimed every surface, so hand them
        # back to the LEADER (op_b's promote hook re-wires on cutover);
        # wire_handoff order matters for the same reason — standby first,
        # leader last
        op_b.wire_handoff(elector_b, replica=replica)
        op._wire_introspection()
        op.wire_handoff(elector_a, source=repl_src)
        print(f"soak: warm standby armed (lease store "
              f"{handoff_dir}/lease.json, replication "
              f"unix:{handoff_dir}/repl.sock)")
    weather_sim = None
    if args.weather:
        from karpenter_provider_aws_tpu import introspect
        from karpenter_provider_aws_tpu.weather import (WeatherSimulator,
                                                        load_scenario)
        scenario = load_scenario(args.weather)
        if args.standby and not scenario.operator_kills:
            print(f"soak: --standby but scenario {scenario.name!r} "
                  "scripts no operator kills — the standby would idle "
                  "the whole run (vacuous handoff)")
            return 1
        if scenario.operator_kills and not args.standby:
            print("soak: scenario scripts operator kills but no "
                  "--standby is attached — killing the only operator "
                  "would just end the control plane")
            return 1
        if args.standby:
            # the runtime the handle crash-stops is created below; the
            # simulator only fires kills after start(), by which point
            # the handle is armed
            handle_a = OperatorHandle(None)
        weather_sim = WeatherSimulator(
            scenario, lattice,
            seed=(args.seed if args.weather_seed is None
                  else args.weather_seed),
            clock=op.clock, pricing=op.pricing_provider, cloud=op.cloud,
            unavailable=op.unavailable, queue=q, solver=op.solver,
            metrics=op.metrics, sidecars=chaos_sidecars,
            operators=([handle_a] if handle_a is not None else None))
        if scenario.sidecar_outages and not chaos_sidecars:
            print("soak: scenario scripts sidecar outages but no "
                  "--solver-pool is attached — the control-plane "
                  "weather would be vacuous")
            return 1
        introspect.registry().register("weather", weather_sim.stats)
        # voluntary consolidation rides the weather: hold through storm
        # windows and crash regimes, keep packing through ice
        # (docs/reference/consolidation.md "Weather gates")
        op.disruption.engine.weather_advisory = \
            weather_sim.consolidation_advisory
        if op_b is not None:
            op_b.disruption.engine.weather_advisory = \
                weather_sim.consolidation_advisory
        print(f"soak: weather scenario {scenario.name!r} "
              f"seed={weather_sim.seed} tick={scenario.tick_seconds}s "
              f"(storms={len(scenario.storms)} ice={len(scenario.ice)} "
              f"regimes={len(scenario.regimes)})")
    # consolidation enablement: the pool default is Never; the CONSOL
    # verdict and the spot-crash advisory gate both need the engine live
    from karpenter_provider_aws_tpu.weather.simulator import CONSOL_HOLD_MU
    crash_scripted = (weather_sim is not None and any(
        r.mu >= CONSOL_HOLD_MU for r in weather_sim.scenario.regimes))
    consolidate_after = args.consolidate_after
    if consolidate_after is None and (args.consol_out or crash_scripted):
        # short enough that storm-churned nodes still age into
        # eligibility mid-window — 15 s leaves the candidate set empty
        # through an interruption storm and the weather gate vacuous
        consolidate_after = 5.0
    if consolidate_after:
        for o in (op, op_b):
            if o is None:
                continue
            dflt = o.node_pools.get("default")
            if dflt is not None:
                dflt.disruption.consolidation_policy = "WhenUnderutilized"
                dflt.disruption.consolidate_after = consolidate_after
                if client is not None and o is op:
                    client.update_nodepool(dflt)   # API mode: via watch
        print(f"soak: consolidation armed (WhenUnderutilized, "
              f"consolidate_after={consolidate_after}s)")
    specs_a = operator_specs(op)
    if consolidate_after:
        # one voluntary disruption per pass at the default 10 s cadence
        # starves the consolidation verdict on a minutes-long soak —
        # emptiness alone eats every pass. Same controller, just paced
        # to the soak's churn tempo.
        for sp in specs_a:
            if sp.name == "disruption":
                sp.interval = 2.0
    rt = ControllerRuntime(specs_a, elector=elector_a).start()
    rt_b = None
    if args.standby:
        handle_a.runtime = rt
        from karpenter_provider_aws_tpu.operator.runtime import \
            ControllerSpec
        specs_b = operator_specs(op_b)
        # the replication pump runs UNGATED (standbys stream; leaders
        # don't poll themselves) and goes quiet on promotion
        specs_b.append(ControllerSpec(
            "handoff-sync",
            lambda: (replica.sync_once()
                     if not elector_b.is_leader else None),
            interval=0.2, gate_on_leadership=False))
        rt_b = ControllerRuntime(specs_b, elector=elector_b).start()
    from karpenter_provider_aws_tpu.debug import Monitor, dump_state
    monitor = Monitor(op).start(interval=1.0)
    # the extra watcher fleet: N pods subscriptions drained by a few
    # consumer threads (kube/apiserver.py bounded queues + 410/relist)
    import threading as _threading
    watch_stats = {"delivered": 0, "resubscribes": 0}
    watch_stop = _threading.Event()
    watch_threads = []
    if args.watchers and api_server is not None:
        from karpenter_provider_aws_tpu.kube.apiserver import TooOldError

        def drain(watch_slice):
            subs = [api_server.watch("pods") for _ in range(watch_slice)]
            delivered = resubs = 0
            while not watch_stop.is_set():
                for i, w in enumerate(subs):
                    try:
                        delivered += len(w.pop_pending())
                    except TooOldError:
                        api_server.stop_watch(w)
                        subs[i] = api_server.watch("pods",
                                                   api_server.last_rv)
                        resubs += 1
                watch_stop.wait(0.05)
            for w in subs:
                api_server.stop_watch(w)
            watch_stats["delivered"] += delivered
            watch_stats["resubscribes"] += resubs

        n_drainers = min(2, args.watchers)
        per = max(args.watchers // n_drainers, 1)
        watch_threads = [
            _threading.Thread(target=drain, args=(per,), daemon=True,
                              name=f"soak-watcher-{i}")
            for i in range(n_drainers)]
        for t in watch_threads:
            t.start()
    # the deliberately idle watcher (--api-watch-queue-bound): never
    # drained, so its queue fills at the raw churn event rate. The
    # drained fleet above never shows the forecaster a rising depth —
    # THIS queue is the one the prediction-before-overflow gate reads
    idle_watch = None
    if args.api_watch_queue_bound and api_server is not None:
        idle_watch = api_server.watch("pods")
        print(f"soak: idle watcher parked against a watch queue bound "
              f"of {args.api_watch_queue_bound} — the headroom "
              "forecaster must name it before it overflows")
    rng = random.Random(args.seed)
    t_start = time.monotonic()
    stop = t_start + args.minutes * 60.0
    i = 0
    pending_faults = list(fault_schedule)
    promote_t = b_first_pass_t = None
    # engine stats frozen at the LAST advisory-held instant: the
    # "savings resumed after the crash" gate compares against these
    consol_stats_at_hold = None

    def safe_instances():
        try:
            return op.cloud.list_instances()
        except Exception:
            return []

    # arm-check the lock-order witness (introspect/contention.py;
    # docs/reference/linting.md): one deliberate benign nesting on
    # dedicated names proves the witness is recording BEFORE the run —
    # the production locks are kept deliberately flat by the out-of-lock
    # discipline, so "0 edges at exit" would otherwise be ambiguous
    # between "nothing nested" and "witness never armed"
    from karpenter_provider_aws_tpu.introspect import contention as _cont
    with _cont.lock("soak_witness_outer"):
        with _cont.lock("soak_witness_inner"):
            pass
    assert _cont.lockorder_stats()["edges"] >= 1, \
        "lock-order witness failed its arm-check"
    print("soak: lock-order witness armed "
          "(soak_witness_outer -> soak_witness_inner recorded)")

    if weather_sim is not None:
        weather_sim.start()
    try:
        while time.monotonic() < stop:
            while pending_faults and \
                    time.monotonic() - t_start >= pending_faults[0][0]:
                _, fname, fval = pending_faults.pop(0)
                apply_fault(op.solver, fname, fval)
                print(f"soak: fault applied {fname}"
                      f"{'' if fval is None else '=' + str(fval)}")
            if weather_sim is not None:
                weather_sim.advance()
                if weather_sim.consolidation_advisory()["hold"]:
                    consol_stats_at_hold = op.disruption.engine.stats()
            # churn lands on the ACTIVE operator: after a cutover the
            # promoted standby's mirror is the live one (the dead
            # leader's would silently swallow every wave)
            aop = op
            if args.standby and elector_b.is_leader:
                aop = op_b
                if promote_t is None:
                    promote_t = time.monotonic()
                    print(f"soak: standby PROMOTED (fence "
                          f"{elector_b.fence}) "
                          f"{promote_t - (handle_a.killed_at or promote_t):.1f}s "
                          "after the leader kill")
                if b_first_pass_t is None and \
                        op_b.provisioner.stats().get("passes", 0) > 0:
                    b_first_pass_t = time.monotonic()
            r = rng.random()
            if r < 0.5:
                wave = []
                for _ in range(rng.randint(1, 15) * args.churn_scale):
                    i += 1
                    wave.append(Pod(
                        name=f"s{i}",
                        requests={"cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                                  "memory": f"{rng.choice([512, 1024, 2048])}Mi"}))
                if client is not None:
                    # through the protocol — one BULK write per wave
                    # (one lock acquisition + one watch flush), the
                    # coalesced ingest path the 100k-churn soak proves
                    client.create_pods(wave)
                else:
                    for pod in wave:
                        aop.cluster.add_pod(pod)
            elif r < 0.8:
                # heavy deletion waves -> underutilized nodes -> consolidation.
                # Bounded at 10% of the population per wave so scaled
                # churn GROWS the cluster instead of strip-mining it —
                # the 100k-churn soak must also hold 100+ nodes under
                # fire, not just cycle a small one fast
                names = list(aop.cluster.pods)
                doomed = rng.sample(
                    names, min(len(names), max(len(names) // 10, 1),
                               rng.randint(5, 30) * args.churn_scale))
                if client is not None:
                    client.delete_pods(doomed)   # NotFound raced = ignored
                else:
                    for name in doomed:
                        aop.cluster.delete_pod(name)
            elif r < 0.88:
                insts = safe_instances()
                if insts:
                    q.send(spot_interruption(rng.choice(insts).id))
            elif r < 0.91:
                # drift churn: rev the pool template; the drift
                # controller must roll stale-hash nodes while the rest
                # of the storm rages (API mode: server-side, so the
                # config watch delivers it like any operator would).
                # With consolidation armed the rev is suppressed: a
                # template revved every second keeps EVERY node
                # perpetually drift-stale, and drift (earlier in the
                # method order) would eat every disruption pass —
                # the consolidation verdict would starve by design.
                pool = (None if consolidate_after
                        else aop.node_pools.get("default"))
                if pool is not None:
                    pool.labels["soak/rev"] = f"r{i}"
                    if client is not None:
                        client.update_nodepool(pool)
            elif r < 0.94:
                op.cloud.inject_error(NotFoundError("soak-chaos"))
            else:
                insts = safe_instances()
                if insts:
                    v = rng.choice(insts)
                    op.cloud.set_capacity(v.capacity_type, v.instance_type,
                                          v.zone, 0)
            time.sleep(rng.uniform(0.01, 0.08))
    finally:
        # a controller blocked mid-pass can outlive the join timeout;
        # invariants must never be read over live mutation
        while not rt.stop():
            print("soak: waiting for a blocked controller thread...")
        if rt_b is not None:
            while not rt_b.stop():
                print("soak: waiting for a blocked standby thread...")
        monitor.stop()
        watch_stop.set()
        for t in watch_threads:
            t.join(timeout=2.0)
        if watch_threads:
            print(f"soak: watcher fleet ({args.watchers}) delivered="
                  f"{watch_stats['delivered']} "
                  f"resubscribes={watch_stats['resubscribes']}")
        if idle_watch is not None:
            # stop_watch folds its depth into the server's monotonic
            # high water; the registry's own high water already holds it
            api_server.stop_watch(idle_watch)

    # the handoff verdict BEFORE any rebind: the gates read both sides
    handoff_ok = True
    handoff_report = None
    if args.standby:
        promoted = elector_b.is_leader or promote_t is not None
        kill_t = handle_a.killed_at
        latency = (promote_t - kill_t) if (promote_t and kill_t) else None
        first_pass = (b_first_pass_t - promote_t) if (b_first_pass_t
                                                      and promote_t) else None
        rs = replica.stats()
        b_passes = op_b.provisioner.stats().get("passes", 0)
        b_deltas = op_b.solver.pipeline_stats.get("delta_solves", 0)
        # duplicate-launch evidence on the SHARED cloud: across both
        # mirrors no provider ID may back two claims (a standby
        # relaunching capacity the dead leader already provisioned
        # would mint a second instance for the same workload)
        # claims replicated to BOTH mirrors legitimately share provider
        # IDs — only collisions WITHIN one mirror are duplicates
        dup_providers = sum(
            len(ps) - len(set(ps)) for ps in (
                [c.provider_id for c in o.cluster.claims.values()
                 if c.provider_id] for o in (op_a, op_b)))
        handoff_report = {
            "promoted": promoted, "fence": elector_b.fence,
            "promote_latency_s": latency, "first_pass_s": first_pass,
            "kill_at_s": (kill_t - t_start) if kill_t else None,
            "standby_passes": b_passes, "standby_delta_solves": b_deltas,
            "replica": rs, "dup_provider_ids": dup_providers,
            "fence_rejections": (op_a._fence_guard.rejections
                                 if op_a._fence_guard else 0),
            "leases_swept": op_b.cluster.leases_swept,
            "promotions_blocked": elector_b.promotions_blocked,
        }
        print(f"soak: handoff {handoff_report}")
        if kill_t is None:
            print("soak: scenario scripted an operator kill but the "
                  "handle never fired (vacuous handoff)")
            handoff_ok = False
        if not promoted:
            print("soak: leader killed but the standby never promoted")
            handoff_ok = False
        if latency is not None and latency > \
                elector_b.lease_duration + 3 * 2.0 + 5.0:
            print(f"soak: promotion took {latency:.1f}s — outside the "
                  "lease window + election cadence")
            handoff_ok = False
        if promoted and first_pass is None:
            print("soak: standby promoted but never carried a "
                  "provisioning pass")
            handoff_ok = False
        elif first_pass is not None and first_pass > 10.0:
            print(f"soak: first post-promotion pass took {first_pass:.1f}s "
                  "(> 10s SLO window)")
            handoff_ok = False
        if promoted and rs.get("prebuilds", 0) == 0:
            # delta solves post-promotion are NOT required: the handoff
            # scenario reprices every tick, and price-changed correctly
            # forces the incremental builder onto the full path — warmth
            # is evidenced by the pre-promotion prebuild stream instead
            print("soak: promoted standby never prebuilt — "
                  "the warm mirror was not actually warm")
            handoff_ok = False
        if promoted and rs.get("snapshots", 0) < 1:
            print("soak: standby promoted without ever applying a "
                  "snapshot")
            handoff_ok = False
        if dup_providers:
            print(f"soak: {dup_providers} duplicate provider IDs across "
                  "the handoff (capacity launched twice)")
            handoff_ok = False
        # hand the exit/convergence machinery the PROMOTED operator: its
        # mirror is the live control plane now. Its runtime released the
        # lease on stop, so re-acquire once — the single-threaded
        # convergence loop below writes through the fence guard.
        if promoted:
            elector_b.try_acquire_or_renew()
            op = op_b
    # converge: clear injected faults (all controller threads have joined,
    # so plain writes are race-free here), then let the single-threaded
    # loop settle PAST the GC grace window so every reapable leak is reaped
    weather_ticks = 0
    if weather_sim is not None:
        # freeze the weather at cutoff: thaw held pools, restore base spot
        # prices (one more price_version bump so downstream memos re-key).
        # The injected device faults clear with the rest below.
        weather_ticks = weather_sim.ticks
        weather_sim.stop()
    op.cloud.next_error = None
    op.cloud.capacity_pools.clear()
    # capacity is restored — flush the ICE marks with it (their 180 s
    # TTL would otherwise mask offerings deep into the convergence tail
    # and strand late-wave pods as unschedulable)
    op.unavailable.flush()
    # quiesce VOLUNTARY disruption for the invariant read: consolidation
    # is a continuous optimizer — on a churn-scaled multi-thousand-pod
    # cluster it drains/rebinds pods indefinitely, and a single-instant
    # "zero pending" is about involuntary state, not about catching the
    # optimizer between a drain and its rebind. Termination/GC keep
    # running so every in-flight drain still completes.
    if consolidate_after:
        # the zero-leg coda: the cache's steady-state claim needs a calm
        # instant the storm never offers. Budget pinned to 0 so nothing
        # moves; one search repopulates the probe cache (the ICE flush
        # above invalidated it), then a pending-only wiggle re-runs the
        # search — every candidate verdict must come back cached, at
        # zero device legs (docs/reference/consolidation.md)
        from karpenter_provider_aws_tpu.apis.objects import \
            DisruptionBudget
        dflt = op.node_pools.get("default")
        if dflt is not None:
            dflt.disruption.budgets = [DisruptionBudget(nodes="0")]
            # churn-fresh replacements are younger than consolidate_after
            # at cutoff; the coda is about the cache, not pacing, so make
            # every initialized node eligible for the search
            dflt.disruption.consolidate_after = 0.0
        # finish whatever the storm left mid-flight — draining originals,
        # unregistered replacements, evicted pods re-pending all keep
        # dirtying bins pass after pass; the coda needs a genuinely calm
        # cluster, and budget 0 keeps anything NEW from starting
        calm, calm_deadline = 0, time.monotonic() + 25.0
        while time.monotonic() < calm_deadline:
            op.run_once()
            if not op.cluster.pending_pods() \
                    and not op.disruption._in_flight:
                calm += 1
                if calm >= 5:
                    break
            else:
                calm = 0
            time.sleep(0.05)
        for _ in range(8):
            if not op.disruption._reconcile_once():
                break
        pre_coda = post_coda = op.disruption.engine.stats()
        for attempt in range(3):
            wiggle = f"consol-coda-{attempt}"
            op.cluster.add_pod(Pod(name=wiggle,
                                   requests={"cpu": "100m",
                                             "memory": "64Mi"}))
            op.disruption._reconcile_once()
            op.cluster.delete_pod(wiggle)
            post_coda = op.disruption.engine.stats()
            if post_coda.get("fp_unchanged", 0) > \
                    pre_coda.get("fp_unchanged", 0):
                break
        print(f"soak: consolidation coda zero-leg hits "
              f"{pre_coda.get('fp_unchanged', 0):g} -> "
              f"{post_coda.get('fp_unchanged', 0):g} "
              f"(dispatches {pre_coda.get('vmapped_whatifs', 0):g} -> "
              f"{post_coda.get('vmapped_whatifs', 0):g})")
    op.disruption.reconcile = lambda: None
    solver_fired = dict(op.solver.faults.fired) if op.solver.faults else {}
    op.solver.inject_faults(None)
    # scaled churn leaves a 10k-pod cluster mid-wave at cutoff; the
    # convergence tail gets proportionally longer so the verdict is
    # about invariants, not about how fast a big cluster can settle
    tail = LEAK_GRACE_SECONDS + 15.0 + (60.0 if args.churn_scale > 1
                                        else 0.0)
    deadline = time.monotonic() + tail
    ticks = 0
    while time.monotonic() < deadline:
        op.run_once()
        ticks += 1
        if ticks % 20 == 0:
            monitor.sample()   # the convergence tail rides the series too
        if not op.cluster.pending_pods() \
                and time.monotonic() > deadline - 10.0:
            break
        time.sleep(0.05)
    monitor.sample()

    pending = op.cluster.pending_pods()
    if pending:
        # name WHY the tail could not settle: the provisioner's last-pass
        # verdict plus a sample of the stuck pods
        print(f"soak: last pass = "
              f"{ {k: v for k, v in op.provisioner.stats().items() if k.startswith('last_pass')} } "
              f"sample stuck: {[p.name for p in pending[:5]]}")
    claimed = {c.provider_id for c in op.cluster.claims.values()
               if c.provider_id}
    leaked = [x for x in op.cloud.list_instances()
              if x.provider_id not in claimed]
    orphans = op.cluster.orphaned_leases()
    print(f"soak: pods_churned={i} pending={len(pending)} "
          f"nodes={len(op.cluster.nodes)} claims={len(op.cluster.claims)} "
          f"leaked={len(leaked)} orphan_leases={len(orphans)}")
    if fault_schedule:
        print(f"soak: solver degraded_counts={op.solver.degraded_counts} "
              f"faults_fired={solver_fired}")
    ok = not pending and not leaked and not orphans
    if args.standby:
        ok = ok and handoff_ok
    if args.pipeline:
        # the overlapped path must have actually carried the soak's
        # solves — a flag that silently fell back to sequential would
        # report a vacuous pass
        pstats = dict(op.solver.pipeline_stats)
        cstats = op.solver._resident.stats()
        print(f"soak: pipeline stats={pstats} resident_cache={cstats}")
        if pstats.get("async_solves", 0) == 0:
            print("soak: --pipeline set but no solve took the "
                  "overlapped path")
            ok = False
    if fault_schedule and not (op.solver.degraded_counts or solver_fired):
        # a schedule that never fired means the soak did not exercise the
        # ladder it promised to — fail loudly rather than report a
        # vacuous pass
        print("soak: fault schedule applied but solver never degraded")
        ok = False
    if client is not None:
        # server-vs-mirror agreement: after convergence the watch-fed
        # mirror and the apiserver's truth must be identical sets
        op.sync_once()
        server_pods = {p.name for p in client.list_pods()}
        server_nodes = {n.name for n in client.list_nodes()}
        agree = (server_pods == set(op.cluster.pods)
                 and server_nodes == set(op.cluster.nodes))
        print(f"soak: server-vs-mirror agreement "
              f"{'OK' if agree else 'VIOLATED'} "
              f"(pods {len(server_pods)}, nodes {len(server_nodes)})")
        if not agree:
            ps, pm = server_pods - set(op.cluster.pods), \
                set(op.cluster.pods) - server_pods
            ns, nm = server_nodes - set(op.cluster.nodes), \
                set(op.cluster.nodes) - server_nodes
            print(f"soak: agreement diff: pods server-only "
                  f"{sorted(ps)[:5]} (+{max(len(ps) - 5, 0)}) "
                  f"mirror-only {sorted(pm)[:5]} (+{max(len(pm) - 5, 0)}); "
                  f"nodes server-only {sorted(ns)[:5]} "
                  f"mirror-only {sorted(nm)[:5]}")
        ok = ok and agree
    # the SLO burn verdict over the whole run (introspect/slo.py — the
    # same gauges /metrics exports and the Monitor artifact carries)
    slo = op.slo.update()
    print(f"soak: slo latency_burn={slo['latency_burn']} "
          f"(p50 {slo['latency_p50_ms']}ms / 200ms) "
          f"cost_burn={slo['cost_burn']} "
          f"(ratio_p50 {slo['cost_ratio_p50']})")
    weather_doc = None
    if weather_sim is not None:
        from karpenter_provider_aws_tpu.weather import WeatherSimulator as _WS
        wsc = weather_sim.scenario
        wstats = weather_sim.stats()
        if args.standby:
            # the storm straddles the cutover: A consumed messages before
            # the kill, B after promotion — the evidence bar sums both
            intr = {}
            for o in (op_a, op_b):
                if o is None or o.interruption is None:
                    continue
                for k, v in o.interruption.stats().items():
                    intr[k] = intr.get(k, 0) + v
        else:
            intr = op.interruption.stats() if op.interruption else {}
        # real interruption schemas only — junk (malformed/unknown) is
        # counted separately and must not pad the >100 evidence bar
        handled = sum(intr.get(f"received_{k}", 0)
                      for k in ("spot_interruption",
                                "rebalance_recommendation",
                                "scheduled_change", "state_change"))
        degraded_total = sum(op.solver.degraded_counts.values())
        # the replay check: the deterministic timeline must re-derive
        # byte-identically from (scenario, seed, ticks) with no control
        # plane attached — the recorded weather was reproducible, not
        # anecdotal
        replay_match = (_WS.replay(wsc, lattice, weather_ticks,
                                   seed=weather_sim.seed)
                        == weather_sim.timeline)
        print(f"soak: weather ticks={weather_ticks} "
              f"events={len(weather_sim.timeline)} "
              f"msgs={wstats['messages_sent']} "
              f"(junk {wstats['junk_sent']}) "
              f"ice_marks={wstats['ice_marks']} "
              f"device_errors={wstats['device_errors']} "
              f"interruptions_handled={handled} "
              f"degraded_total={degraded_total} "
              f"replay={'IDENTICAL' if replay_match else 'DIVERGED'}")
        # the weather gates: the paper's bars must hold WHILE the ladder
        # is engaged and the market moves (burn thresholds per ISSUE 9 /
        # ROADMAP item 5), and the chaos must be demonstrably non-vacuous
        if not replay_match:
            print("soak: weather timeline is not same-seed reproducible")
            ok = False
        if slo["latency_burn"] >= 1.0:
            print(f"soak: sustained latency burn {slo['latency_burn']} "
                  ">= 1.0 under weather")
            ok = False
        if slo["cost_burn"] > 1.0:
            print(f"soak: cost burn {slo['cost_burn']} > 1.0 "
                  "(>2% vs FFD referee) under weather")
            ok = False
        if wsc.storms:
            if handled <= 100:
                print(f"soak: weather storms configured but only {handled} "
                      "interruption messages handled (> 100 required)")
                ok = False
            # the storms themselves must have produced evidence: the
            # churn loop's own ad-hoc spot interruptions also land in
            # `handled`, so a run whose scripted storms never fired (too
            # short, or zone filters matching nothing) must not pass on
            # churn-generated padding
            storm_real = wstats["messages_sent"] - wstats["junk_sent"]
            if wstats["storm_ticks"] == 0 or storm_real == 0:
                print(f"soak: weather storms configured but produced no "
                      f"storm-sourced messages (storm_ticks="
                      f"{wstats['storm_ticks']}, real msgs={storm_real})")
                ok = False
            if any(s.device_error_rate for s in wsc.storms) \
                    and degraded_total == 0:
                print("soak: weather device faults configured but the "
                      "solver never degraded")
                ok = False
        # the same non-vacuity bar for the other weather systems: a
        # scenario that scripts ICE spells or regime shifts must have
        # actually applied them (a run shorter than the schedule, or
        # filters matching no offering, must not read as a survived
        # scarcity/price drill)
        if wsc.ice and wstats["ice_marks"] == 0:
            print("soak: weather ICE spells configured but no offering "
                  "was ever held (ice_marks=0)")
            ok = False
        if wsc.regimes and wstats["regime_shifts"] == 0:
            print("soak: weather regimes configured but none activated "
                  "(regime_shifts=0)")
            ok = False
        # the consolidation weather gate must be NON-VACUOUS on a crash
        # scenario (docs/reference/consolidation.md "Weather gates"):
        # holds demonstrably recorded DURING the crash window, and the
        # engine demonstrably resuming (savings growing) after it
        # cleared — a run that merely never consolidated proves nothing
        if crash_scripted:
            cst = op.disruption.engine.stats()
            held = cst.get("weather_holds", 0)
            hold_skips = cst.get("skip_consolidation_weather_hold", 0)
            print(f"soak: consolidation weather gate holds={held:g} "
                  f"hold_skips={hold_skips:g} "
                  f"savings_at_last_hold="
                  f"{(consol_stats_at_hold or {}).get('savings_per_hour')} "
                  f"savings_final={cst.get('savings_per_hour', 0.0):g}")
            if held == 0 or hold_skips == 0:
                print("soak: a spot-crash regime was scripted but "
                      "consolidation never recorded a weather hold "
                      "(vacuous gate — was the engine ever eligible "
                      "during the window?)")
                ok = False
            if consol_stats_at_hold is None:
                print("soak: crash regime scripted but the advisory "
                      "never reported hold to the churn loop")
                ok = False
            elif cst.get("savings_per_hour", 0.0) <= \
                    consol_stats_at_hold.get("savings_per_hour", 0.0) \
                    + 1e-9:
                print("soak: consolidation never RESUMED after the "
                      "crash window (savings flat since the last hold)")
                ok = False
        # control-plane weather gates (docs/reference/solver-pool.md):
        # a blackout drill must demonstrably have exercised the pool —
        # failovers happened, the local rung engaged ONLY under a
        # scripted full blackout, no pass was lost to a solve error,
        # and the pool RECOVERED (every breaker closed again after the
        # outage windows + convergence tail)
        if wsc.sidecar_outages and chaos_sidecars:
            # give the breakers their probation: the half-open probe
            # rides the injected clock (wall time here), and repeated
            # opens back off up to ~30 s — poll until every endpoint is
            # closed again or the recovery budget runs out
            recover_deadline = time.monotonic() + 45.0
            while time.monotonic() < recover_deadline:
                op.solver.check_endpoints()
                pst = op.solver.pool_stats()
                if pst["healthy"] == pst["endpoints"]:
                    break
                time.sleep(0.5)
            pst = op.solver.pool_stats()
            full_blackout = full_blackout_scripted(wsc,
                                                   len(chaos_sidecars))
            print(f"soak: pool endpoints={pst['endpoints']} "
                  f"healthy={pst['healthy']} "
                  f"failovers={pst['failovers']} "
                  f"delegated={pst['delegated_solves']} "
                  f"local={pst['local_solves']} "
                  f"breakers="
                  + ",".join(op.solver.breaker_states().values()))
            if pst["failovers"] == 0:
                print("soak: sidecar outages scripted but the pool "
                      "never failed over (failovers=0)")
                ok = False
            if pst["healthy"] != pst["endpoints"]:
                print("soak: pool did not recover after the outage "
                      f"window ({pst['healthy']}/{pst['endpoints']} "
                      "breakers closed)")
                ok = False
            if full_blackout and pst["local_solves"] == 0:
                print("soak: a full blackout was scripted but the "
                      "local rung never engaged (local_solves=0)")
                ok = False
            if not full_blackout and pst["local_solves"] > 0:
                print(f"soak: local rung engaged {pst['local_solves']}x "
                      "without a scripted full blackout (a healthy "
                      "endpoint existed the whole run)")
                ok = False
            solve_errors = op.provisioner.explain.stats().get(
                "reason_solve_error", 0)
            if solve_errors:
                print(f"soak: {solve_errors:g} passes lost to "
                      "solve-error under control-plane weather")
                ok = False
        t_base = monitor.samples[0]["t"] if monitor.samples else 0.0
        burn_series = [
            [round(s["t"] - t_base, 1),
             s["subsystems"]["slo"].get("latency_burn", 0.0),
             s["subsystems"]["slo"].get("cost_burn", 0.0)]
            for s in monitor.samples if "slo" in s.get("subsystems", {})]
        weather_doc = weather_sim.artifact(
            slo=slo, burn_series=burn_series,
            degraded_counts=dict(op.solver.degraded_counts),
            solver_faults_fired=solver_fired,
            solver_pool=(op.solver.pool_stats()
                         if chaos_sidecars else None),
            interruption=intr, interruptions_handled=handled,
            replay_match=replay_match,
            handoff=(handoff_report if args.standby else None),
            soak={"pods_churned": i, "minutes": args.minutes,
                  "seed": args.seed, "api_mode": bool(args.api_mode),
                  "churn_scale": args.churn_scale})
    # ONE summary pass serves every exit print below (summary() rescans
    # all retained samples, including the per-sample contention sweep)
    summ = monitor.summary()
    print(f"soak: incremental builds="
          f"{op.provisioner.inc_builder.incremental_builds} "
          f"full={op.provisioner.inc_builder.full_builds} "
          f"delta_solves={op.solver.pipeline_stats['delta_solves']} "
          f"peak_latency_burn={summ.get('peak_latency_burn')}")
    if "peak_lock_wait_ms" in summ:
        print(f"soak: peak lock wait {summ['peak_lock_wait_ms']}ms "
              f"({summ.get('peak_lock_wait_lock')}) "
              f"burn_captures={op.burn_capture.stats().get('total', 0)}")
    # the contention verdict (introspect/contention.py; what `kpctl top`
    # CONTENTION renders): top-3 locks by wait p99 — the write-path
    # acceptance for the API stratum is api_server OUT of this list
    from karpenter_provider_aws_tpu.introspect import contention
    top3 = contention.top_waits(3)
    print("soak: contention top3 = "
          + (", ".join(f"{n} p99={p * 1e3:.2f}ms ({c}x)"
                       for n, p, c in top3) or "(none contended)"))
    print("soak: contention full ranking = "
          + (", ".join(f"{n} p99={p * 1e3:.2f}ms ({c}x)"
                       for n, p, c in contention.top_waits(10))
             or "(none)"))
    # the lock-order witness verdict (introspect/contention.py;
    # docs/reference/linting.md): a threaded run must have WITNESSED
    # orderings (edges > 0 — a zero-edge run means the witness never
    # armed, a vacuous pass) and found NO cycle (a cycle is a potential
    # deadlock two threads can complete any day)
    lo = contention.lockorder_stats()
    lo_cycles = contention.lockorder_cycles()
    lo_edges = contention.lockorder_detail()["edges"]
    prod_edges = [e for e in lo_edges
                  if not e.startswith("soak_witness")]
    print(f"soak: lockorder edges={lo['edges']:g} "
          f"(production {len(prod_edges)}: {sorted(prod_edges)}) "
          f"cycles={len(lo_cycles)} "
          f"ordered_acquires={lo['ordered_acquires']:g}")
    if lo["edges"] == 0:
        # the arm-check edge alone guarantees >= 1: zero means the
        # witness machinery itself stopped recording mid-run
        print("soak: lock-order witness recorded no edges — witness "
              "disarmed (even the arm-check edge is gone)")
        ok = False
    if lo_cycles:
        import json as _json
        print("soak: LOCK-ORDER CYCLES (potential deadlock):")
        for cyc in contention.lockorder_detail()["cycles"]:
            print(_json.dumps(cyc, indent=1))
        ok = False
    if client is not None:
        api_ranked = any(n == "api_server" for n, _, _ in top3)
        print(f"soak: api_server in contention top-3: "
              f"{'YES' if api_ranked else 'no'} "
              f"(bulk_ops={api_server.bulk_ops}, "
              f"watch_drops={api_server.stats()['watch_drops']}, "
              f"bookmarks={api_server.bookmarks_sent}, "
              f"fanout_copies={api_server.fanout_envelope_copies})")
        api_doc = contention.detail()["locks"].get("api_server", {})
        print(f"soak: api_server owners-at-contention = "
              f"{api_doc.get('ownersAtContention', {})} "
              f"(contended {api_doc.get('contended', 0)}, "
              f"maxWaitMs {api_doc.get('maxWaitMs', 0)}, "
              f"maxHoldMs {api_doc.get('maxHoldMs', 0)})")
    # the per-pass reason-code histogram (solver/explain.py; the
    # "explain" provider's reason_* counters ride every monitor sample,
    # so the artifact embeds the full time series) — the exit report
    # prints the final tally so a weather run's pending pods are
    # attributable at a glance
    ex_stats = op.provisioner.explain.stats()
    reasons = {k[len("reason_"):].replace("_", "-"): v
               for k, v in ex_stats.items()
               if k.startswith("reason_") and v > 0}
    print(f"soak: explain passes={ex_stats.get('passes', 0):g} "
          f"reason histogram: "
          + (" ".join(f"{k}={v:g}" for k, v in sorted(reasons.items()))
             or "(no unschedulable pods)"))
    # the mesh verdict (docs/reference/sharding.md): with a mesh
    # requested, sharded solves must actually have carried passes — a
    # planner silently falling back to single-device must not read as a
    # survived mesh soak
    sst = op.solver.stats()
    print(f"soak: mesh devices={sst.get('mesh_devices', 1):g} "
          f"sharded_solves={sst.get('mesh_solves', 0):g} "
          f"imbalance={sst.get('mesh_shard_imbalance', 0.0):g}")
    # same normalization as plan_mesh: only a FORCING spec arms the
    # gate — "auto" legitimately plans single-device on the cpu backend
    mesh_spec = (args.mesh or "").strip().lower()
    if mesh_spec and mesh_spec not in ("auto", "off", "none", "single", "1"):
        if sst.get("mesh_devices", 1) <= 1 or sst.get("mesh_solves", 0) == 0:
            print(f"soak: --mesh {args.mesh} requested but the sharded "
                  "path never carried a pass (mesh_devices="
                  f"{sst.get('mesh_devices')}, "
                  f"mesh_solves={sst.get('mesh_solves')})")
            ok = False
    if args.warm_start:
        peak = summ.get("peak_latency_burn", 0.0) or 0.0
        if peak >= 2.0:
            # the satellite's regression bar: with AOT warmup active a
            # cold-compile first pass must not read as an SLO burn spike
            # (SOAK_r06 recorded ~8 without it)
            print(f"soak: --warm-start set but peak latency burn {peak} "
                  ">= 2.0 (cold-compile spike leaked into the SLO window)")
            ok = False
    if args.out:
        monitor.write(args.out)
        print(f"soak: time series -> {args.out} "
              f"({len(monitor.samples)} samples, "
              f"peak_nodes={summ.get('peak_nodes')}, "
              f"peak_cost/hr={summ.get('peak_cost_per_hour')}, "
              f"peak_latency_burn={summ.get('peak_latency_burn')})")
    if weather_doc is not None:
        import gzip
        import json
        weather_doc["invariants_ok"] = ok
        wout = args.weather_out or \
            f"WEATHER_{weather_sim.scenario.name.replace('-', '_')}.json.gz"
        if wout.endswith(".gz"):
            with gzip.open(wout, "wt") as f:
                json.dump(weather_doc, f, separators=(",", ":"))
        else:
            with open(wout, "w") as f:
                json.dump(weather_doc, f, indent=1)
        print(f"soak: weather artifact -> {wout} "
              f"({len(weather_doc['timeline'])} timeline events, "
              f"{len(weather_doc['burn_series'])} burn samples)")
    # ---- the saturation verdict (docs/reference/headroom.md) ----------
    # Gated on EVERY soak: the final first-to-break table prints, and
    # any queue-kind resource whose monotonic high water reached its
    # capacity must be EXPLAINED — by the weather scenario or by a
    # deliberately tightened --api-watch-queue-bound — or the run
    # fails. "The bound worked, silently" is exactly the failure mode
    # the observatory exists to end.
    hr_rows = op.headroom.table()
    hr_sum = op.headroom.stats()
    print(f"soak: headroom first-to-break table (top 5 of "
          f"{len(hr_rows)}):")
    for row in hr_rows[:5]:
        tte = row["seconds_to_exhaustion"]
        print(f"soak:   {row['resource']:<26} {row['kind']:<5} "
              f"depth={row['depth']:g}/{row['capacity']:g} "
              f"hw={row['highwater']:g} drops={row['drops']:g} "
              f"occ={row['occupancy']:.2f} "
              f"tte={'-' if tte is None else format(tte, '.1f') + 's'}")
    print(f"soak: headroom saturated={hr_sum['saturated']:g} "
          f"episodes={hr_sum['episodes']:g} "
          f"probe_errors={hr_sum['probe_errors']:g} "
          f"first_to_break={hr_sum['first_to_break'] or '(none)'}")
    unexplained = [
        row["resource"] for row in hr_rows
        if row["kind"] == "queue" and row["capacity"] > 0
        and row["highwater"] >= row["capacity"]
        and not (weather_sim is not None
                 or (row["resource"] == "api_watch_queues"
                     and args.api_watch_queue_bound))]
    if unexplained:
        print("soak: UNEXPLAINED SATURATION — queue-kind resources hit "
              "their bound with no weather scenario or deliberately "
              f"tightened bound to blame: {unexplained}")
        ok = False
    # the prediction-before-overflow gate (armed by the tightened
    # bound): in the monitor's per-sample headroom trajectory, the
    # first sample ranking api_watch_queues first-to-break must
    # PRECEDE the first sample showing a drop — and both must exist,
    # or the drill was vacuous
    hr_t0 = monitor.samples[0]["t"] if monitor.samples else 0.0
    first_rank_t = first_drop_t = None
    for s in monitor.samples:
        h = s.get("subsystems", {}).get("headroom", {})
        if not h:
            continue
        if first_rank_t is None and \
                h.get("first_to_break") == "api_watch_queues":
            first_rank_t = round(s["t"] - hr_t0, 1)
        if first_drop_t is None and \
                h.get("api_watch_queues_drops", 0.0) > 0:
            first_drop_t = round(s["t"] - hr_t0, 1)
    if args.api_watch_queue_bound:
        if first_drop_t is None:
            print("soak: --api-watch-queue-bound set but the idle "
                  "watcher never overflowed (vacuous prediction drill "
                  "— bound too loose for this churn rate)")
            ok = False
        elif first_rank_t is None or first_rank_t >= first_drop_t:
            print("soak: the forecaster never ranked api_watch_queues "
                  "first-to-break BEFORE its first overflow (ranked_at="
                  f"{first_rank_t} first_drop={first_drop_t}) — the "
                  "observatory narrated the break instead of "
                  "predicting it")
            ok = False
        else:
            print(f"soak: headroom forecast led the first overflow by "
                  f"{first_drop_t - first_rank_t:.1f}s "
                  f"(ranked at t={first_rank_t}s, first drop at "
                  f"t={first_drop_t}s)")
    if args.headroom_out:
        import gzip as _gzip
        import json as _json
        hfields = ["t", "min_tte_seconds", "saturated", "episodes",
                   "probe_errors", "first_to_break",
                   "api_watch_queues_depth", "api_watch_queues_occ",
                   "api_watch_queues_drops"]
        head_series = [
            [round(s["t"] - hr_t0, 1)] + [
                h.get(k, 0.0) for k in hfields[1:]]
            for s in monitor.samples
            for h in [s.get("subsystems", {}).get("headroom", {})]
            if h]
        head_doc = {
            "final_table": hr_rows,
            "summary": hr_sum,
            "series_fields": hfields,
            "series": head_series,
            "watch_queue_bound": args.api_watch_queue_bound or None,
            "forecast_ranked_at_s": first_rank_t,
            "first_overflow_at_s": first_drop_t,
            "forecast_lead_s": (round(first_drop_t - first_rank_t, 1)
                                if first_rank_t is not None
                                and first_drop_t is not None else None),
            "unexplained_saturation": unexplained,
            "weather": (weather_sim.scenario.name
                        if weather_sim is not None else None),
            "soak": {"pods_churned": i, "minutes": args.minutes,
                     "seed": args.seed, "api_mode": bool(args.api_mode),
                     "watchers": args.watchers,
                     "churn_scale": args.churn_scale},
            "invariants_ok": ok,
        }
        if args.headroom_out.endswith(".gz"):
            with _gzip.open(args.headroom_out, "wt") as f:
                _json.dump(head_doc, f, separators=(",", ":"))
        else:
            with open(args.headroom_out, "w") as f:
                _json.dump(head_doc, f, indent=1)
        print(f"soak: headroom artifact -> {args.headroom_out} "
              f"({len(head_series)} trajectory samples)")
    if args.consol_out:
        # the CONSOLIDATION verdict (docs/reference/consolidation.md
        # "Gates"): the vmapped engine must demonstrably have carried
        # the run's consolidation — each bar names the machinery it
        # proves, so a quietly-dead engine can't ride a green soak
        eng = op.disruption.engine
        cst = eng.stats()
        cb = monitor.samples[0]["t"] if monitor.samples else 0.0
        consol_series = [
            [round(s["t"] - cb, 1)] + [
                s["subsystems"]["consolidation"].get(k, 0.0)
                for k in ("savings_per_hour", "nodes_consolidated",
                          "vmapped_whatifs", "batched_candidates",
                          "fp_unchanged", "host_fallbacks",
                          "weather_holds")]
            for s in monitor.samples
            if "consolidation" in s.get("subsystems", {})]
        print(f"soak: consolidation accepted={cst.get('accepted', 0):g} "
              f"nodes={cst.get('nodes_consolidated', 0):g} "
              f"savings/hr=${cst.get('savings_per_hour', 0.0):.4f} "
              f"dispatches={cst.get('vmapped_whatifs', 0):g} "
              f"({cst.get('batched_candidates', 0):g} sets) "
              f"cached={cst.get('fp_unchanged', 0):g} "
              f"host={cst.get('host_fallbacks', 0):g} "
              f"referee={cst.get('referee_checks', 0):g}/"
              f"{cst.get('referee_rejects', 0):g} rejects")
        if cst.get("accepted", 0) == 0:
            print("soak: --consol-out set but the engine never accepted "
                  "a removal (no savings recorded)")
            ok = False
        if cst.get("vmapped_whatifs", 0) == 0:
            print("soak: --consol-out set but no batched device "
                  "dispatch ever ran")
            ok = False
        elif cst.get("batched_candidates", 0) <= \
                cst.get("vmapped_whatifs", 0):
            print("soak: dispatches averaged <=1 candidate set — the "
                  "candidate axis never actually batched")
            ok = False
        if cst.get("fp_unchanged", 0) == 0:
            print("soak: the zero-leg probe cache never served a "
                  "fingerprint-unchanged candidate")
            ok = False
        if cst.get("referee_checks", 0) < cst.get("accepted", 0):
            print("soak: fewer referee checks than accepted removals — "
                  "an accept bypassed the host FFD envelope")
            ok = False
        import gzip as _gzip
        import json as _json
        consol_doc = {
            "engine": cst,
            "series_fields": ["t", "savings_per_hour",
                              "nodes_consolidated", "vmapped_whatifs",
                              "batched_candidates", "fp_unchanged",
                              "host_fallbacks", "weather_holds"],
            "series": consol_series,
            "slo": slo,
            "referee_envelope": 0.02,
            "weather": (weather_sim.scenario.name
                        if weather_sim is not None else None),
            "replay_identical": (bool(weather_doc["replay_match"])
                                 if weather_doc is not None else None),
            "soak": {"pods_churned": i, "minutes": args.minutes,
                     "seed": args.seed,
                     "consolidate_after": consolidate_after,
                     "churn_scale": args.churn_scale},
            "invariants_ok": ok,
        }
        if args.consol_out.endswith(".gz"):
            with _gzip.open(args.consol_out, "wt") as f:
                _json.dump(consol_doc, f, separators=(",", ":"))
        else:
            with open(args.consol_out, "w") as f:
                _json.dump(consol_doc, f, indent=1)
        print(f"soak: consolidation artifact -> {args.consol_out} "
              f"({len(consol_series)} trajectory samples)")
    if chaos_sidecars:
        pst = op.solver.pool_stats()
        print(f"soak: pool exit state endpoints={pst['endpoints']} "
              f"healthy={pst['healthy']} failovers={pst['failovers']} "
              f"delegated={pst['delegated_solves']} "
              f"local={pst['local_solves']}")
        op.solver.close()
        for sc_h in chaos_sidecars:
            sc_h.kill()
    print("soak: INVARIANTS " + ("OK" if ok else "VIOLATED"))
    if not ok:
        print(dump_state(op))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
