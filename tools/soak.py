"""soak — wall-clock chaos soak of the threaded control plane.

The committed analog of the reference's long-running e2e chaos suite
(test/suites/chaos + the scale deprovisioning matrix run against a real
cluster for hours): every controller on its own thread
(operator/runtime.ControllerRuntime), real time, and a churn driver that
injects the full fault surface — pod waves, heavy deletion (consolidation
pressure), spot interruption messages, transient API errors, and ICE'd
capacity pools.

Exit criteria (after churn stops, the control plane must converge):
- zero pending pods,
- zero leaked instances (checked AFTER the GC grace window — an instance
  the GC hasn't been entitled to reap yet is not a leak),
- zero orphaned node leases.

Usage: python tools/soak.py [--minutes 5] [--seed 0] [--out soak_timeseries.json.gz]
Exits non-zero if any invariant fails (and prints a full control-plane
dump). A 6-minute run churns ~20k pods. The run records a time-series
artifact (pending/nodes/claims/cost per second — the reference's
monitor.go + Timestream metrics-pipeline analog, debug.Monitor).

``--fault-schedule`` drives the SOLVER degradation ladder
(docs/concepts/degradation.md) mid-soak, on top of the cloud chaos:
a comma-separated list of ``SECONDS:ACTION`` entries applied once the
run clock passes each mark. Actions: ``device-error[=N]`` (inject N
device failures, default 3), ``g-limit=N`` (fake group-bucket ceiling
→ wave-split), ``b-limit=N`` (fake bin-table ceiling → host-FFD
fallback), ``clear`` (drop all injected ceilings). Example:
``--fault-schedule 30:device-error,60:g-limit=64,120:clear``. Faults
are always cleared before convergence, and the run prints the
solver's degraded counters so a soak can assert the ladder actually
fired.

``--pipeline`` exercises the overlapped solve path
(docs/concepts/performance.md "Pipelining & the tunnel link") under the
same sustained churn: the pipelined path is forced on, and the run
FAILS unless it actually engaged — the solver's async-dispatch counter
and the resident-input cache's hit/shipped counters are printed and
asserted non-vacuous, so "pipelined soak passed" can never mean "soak
quietly ran sequential".
"""

from __future__ import annotations

import argparse
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.controllers.garbagecollection import LEAK_GRACE_SECONDS
from karpenter_provider_aws_tpu.errors import NotFoundError
from karpenter_provider_aws_tpu.interruption.messages import spot_interruption
from karpenter_provider_aws_tpu.interruption.queue import FakeQueue
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.operator.runtime import (ControllerRuntime,
                                                         operator_specs)
from karpenter_provider_aws_tpu.solver import FaultInjector


def parse_fault_schedule(spec: str):
    """'30:device-error,60:g-limit=64' → sorted [(30.0, 'device-error',
    None), (60.0, 'g-limit', 64)]."""
    out = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        at, _, action = entry.partition(":")
        if not _:
            raise SystemExit(f"fault entry {entry!r}: want SECONDS:ACTION")
        name, _, val = action.partition("=")
        name = name.strip()
        if name not in ("device-error", "g-limit", "b-limit", "clear"):
            raise SystemExit(f"unknown fault action {name!r}")
        if name in ("g-limit", "b-limit") and not val:
            raise SystemExit(f"fault action {name} needs =N")
        out.append((float(at), name, int(val) if val else None))
    return sorted(out)


def apply_fault(solver, name: str, val):
    """Apply one schedule entry to the solver's (possibly new) injector.
    Mutations take the injector's own lock: the operator thread is
    consuming device_errors concurrently via take_device_error."""
    if name == "clear":
        solver.inject_faults(None)
        return
    inj = solver.faults or FaultInjector()
    with inj._lock:
        if name == "device-error":
            inj.device_errors += val if val is not None else 3
        elif name == "g-limit":
            inj.g_limit = val
        elif name == "b-limit":
            inj.b_limit = val
    solver.inject_faults(inj)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=5.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--families", default="m5,c5,r5,t3")
    ap.add_argument("--out", default="soak_timeseries.json.gz",
                    help="time-series artifact path ('' disables; a .gz "
                         "suffix gzips — SOAK_r06-scale runs are ~18k "
                         "lines plain; debug.load_timeseries reads both)")
    ap.add_argument("--api-mode", action="store_true",
                    help="drive ALL churn through the fake apiserver "
                         "(watch/list protocol + ApiWriter controllers); "
                         "adds a server-vs-mirror agreement invariant")
    ap.add_argument("--fault-schedule", default="",
                    help="SECONDS:ACTION[,...] solver fault injections "
                         "(device-error[=N], g-limit=N, b-limit=N, clear)")
    ap.add_argument("--compile-cache-dir", default="",
                    help="persistent XLA compile cache directory "
                         "(solver/solve.py enable_persistent_compile_cache)"
                         ": a SECOND soak boot against the same dir pays "
                         "no fresh compile — the cold-start burn-spike "
                         "acceptance evidence")
    ap.add_argument("--warm-start", action="store_true",
                    help="AOT-compile the warm bucket ladder on a "
                         "background thread at boot and hold the SLO "
                         "warmup window open until it finishes — the "
                         "cold-compile first pass then cannot spike the "
                         "latency burn (peak burn printed at exit)")
    ap.add_argument("--pipeline", action="store_true",
                    help="exercise the overlapped solve path "
                         "(docs/concepts/performance.md 'Pipelining & the "
                         "tunnel link') under sustained load: force the "
                         "pipelined path on and FAIL the soak if it never "
                         "engaged (async solves / resident-cache counters)")
    args = ap.parse_args(argv)
    fault_schedule = parse_fault_schedule(args.fault_schedule)

    fams = tuple(args.families.split(","))
    lattice = build_lattice([s for s in build_catalog() if s.family in fams])
    q = FakeQueue("soak-q")
    api_server = client = None
    if args.api_mode:
        from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
        from karpenter_provider_aws_tpu.kube.apiserver import NotFoundError as KubeNotFound
        api_server = FakeAPIServer()
        client = KubeClient(api_server)
    op = Operator(options=Options(registration_delay=0.2,
                                  batch_idle_duration=0.05,
                                  batch_max_duration=0.5,
                                  interruption_queue="soak-q",
                                  compile_cache_dir=args.compile_cache_dir),
                  lattice=lattice, interruption_queue=q,
                  api_server=api_server)
    if args.pipeline:
        op.solver.set_pipeline(True)
    if args.warm_start:
        # the SLO warmup window stays open until the AOT ladder lands:
        # cold-compile passes are boot cost, not burn signal
        op.slo.begin_warmup()
        op.solver.warmup(node_pools_count=len(op.node_pools),
                         background=True,
                         aot=bool(args.compile_cache_dir),
                         on_done=op.slo.end_warmup)
    rt = ControllerRuntime(operator_specs(op)).start()
    from karpenter_provider_aws_tpu.debug import Monitor, dump_state
    monitor = Monitor(op).start(interval=1.0)
    rng = random.Random(args.seed)
    t_start = time.monotonic()
    stop = t_start + args.minutes * 60.0
    i = 0
    pending_faults = list(fault_schedule)

    def safe_instances():
        try:
            return op.cloud.list_instances()
        except Exception:
            return []

    try:
        while time.monotonic() < stop:
            while pending_faults and \
                    time.monotonic() - t_start >= pending_faults[0][0]:
                _, fname, fval = pending_faults.pop(0)
                apply_fault(op.solver, fname, fval)
                print(f"soak: fault applied {fname}"
                      f"{'' if fval is None else '=' + str(fval)}")
            r = rng.random()
            if r < 0.5:
                for _ in range(rng.randint(1, 15)):
                    i += 1
                    pod = Pod(
                        name=f"s{i}",
                        requests={"cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                                  "memory": f"{rng.choice([512, 1024, 2048])}Mi"})
                    if client is not None:
                        client.create_pod(pod)   # through the protocol
                    else:
                        op.cluster.add_pod(pod)
            elif r < 0.8:
                # heavy deletion waves -> underutilized nodes -> consolidation
                names = list(op.cluster.pods)
                for name in rng.sample(names,
                                       min(len(names), rng.randint(5, 30))):
                    if client is not None:
                        try:
                            client.delete_pod(name)
                        except KubeNotFound:
                            pass   # raced a controller's teardown
                    else:
                        op.cluster.delete_pod(name)
            elif r < 0.88:
                insts = safe_instances()
                if insts:
                    q.send(spot_interruption(rng.choice(insts).id))
            elif r < 0.91:
                # drift churn: rev the pool template; the drift
                # controller must roll stale-hash nodes while the rest
                # of the storm rages (API mode: server-side, so the
                # config watch delivers it like any operator would)
                pool = op.node_pools.get("default")
                if pool is not None:
                    pool.labels["soak/rev"] = f"r{i}"
                    if client is not None:
                        client.update_nodepool(pool)
            elif r < 0.94:
                op.cloud.inject_error(NotFoundError("soak-chaos"))
            else:
                insts = safe_instances()
                if insts:
                    v = rng.choice(insts)
                    op.cloud.set_capacity(v.capacity_type, v.instance_type,
                                          v.zone, 0)
            time.sleep(rng.uniform(0.01, 0.08))
    finally:
        # a controller blocked mid-pass can outlive the join timeout;
        # invariants must never be read over live mutation
        while not rt.stop():
            print("soak: waiting for a blocked controller thread...")
        monitor.stop()

    # converge: clear injected faults (all controller threads have joined,
    # so plain writes are race-free here), then let the single-threaded
    # loop settle PAST the GC grace window so every reapable leak is reaped
    op.cloud.next_error = None
    op.cloud.capacity_pools.clear()
    solver_fired = dict(op.solver.faults.fired) if op.solver.faults else {}
    op.solver.inject_faults(None)
    deadline = time.monotonic() + LEAK_GRACE_SECONDS + 15.0
    ticks = 0
    while time.monotonic() < deadline:
        op.run_once()
        ticks += 1
        if ticks % 20 == 0:
            monitor.sample()   # the convergence tail rides the series too
        if not op.cluster.pending_pods() \
                and time.monotonic() > deadline - 10.0:
            break
        time.sleep(0.05)
    monitor.sample()

    pending = op.cluster.pending_pods()
    claimed = {c.provider_id for c in op.cluster.claims.values()
               if c.provider_id}
    leaked = [x for x in op.cloud.list_instances()
              if x.provider_id not in claimed]
    orphans = op.cluster.orphaned_leases()
    print(f"soak: pods_churned={i} pending={len(pending)} "
          f"nodes={len(op.cluster.nodes)} claims={len(op.cluster.claims)} "
          f"leaked={len(leaked)} orphan_leases={len(orphans)}")
    if fault_schedule:
        print(f"soak: solver degraded_counts={op.solver.degraded_counts} "
              f"faults_fired={solver_fired}")
    ok = not pending and not leaked and not orphans
    if args.pipeline:
        # the overlapped path must have actually carried the soak's
        # solves — a flag that silently fell back to sequential would
        # report a vacuous pass
        pstats = dict(op.solver.pipeline_stats)
        cstats = op.solver._resident.stats()
        print(f"soak: pipeline stats={pstats} resident_cache={cstats}")
        if pstats.get("async_solves", 0) == 0:
            print("soak: --pipeline set but no solve took the "
                  "overlapped path")
            ok = False
    if fault_schedule and not (op.solver.degraded_counts or solver_fired):
        # a schedule that never fired means the soak did not exercise the
        # ladder it promised to — fail loudly rather than report a
        # vacuous pass
        print("soak: fault schedule applied but solver never degraded")
        ok = False
    if client is not None:
        # server-vs-mirror agreement: after convergence the watch-fed
        # mirror and the apiserver's truth must be identical sets
        op.sync_once()
        server_pods = {p.name for p in client.list_pods()}
        server_nodes = {n.name for n in client.list_nodes()}
        agree = (server_pods == set(op.cluster.pods)
                 and server_nodes == set(op.cluster.nodes))
        print(f"soak: server-vs-mirror agreement "
              f"{'OK' if agree else 'VIOLATED'} "
              f"(pods {len(server_pods)}, nodes {len(server_nodes)})")
        ok = ok and agree
    # the SLO burn verdict over the whole run (introspect/slo.py — the
    # same gauges /metrics exports and the Monitor artifact carries)
    slo = op.slo.update()
    print(f"soak: slo latency_burn={slo['latency_burn']} "
          f"(p50 {slo['latency_p50_ms']}ms / 200ms) "
          f"cost_burn={slo['cost_burn']} "
          f"(ratio_p50 {slo['cost_ratio_p50']})")
    # ONE summary pass serves every exit print below (summary() rescans
    # all retained samples, including the per-sample contention sweep)
    summ = monitor.summary()
    print(f"soak: incremental builds="
          f"{op.provisioner.inc_builder.incremental_builds} "
          f"full={op.provisioner.inc_builder.full_builds} "
          f"delta_solves={op.solver.pipeline_stats['delta_solves']} "
          f"peak_latency_burn={summ.get('peak_latency_burn')}")
    if "peak_lock_wait_ms" in summ:
        print(f"soak: peak lock wait {summ['peak_lock_wait_ms']}ms "
              f"({summ.get('peak_lock_wait_lock')}) "
              f"burn_captures={op.burn_capture.stats().get('total', 0)}")
    if args.warm_start:
        peak = summ.get("peak_latency_burn", 0.0) or 0.0
        if peak >= 2.0:
            # the satellite's regression bar: with AOT warmup active a
            # cold-compile first pass must not read as an SLO burn spike
            # (SOAK_r06 recorded ~8 without it)
            print(f"soak: --warm-start set but peak latency burn {peak} "
                  ">= 2.0 (cold-compile spike leaked into the SLO window)")
            ok = False
    if args.out:
        monitor.write(args.out)
        print(f"soak: time series -> {args.out} "
              f"({len(monitor.samples)} samples, "
              f"peak_nodes={summ.get('peak_nodes')}, "
              f"peak_cost/hr={summ.get('peak_cost_per_hour')}, "
              f"peak_latency_burn={summ.get('peak_latency_burn')})")
    print("soak: INVARIANTS " + ("OK" if ok else "VIOLATED"))
    if not ok:
        print(dump_state(op))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
