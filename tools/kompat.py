"""kompat — Kubernetes compatibility-matrix tool.

Analog of the reference's ``tools/kompat`` CLI (reference
tools/kompat/README.md): reads a ``compatibility.yaml`` holding rows of
``{appVersion, minK8sVersion, maxK8sVersion}``, renders the matrix as
markdown (the docs generator embeds it), validates it, and answers "is
app version X compatible with control-plane version Y" — the same check
an operator runs before an upgrade.

Usage:
  python tools/kompat.py [matrix.yaml] [-n LAST_N]          # render
  python tools/kompat.py [matrix.yaml] validate             # lint ranges
  python tools/kompat.py [matrix.yaml] check APP_VER K8S_VER
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEFAULT_MATRIX = Path(__file__).resolve().parent.parent / "deploy" / "compatibility.yaml"


@dataclass
class Row:
    app_version: str
    min_k8s: Tuple[int, int]
    max_k8s: Tuple[int, int]


def _parse_minor(v: str) -> Tuple[int, int]:
    """'1.27' → (1, 27); tolerates a patch suffix ('1.27.3' → (1, 27))."""
    parts = str(v).split(".")
    if len(parts) < 2:
        raise ValueError(f"not a <major>.<minor> version: {v!r}")
    return int(parts[0]), int(parts[1])


def load_matrix(path: Path = DEFAULT_MATRIX) -> Tuple[str, List[Row]]:
    import yaml
    doc = yaml.safe_load(Path(path).read_text())
    rows = [Row(app_version=str(r["appVersion"]),
                min_k8s=_parse_minor(r["minK8sVersion"]),
                max_k8s=_parse_minor(r["maxK8sVersion"]))
            for r in doc.get("compatibility", ())]
    return str(doc.get("name", "unknown")), rows


def validate(rows: List[Row]) -> List[str]:
    """Lints mirroring kompat's: non-empty, min<=max per row, and ranges
    non-regressing as app versions advance (a newer app line must not
    support an OLDER minimum-max than its predecessor dropped)."""
    errs = []
    if not rows:
        errs.append("matrix has no compatibility rows")
    for r in rows:
        if r.min_k8s > r.max_k8s:
            errs.append(f"{r.app_version}: minK8sVersion {r.min_k8s} > "
                        f"maxK8sVersion {r.max_k8s}")
    for prev, cur in zip(rows, rows[1:]):
        if cur.max_k8s < prev.max_k8s:
            errs.append(f"{cur.app_version}: maxK8sVersion regressed vs "
                        f"{prev.app_version}")
    return errs


def _matches(pattern: str, version: str) -> bool:
    """appVersion patterns use a '.x' wildcard tail ('0.1.x')."""
    p = pattern.split(".")
    v = str(version).split(".")
    for i, part in enumerate(p):
        if part == "x":
            return True
        if i >= len(v) or part != v[i]:
            return False
    return len(v) == len(p)


def check(rows: List[Row], app_version: str, k8s_version: str) -> Optional[Row]:
    """The row proving compatibility, or None."""
    k = _parse_minor(k8s_version)
    for r in rows:
        if _matches(r.app_version, app_version) and r.min_k8s <= k <= r.max_k8s:
            return r
    return None


def render(name: str, rows: List[Row], last_n: Optional[int] = None) -> str:
    """The kompat markdown matrix: one column per app version, the
    supported k8s range beneath."""
    shown = rows[-last_n:] if last_n else rows
    head = [name.upper()] + [r.app_version for r in shown]
    vals = ["Kubernetes"] + [
        f"{r.min_k8s[0]}.{r.min_k8s[1]} - {r.max_k8s[0]}.{r.max_k8s[1]}"
        for r in shown]
    w = [max(len(a), len(b)) for a, b in zip(head, vals)]
    line = lambda cells: "| " + " | ".join(c.ljust(n) for c, n in zip(cells, w)) + " |"
    sep = "|-" + "-|-".join("-" * n for n in w) + "-|"
    return "\n".join([line(head), sep, line(vals)])


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    path = DEFAULT_MATRIX
    if args and args[0].endswith((".yaml", ".yml")):
        path = Path(args.pop(0))
    name, rows = load_matrix(path)

    if args and args[0] == "validate":
        errs = validate(rows)
        for e in errs:
            print(f"error: {e}", file=sys.stderr)
        print(f"{name}: {len(rows)} rows, "
              f"{'INVALID' if errs else 'valid'}")
        return 1 if errs else 0

    if args and args[0] == "check":
        if len(args) != 3:
            print("usage: kompat.py [matrix] check APP_VER K8S_VER",
                  file=sys.stderr)
            return 2
        row = check(rows, args[1], args[2])
        if row is None:
            print(f"{name} {args[1]} is NOT compatible with "
                  f"Kubernetes {args[2]}")
            return 1
        print(f"{name} {args[1]} is compatible with Kubernetes {args[2]} "
              f"(row {row.app_version}: {row.min_k8s[0]}.{row.min_k8s[1]} - "
              f"{row.max_k8s[0]}.{row.max_k8s[1]})")
        return 0

    last_n = None
    if len(args) >= 2 and args[0] == "-n":
        last_n = int(args[1])
    print(render(name, rows, last_n))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
