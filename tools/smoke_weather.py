#!/usr/bin/env python
"""CI smoke for the adversarial weather suite (ci.sh weather gate).

Runs the ``squall`` scenario (weather/scenario.py) for its scripted 60
seconds on FakeClock against a real Operator — the deterministic twin of
``tools/soak.py --weather squall`` — and asserts the four things the
chaos suite exists to prove (docs/reference/weather.md):

1. the degradation ladder ENGAGED under device weather
   (``sum(Solver.degraded_counts) > 0`` — a storm that never forced a
   rung off the primary path would be a vacuous pass),
2. the control plane RECOVERED: after the front passes, the rolling SLO
   window drains the storm-era samples and the latency burn reads
   < 1.0 again, the queue is empty, every pod is scheduled, and no
   instance leaked,
3. interruption robustness held: every storm message (all four
   EventBridge schemas plus junk) was counted and dropped —
   ``handler_errors == 0`` and queue depth 0,
4. the weather was REPLAYABLE: a second no-op derivation from the same
   (scenario, seed, ticks) produces the byte-identical event timeline.

Fast by design: small-family lattice, ~2 pods/tick churn — under two
minutes on the CPU backend including compiles.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.interruption.queue import FakeQueue
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock
    from karpenter_provider_aws_tpu.weather import WeatherSimulator, named

    failures = []
    # arm-check the lock-order witness before the run (the soak does the
    # same): the production locks are deliberately flat, so "0 cycles"
    # from an empty graph would be ambiguous between "no deadlock" and
    # "witness never armed"
    from karpenter_provider_aws_tpu.introspect import contention
    with contention.lock("smoke_witness_outer"):
        with contention.lock("smoke_witness_inner"):
            pass
    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    queue = FakeQueue("weather-smoke")
    op = Operator(options=Options(registration_delay=0.5),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                  interruption_queue=queue)
    scenario = named("squall")
    sim = WeatherSimulator(scenario, lattice, clock=clock,
                           pricing=op.pricing_provider, cloud=op.cloud,
                           unavailable=op.unavailable, queue=queue,
                           solver=op.solver, metrics=op.metrics).start()
    price_v0 = lattice.price_version

    # the scripted 60 s: sustained pod churn while the squall passes over
    serial = 0
    for _ in range(int(scenario.duration_seconds / scenario.tick_seconds)):
        for _ in range(2):
            serial += 1
            op.cluster.add_pod(Pod(name=f"w{serial}",
                                   requests={"cpu": "500m",
                                             "memory": "1Gi"}))
        op.run_once(force_provision=True)
        clock.step(scenario.tick_seconds)
        sim.advance()
    storm_ticks = sim.ticks

    if lattice.price_version == price_v0:
        failures.append("weather never repriced the lattice "
                        "(price_version unchanged)")
    degraded_total = sum(op.solver.degraded_counts.values())
    if degraded_total == 0:
        failures.append("degradation ladder never engaged "
                        f"(degraded_counts={op.solver.degraded_counts})")
    wstats = sim.stats()
    if wstats["messages_sent"] == 0 or wstats["junk_sent"] == 0:
        failures.append(f"storm sent no messages ({wstats})")

    # the front passes: fair weather + convergence. Step the clock well
    # past the SLO window so storm-era latency samples age out and the
    # burn reading is about the recovered steady state.
    sim.stop()
    op.solver.inject_faults(None)
    for r in range(40):
        if r % 4 == 0:
            # keep real (fast, un-faulted) passes landing in the SLO
            # window so "recovered" is a measured p50, not an empty ring
            serial += 1
            op.cluster.add_pod(Pod(name=f"w{serial}",
                                   requests={"cpu": "250m",
                                             "memory": "512Mi"}))
        op.run_once(force_provision=True)
        clock.step(10.0)
    slo = op.slo.update()
    if slo["latency_p50_ms"] <= 0.0:
        failures.append("recovery window recorded no latency samples "
                        "(vacuous recovery check)")
    if slo["latency_burn"] >= 1.0:
        failures.append(f"latency burn did not recover after the storm "
                        f"(burn={slo['latency_burn']})")
    if slo["cost_burn"] > 1.0:
        failures.append(f"cost burn {slo['cost_burn']} > 1.0 "
                        "(>2% vs FFD referee)")
    if op.cluster.pending_pods():
        failures.append(f"{len(op.cluster.pending_pods())} pods still "
                        "pending after recovery")
    if len(queue) != 0:
        failures.append(f"{len(queue)} interruption messages stranded")
    intr = op.interruption.stats()
    if intr.get("handler_errors", 0) != 0:
        failures.append(f"interruption handler errors: {intr}")
    if intr.get("received_malformed", 0) == 0:
        failures.append("junk bodies were sent but none counted malformed")
    claimed = {c.provider_id for c in op.cluster.claims.values()
               if c.provider_id}
    leaked = [x for x in op.cloud.list_instances()
              if x.provider_id not in claimed]
    if leaked:
        failures.append(f"{len(leaked)} instances leaked")

    # replay determinism: the recorded timeline must re-derive
    # byte-identically from (scenario, seed, ticks) alone
    replay = WeatherSimulator.replay(scenario, lattice, storm_ticks,
                                     seed=sim.seed)
    if replay != sim.timeline:
        failures.append("same-seed replay diverged from the recorded "
                        "timeline")

    # the lock-order witness must be armed (>= the arm-check edge) and
    # cycle-free at exit (introspect/contention.py; docs/reference/
    # linting.md) — a cycle found even on this single-threaded
    # deterministic run is a deadlock two threads can complete in
    # production
    lo_cycles = contention.lockorder_cycles()
    lo_edges = contention.lockorder_stats()["edges"]
    if lo_edges < 1:
        failures.append("lock-order witness lost even its arm-check edge "
                        "(witness disarmed mid-run?)")
    if lo_cycles:
        failures.append(f"lock-order witness found cycles: {lo_cycles} "
                        "(see /debug/pprof/lockorder)")

    if failures:
        print("weather smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"weather smoke: OK (ticks={storm_ticks}, "
          f"timeline={len(sim.timeline)} events, "
          f"degraded_total={degraded_total}, "
          f"messages={wstats['messages_sent']} "
          f"(junk {wstats['junk_sent']}), "
          f"recovered latency_burn={slo['latency_burn']}, "
          f"lockorder {lo_edges:g} edges / 0 cycles, "
          f"replay identical)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
