#!/usr/bin/env python
"""CI smoke for zero-downtime operator handoff (ci.sh handoff gate).

Two REAL OS processes on a shared tmpdir: a leader operator churning
pods behind a FileLeaseStore lease + replication stream (unix socket),
and a warm standby applying snapshot + journal deltas into its own
mirror while pre-building through IncrementalProblemBuilder. The parent
SIGKILLs the leader mid-churn (kill -9: no lease release, no goodbye)
and asserts the things the handoff subsystem exists to prove:

1. the standby streams BEFORE the kill: snapshot applied, deltas > 0,
   prebuilds > 0 — and it is NOT leader (the lease holds it out),
2. after the kill the standby PROMOTES within the lease window (+ slack)
   with a rotated fence token, and CARRIES passes: provisioner passes
   grow, new pods get capacity (create_claim > 0), and the delta solve
   path engages on the replicated mirror (delta_solves > 0 — the warm
   standby was actually warm, not a cold rebuild),
3. zero duplicate launches: pods bound at promotion stay on their nodes
   (no relaunch of capacity the dead leader already provisioned),
4. the surfaces tell the story over live HTTP: the handoff introspection
   provider, a kpctl top LEADER/HANDOFF row, karpenter_operator_* gauges
   on a /metrics scrape that lints clean,
5. the lock-order witness is cycle-free in BOTH processes.

Fast by design: small-family lattice, ~3 s lease. The cutover-ladder
matrix (stale anchor, version mismatch, corrupt lease files) lives in
tests/test_handoff.py; this gate is the end-to-end two-process proof.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

LEASE_DURATION = 3.0
PROMOTE_SLACK = 20.0      # lease window + election cadence + CI jitter


# ---------------------------------------------------------------- children

def _election_loop(elector, replica=None, period: float = 0.5) -> None:
    """The dedicated election thread (what ControllerRuntime registers as
    its leader-election controller): a pass blocked in an XLA compile
    must not cost the incumbent its lease. On a standby the same thread
    pumps the replication stream between ticks."""
    while True:
        if replica is not None and not elector.is_leader:
            replica.sync_once()
        elector.try_acquire_or_renew()
        time.sleep(period)


def _build_operator(workdir: Path):
    from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                    build_lattice)
    from karpenter_provider_aws_tpu.operator import Operator, Options
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    return Operator(options=Options(
        registration_delay=0.5,
        compile_cache_dir=str(workdir / "compile-cache")),
        lattice=lattice)


def run_leader(workdir: Path) -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.kube.writer import FencedWriteError
    from karpenter_provider_aws_tpu.operator.leaderelection import (
        FileLeaseStore, LeaderElector)
    from karpenter_provider_aws_tpu.state.replication import (
        ReplicationService, ReplicationSource, serve_replication)

    import threading

    op = _build_operator(workdir)
    src = ReplicationSource(op.cluster)
    repl = serve_replication(ReplicationService(src),
                             f"unix:{workdir}/repl.sock")
    elector = LeaderElector(FileLeaseStore(str(workdir / "lease.json")),
                            "leader", lease_duration=LEASE_DURATION)
    op.wire_handoff(elector, source=src)
    threading.Thread(target=_election_loop, args=(elector,),
                     daemon=True).start()
    http = start_server(op, 0)
    (workdir / "leader.port").write_text(str(http.server_address[1]))
    serial = 0
    try:
        while True:   # until the parent SIGKILLs us (that's the point)
            if elector.is_leader:
                for _ in range(2):
                    serial += 1
                    op.cluster.add_pod(Pod(
                        name=f"lp{serial}",
                        requests={"cpu": "500m", "memory": "1Gi"}))
                try:
                    op.run_once(force_provision=True)
                except FencedWriteError:
                    pass   # demoted mid-pass: correctly fenced, go quiet
            src.tick()
            time.sleep(0.3)
    finally:
        repl.stop(0)
        http.shutdown()
    return 0


def run_standby(workdir: Path) -> int:
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud.fake import (CloudInstance,
                                                       parse_instance_id)
    from karpenter_provider_aws_tpu.operator.leaderelection import (
        FileLeaseStore, LeaderElector)
    from karpenter_provider_aws_tpu.state.replication import (
        ReplicationClient, StandbyReplica)

    op = _build_operator(workdir)
    replica = StandbyReplica(
        op.cluster, ReplicationClient(f"unix:{workdir}/repl.sock"),
        prebuild=lambda: op.provisioner.warm_build())
    elector = LeaderElector(FileLeaseStore(str(workdir / "lease.json")),
                            "standby", lease_duration=LEASE_DURATION,
                            promotion_gate=replica.promotion_ready)

    smoke = {"promoted": False, "rebinds": 0, "bound_at_promotion": 0,
             "convergence_claims": 0}
    bound0 = {}

    def on_promote() -> None:
        # adopt the dead leader's fleet: the mirror replicated its claims,
        # so materialize their instances in OUR cloud before any
        # controller lists it (otherwise GC reads the fleet as vanished
        # and the convergence passes relaunch everything — the exact
        # duplicate-launch failure this smoke gates on)
        for c in list(op.cluster.claims.values()):
            if not c.provider_id:
                continue
            iid = parse_instance_id(c.provider_id)
            op.cloud.instances[iid] = CloudInstance(
                id=iid, instance_type=c.instance_type or "m5.large",
                zone=c.zone or "us-west-2a",
                capacity_type=c.capacity_type or "on-demand",
                launch_time=c.launched_at or 0.0)
        bound0.update({p.name: p.node_name
                       for p in op.cluster.pods.values() if p.node_name})
        smoke["promoted"] = True
        smoke["bound_at_promotion"] = len(bound0)
        introspect.registry().register("smoke", lambda: dict(smoke))

    elector.on_promote = on_promote          # wire_handoff chains onto it
    op.wire_handoff(elector, replica=replica)
    import threading
    threading.Thread(target=_election_loop, args=(elector, replica),
                     daemon=True).start()
    http = start_server(op, 0)
    (workdir / "standby.port").write_text(str(http.server_address[1]))
    serial = 0
    passes = 0
    try:
        while True:
            # gate passes on the PROMOTE HOOK having finished (not bare
            # is_leader): the fleet adoption above must land before the
            # first pass lists the cloud
            if not smoke["promoted"]:
                pass   # the election thread streams + gates promotion
            else:
                passes += 1
                if passes > 3:   # first passes are pure convergence:
                    serial += 1  # nothing new to place, nothing launched
                    op.cluster.add_pod(Pod(
                        name=f"sp{serial}",
                        requests={"cpu": "500m", "memory": "1Gi"}))
                op.run_once(force_provision=True)
                if passes == 3:
                    smoke["convergence_claims"] = \
                        op.writer.counts.get("create_claim", 0)
                smoke["rebinds"] = sum(
                    1 for name, node in bound0.items()
                    if (p := op.cluster.pods.get(name)) is not None
                    and p.node_name != node)
                (workdir / "standby.status.json").write_text(
                    json.dumps(smoke))
            time.sleep(0.3)
    finally:
        http.shutdown()
    return 0


# ---------------------------------------------------------------- parent

def _fetch(base: str, path: str, timeout: float = 10.0):
    return urllib.request.urlopen(f"{base}{path}", timeout=timeout).read()


def _vars(base: str) -> dict:
    return json.loads(_fetch(base, "/debug/vars"))


def _wait(what: str, deadline: float, fn):
    """Poll ``fn`` until it returns a truthy value; raise past deadline."""
    while True:
        try:
            v = fn()
        except Exception:
            v = None
        if v:
            return v
        if time.time() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.25)


def _spawn(workdir: Path, role: str) -> subprocess.Popen:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    logf = open(workdir / f"{role}.log", "w")
    return subprocess.Popen(
        [sys.executable, __file__, "--role", role, "--dir", str(workdir)],
        cwd=str(REPO), env=env, stdout=logf, stderr=subprocess.STDOUT)


def main() -> int:
    import tempfile
    workdir = Path(tempfile.mkdtemp(prefix="smoke-handoff-"))
    failures = []
    leader = standby = None
    try:
        # phase 1: leader boots, wins the lease, provisions under churn
        leader = _spawn(workdir, "leader")
        port_a = int(_wait("leader port file", time.time() + 120,
                           lambda: (workdir / "leader.port").exists()
                           and (workdir / "leader.port").read_text()))
        base_a = f"http://127.0.0.1:{port_a}"
        doc_a = _wait("leader first passes", time.time() + 180, lambda: (
            lambda d: d if (d["providers"]["provisioner"].get("passes", 0)
                            >= 2 and d["providers"]["cluster"].get("nodes",
                                                                   0) > 0)
            else None)(_vars(base_a)))
        ho_a = doc_a["providers"].get("handoff", {})
        if not ho_a.get("leader"):
            failures.append(f"leader process not leading: {ho_a}")
        if ho_a.get("fence", 0) < 1:
            failures.append(f"leader fence never rotated up: {ho_a}")
        if doc_a["providers"].get("lockorder", {}).get("cycles", 1) != 0:
            failures.append("lock-order witness cycle in the LEADER")

        # phase 2: standby streams while the leader lives — and stays out
        standby = _spawn(workdir, "standby")
        port_b = int(_wait("standby port file", time.time() + 120,
                           lambda: (workdir / "standby.port").exists()
                           and (workdir / "standby.port").read_text()))
        base_b = f"http://127.0.0.1:{port_b}"
        ho_b = _wait("standby streaming", time.time() + 180, lambda: (
            lambda h: h if (h.get("replica_anchor", -1) >= 0
                            and h.get("replica_deltas", 0) > 0
                            and h.get("replica_prebuilds", 0) > 0)
            else None)(_vars(base_b)["providers"].get("handoff", {})))
        if ho_b.get("leader"):
            failures.append("standby leads while the leader is alive")
        if ho_b.get("replica_snapshots", 0) < 1:
            failures.append(f"standby never applied a snapshot: {ho_b}")

        # phase 3: kill -9 the leader mid-churn; standby must promote
        # within the lease window (+ slack) with a rotated fence
        leader_fence = ho_a.get("fence", 0)
        os.kill(leader.pid, signal.SIGKILL)
        leader.wait(15)
        t_kill = time.time()
        ho_b = _wait("standby promotion",
                     t_kill + LEASE_DURATION + PROMOTE_SLACK,
                     lambda: (lambda h: h if h.get("leader") else None)(
                         _vars(base_b)["providers"].get("handoff", {})))
        promote_latency = time.time() - t_kill
        if ho_b.get("fence", 0) <= leader_fence:
            failures.append(f"promotion did not rotate the fence "
                            f"(leader {leader_fence} -> {ho_b.get('fence')})")

        # phase 4: the promoted standby CARRIES passes — new pods get
        # capacity, the delta solve path engages on the replicated
        # mirror, and nothing already-bound is relaunched
        def _carrying():
            d = _vars(base_b)
            pr = d["providers"]
            ok = (pr["provisioner"].get("passes", 0) >= 5
                  and pr.get("writer", {}).get("create_claim", 0) > 0
                  and pr["solver"].get("delta_solves", 0) > 0)
            return d if ok else None
        doc_b = _wait("promoted standby carrying passes",
                      time.time() + 180, _carrying)
        status = json.loads((workdir / "standby.status.json").read_text())
        if not status.get("promoted"):
            failures.append(f"standby status never marked promoted: {status}")
        if status.get("bound_at_promotion", 0) <= 0:
            failures.append("vacuous handoff: no pods were bound at "
                            "promotion (leader never really worked)")
        if status.get("rebinds", 0) != 0:
            failures.append(f"{status['rebinds']} pods rebound after "
                            "promotion (duplicate launch territory)")
        if status.get("convergence_claims", 0) != 0:
            failures.append(f"{status['convergence_claims']} claims "
                            "launched during pure convergence passes — "
                            "duplicate capacity for already-bound pods")

        # phase 5: the surfaces — kpctl rows, /metrics lint, lockorder
        from karpenter_provider_aws_tpu.metrics import lint_exposition
        import kpctl
        top = "\n".join(kpctl._render_top(doc_b, base_b))
        leader_rows = [ln for ln in top.splitlines()
                       if ln.startswith("LEADER")]
        if not leader_rows or "leader" not in leader_rows[0]:
            failures.append(f"kpctl top LEADER row wrong: {leader_rows}")
        if not any(ln.startswith("HANDOFF") for ln in top.splitlines()):
            failures.append("kpctl top renders no HANDOFF row")
        scrape = _fetch(base_b, "/metrics").decode()
        for series in ("karpenter_operator_leader_state",
                       "karpenter_operator_handoff_fence_token",
                       "karpenter_operator_handoff_deltas",
                       "karpenter_operator_handoff_rebuilds"):
            if series not in scrape:
                failures.append(f"/metrics missing {series}")
        lint = lint_exposition(scrape)
        if lint:
            failures.append(f"live scrape lint: {lint[:3]}")
        if doc_b["providers"].get("lockorder", {}).get("cycles", 1) != 0:
            failures.append("lock-order witness cycle in the STANDBY")
    except Exception as e:  # noqa: BLE001 - any escape is the failure
        failures.append(f"{type(e).__name__}: {e}")
    finally:
        for proc in (leader, standby):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(15)
                except subprocess.TimeoutExpired:
                    proc.kill()

    if failures:
        print("handoff smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        for role in ("leader", "standby"):
            log = workdir / f"{role}.log"
            if log.exists():
                tail = log.read_text().splitlines()[-15:]
                print(f"  --- {role}.log tail ---")
                for ln in tail:
                    print(f"  {ln}")
        return 1
    print(f"handoff smoke: OK (promoted in {promote_latency:.1f}s, "
          f"fence {leader_fence}->{ho_b.get('fence')}, "
          f"deltas={ho_b.get('replica_deltas')}, "
          f"carried {status['bound_at_promotion']} bound pods, "
          f"0 rebinds, 0 convergence launches)")
    return 0


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", choices=("leader", "standby"))
    ap.add_argument("--dir")
    a = ap.parse_args()
    if a.role == "leader":
        raise SystemExit(run_leader(Path(a.dir)))
    if a.role == "standby":
        raise SystemExit(run_standby(Path(a.dir)))
    raise SystemExit(main())
