#!/usr/bin/env python
"""profile_run — record the continuous-profiling acceptance artifact.

Drives the SAME 15k-pod API-mode workload twice — once with the
sampling profiler on (50 Hz), once as an unprofiled control — through
the full protocol path (KubeClient writes -> admission -> informers ->
provisioning passes -> watch fan-out), and records PROF_r08.json:

- the top write-path / watch-fan-out frames (profile filtered to
  kube/writer.py, kube/apiserver.py, operator/sync.py, kube/informer.py)
  and the overall top frames,
- the top contended locks (wait p99 + owner-at-contention tags),
- the device cost model's measured-vs-modeled per shape,
- profiler overhead: wall-time delta vs the control run AND the
  sampler's self-measured cost — the ISSUE 7 "<5% enabled" bound,
- any burn-triggered captures the run produced.

Usage: python tools/profile_run.py [--pods 15000] [--out PROF_r08.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

WRITE_PATH_FILES = ("writer.py", "apiserver.py", "sync.py", "informer.py",
                    "client.py", "httpserver.py")


_LATTICE = None


def _lattice():
    global _LATTICE
    if _LATTICE is None:
        from karpenter_provider_aws_tpu.lattice import build_lattice
        from karpenter_provider_aws_tpu.lattice.realdata import load_catalog
        _LATTICE = build_lattice(load_catalog(require_price=True))
    return _LATTICE


def run_workload(pods: int, profile: bool, hz: float = 50.0,
                 label: str = ""):
    """One THREADED API-mode churn run (every controller on its own
    cadence, the soak stratum): pod waves through the protocol
    (admission -> store -> watch -> informer thread -> mirror) while the
    provisioner/lifecycle/metrics threads run concurrently — real lock
    contention, the round-5 "API-mode degrades 1k->15k" shape this
    layer exists to explain. Returns (wall_seconds, op, profiler)."""
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.introspect import SamplingProfiler
    from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.operator.runtime import (
        ControllerRuntime, operator_specs)

    api = FakeAPIServer()
    client = KubeClient(api)
    op = Operator(options=Options(registration_delay=0.1,
                                  batch_idle_duration=0.05,
                                  batch_max_duration=0.5),
                  lattice=_lattice(), api_server=api)
    prof = None
    if profile:
        prof = SamplingProfiler(hz=hz).start()
        introspect.set_profiler(prof)
    rt = ControllerRuntime(operator_specs(op)).start()
    sizes = [(250, 512), (500, 1024), (1000, 2048), (2000, 4096)]
    t0 = time.perf_counter()
    created = 0
    wave = 0
    try:
        while created < pods:
            wave += 1
            n = min(1500, pods - created)
            for i in range(n):
                cpu, mem = sizes[(created + i) % len(sizes)]
                client.create_pod(Pod(
                    name=f"prof-{label}w{wave}-{i}",
                    requests={"cpu": f"{cpu}m", "memory": f"{mem}Mi"}))
            created += n
            # let the threaded control plane mostly drain this wave
            # before the next (bounded): sustained back-to-back passes,
            # not one 15k mega-batch
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if len(op.cluster.pending_pods()) < 200:
                    break
                time.sleep(0.05)
        # full drain
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if not op.cluster.pending_pods():
                break
            time.sleep(0.1)
    finally:
        wall = time.perf_counter() - t0
        # pending at RUN END: read later, nomination expiry against
        # stopped controllers re-pends pods and lies about the drain
        op.final_pending = len(op.cluster.pending_pods())
        while not rt.stop():
            print("profile_run: waiting for a blocked controller...")
        if prof is not None:
            prof.stop()
    return wall, op, prof


def run_deterministic(pods: int, profile: bool, hz: float = 50.0,
                      label: str = ""):
    """The overhead-measurement stratum: the SAME single-threaded
    API-mode pump (sync -> provision -> lifecycle -> sync, no sleeps, no
    thread scheduling) executes an IDENTICAL operation sequence with and
    without the profiler daemon sampling over it — so the wall-clock
    ratio measures the profiler, not workload scatter (the threaded
    churn run's wall time varies >5% between identical configs, which
    is larger than the signal)."""
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.introspect import SamplingProfiler
    from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
    from karpenter_provider_aws_tpu.operator import Operator, Options

    api = FakeAPIServer()
    client = KubeClient(api)
    op = Operator(options=Options(registration_delay=0.0,
                                  batch_idle_duration=0.05,
                                  batch_max_duration=0.5),
                  lattice=_lattice(), api_server=api)
    prof = None
    if profile:
        prof = SamplingProfiler(hz=hz).start()
        introspect.set_profiler(prof)
    sizes = [(250, 512), (500, 1024), (1000, 2048), (2000, 4096)]
    # GC symmetry: constructing this Operator replaced the previous
    # run's introspection providers (the last references to its 15k-pod
    # object graph) — collect it NOW and disable the collector for the
    # measured window, otherwise whichever run goes second drags the
    # bigger live heap through every gen-2 pass and the comparison
    # measures GC, not the profiler (observed at ±10%)
    import gc
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        created = 0
        wave = 0
        while created < pods:
            wave += 1
            n = min(1500, pods - created)
            for i in range(n):
                cpu, mem = sizes[(created + i) % len(sizes)]
                client.create_pod(Pod(
                    name=f"det-{label}w{wave}-{i}",
                    requests={"cpu": f"{cpu}m", "memory": f"{mem}Mi"}))
            created += n
            for _ in range(60):
                op.sync_once()
                if op.cluster.pending_pods():
                    op.provisioner.provision_once()
                op.lifecycle.reconcile()
                op.sync_once()
                if not op.cluster.pending_pods():
                    break
            op.emit_gauges()
        wall = time.perf_counter() - t0
    finally:
        gc.enable()
    op.final_pending = len(op.cluster.pending_pods())
    if prof is not None:
        prof.stop()
    return wall, op, prof


def filtered_top(prof, files, n=10):
    """Top frames restricted to the given source files."""
    return [d for d in prof.top(400)
            if any(d["frame"].startswith(f + ":") for f in files)][:n]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=15000)
    ap.add_argument("--hz", type=float, default=50.0)
    ap.add_argument("--out", default="PROF_r08.json")
    args = ap.parse_args(argv)

    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.introspect import contention
    from karpenter_provider_aws_tpu.solver import costmodel

    # shared warmup: the process-global jit cache means whichever run
    # goes FIRST would otherwise pay XLA compiles the other reuses and
    # the overhead comparison would be fiction in either direction. A
    # small churn run warms the protocol path, then the solver's OWN
    # bucket ladder is compiled explicitly up to the B buckets a
    # 15k-pod run's growing existing-bin table reaches (the first
    # attempt skipped this and recorded a -25% "overhead" — pure
    # compile asymmetry).
    print("profile_run: warmup run (1000 pods + solver ladder)...")
    warm_wall, warm_op, _ = run_deterministic(1000, profile=False,
                                              label="warm")
    warm_op.solver.warmup(node_pools_count=len(warm_op.node_pools),
                          g_buckets=(16, 32),
                          b_buckets=(32, 128, 512, 1024, 2048))
    warm_op.solver.capture_cost_model(
        node_pools_count=len(warm_op.node_pools))
    print(f"profile_run: warmup {warm_wall:.1f}s (compiles paid)")

    # ---- overhead stratum: deterministic pair, identical op sequence.
    # PROFILED first: compile residue the warmup still missed lands on
    # the profiled side — the measurement is an upper bound.
    print(f"profile_run: deterministic profiled run ({args.pods} pods, "
          f"{args.hz} Hz)...")
    det_p_wall, det_p_op, det_prof = run_deterministic(
        args.pods, profile=True, hz=args.hz, label="p")
    det_p_pass_p50 = det_p_op.slo.latency_percentiles()[0]
    det_p_nodes = len(det_p_op.cluster.nodes)
    det_p_pending = det_p_op.final_pending
    det_pstats = det_prof.stats()
    det_samples = det_prof.samples
    print(f"profile_run: det profiled {det_p_wall:.1f}s, "
          f"nodes={det_p_nodes}, samples={det_samples}")
    # drop this run's 15k-pod graph BEFORE the control run (the next
    # Operator's provider registration releases the last references)
    del det_p_op, det_prof
    print(f"profile_run: deterministic control run ({args.pods} pods)...")
    det_c_wall, det_c_op, _ = run_deterministic(args.pods, profile=False,
                                                label="c")
    det_c_pass_p50 = det_c_op.slo.latency_percentiles()[0]
    det_c_nodes = len(det_c_op.cluster.nodes)
    print(f"profile_run: det control {det_c_wall:.1f}s, "
          f"nodes={det_c_nodes}")
    del det_c_op
    if det_p_nodes != det_c_nodes:
        print(f"profile_run: WARNING det runs diverged ({det_p_nodes} vs "
              f"{det_c_nodes} nodes) — overhead comparison weakened")
    control_wall, wall = det_c_wall, det_p_wall
    control_pass_p50, prof_pass_p50 = det_c_pass_p50, det_p_pass_p50

    # ---- attribution stratum: the THREADED runtime (real concurrency,
    # real lock contention, the soak shape) with the profiler on
    print(f"profile_run: threaded attribution run ({args.pods} pods, "
          f"{args.hz} Hz)...")
    # fresh contention accounting: the artifact's lock table must
    # describe THIS run, not the warmup/deterministic residue
    contention.reset()
    thr_wall, op, prof = run_workload(args.pods, profile=True, hz=args.hz,
                                      label="t")
    print(f"profile_run: threaded {thr_wall:.1f}s, "
          f"nodes={len(op.cluster.nodes)}, samples={prof.samples}, "
          f"pending_at_end={op.final_pending}")

    overhead_pct = 100.0 * (wall - control_wall) / control_wall
    pstats = prof.stats()
    top_locks = [
        {"lock": name, "waitP99Ms": round(p99 * 1e3, 3), "contended": n,
         "owners": contention._stats_for(name).owner_tags}
        for name, p99, n in contention.top_waits(5)]
    bc = introspect.burn_capture()
    doc = {
        "artifact": "PROF_r08",
        "what": "15k-pod API-mode churn with the continuous-profiling "
                "layer on: write-path/watch-fan-out frame attribution, "
                "lock contention, device cost model, and measured "
                "profiler overhead vs an unprofiled control run "
                "(ISSUE 7 acceptance)",
        "pods": args.pods,
        "api_mode": True,
        "backend_note": "CPU backend (jax_platforms=cpu): device-solve "
                        "frames are XLA-on-host; the attribution "
                        "machinery is identical on TPU",
        "profiler": {
            "hz": args.hz,
            "threaded_run_samples": prof.samples,
            "threaded_unique_stacks": pstats["unique_stacks"],
            "dropped_stacks": pstats["dropped_stacks"],
            "self_measured_overhead_pct": pstats["overhead_pct"],
            "avg_sample_ms": pstats["avg_sample_ms"],
        },
        "overhead": {
            "methodology": "deterministic single-threaded API-mode pump "
                           "executing an IDENTICAL operation sequence "
                           "with/without the sampler (the threaded churn "
                           "run's wall scatter exceeds the signal); "
                           "profiled run FIRST after a shared jit-cache "
                           "warmup (churn + explicit solver bucket "
                           "ladder), so compile residue, if any, lands "
                           "on the profiled side — an upper bound",
            "control_wall_seconds": round(control_wall, 2),
            "profiled_wall_seconds": round(wall, 2),
            "e2e_overhead_pct": round(overhead_pct, 2),
            "control_pass_p50_ms": round(control_pass_p50 * 1e3, 2),
            "profiled_pass_p50_ms": round(prof_pass_p50 * 1e3, 2),
            "pass_p50_overhead_pct": round(
                100.0 * (prof_pass_p50 - control_pass_p50)
                / control_pass_p50, 2) if control_pass_p50 else None,
            "det_runs_node_parity": det_p_nodes == det_c_nodes,
            "det_profiler_samples": det_samples,
            "det_self_measured_overhead_pct": det_pstats["overhead_pct"],
            "bound_pct": 5.0,
            "within_bound": overhead_pct < 5.0,
        },
        "top_frames_overall": prof.top(15),
        "top_frames_write_path": filtered_top(prof, WRITE_PATH_FILES),
        "top_contended_locks": top_locks,
        "contention": {k: v for k, v in contention.stats().items()
                       if not k.endswith("_acquisitions")},
        "device_cost_model": costmodel.model().summary(),
        "burn_captures": bc.doc() if bc is not None else {},
        "parity": {
            "det_control_nodes": det_c_nodes,
            "det_profiled_nodes": det_p_nodes,
            "det_pending_at_end": det_p_pending,
            "threaded_nodes": len(op.cluster.nodes),
            "threaded_wall_seconds": round(thr_wall, 2),
            "threaded_pending_at_end": op.final_pending,
        },
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"profile_run: wrote {args.out}")
    print(f"  e2e overhead {overhead_pct:+.2f}% (bound 5%), "
          f"self-measured {pstats['overhead_pct']:.2f}%")
    print("  top write-path frames:")
    for d in doc["top_frames_write_path"][:3]:
        print(f"    {d['frame']}  incl={d['inclusive']} self={d['self']}")
    print("  top contended locks:")
    for d in top_locks[:3]:
        print(f"    {d['lock']}  p99={d['waitP99Ms']}ms "
              f"contended={d['contended']}")
    introspect.set_profiler(None)
    ok = (overhead_pct < 5.0 and det_p_pending == 0
          and op.final_pending == 0)
    print(f"profile_run: {'OK' if ok else 'VIOLATED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
