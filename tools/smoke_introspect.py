#!/usr/bin/env python
"""CI smoke for the introspection layer + metrics wire format.

What the ci.sh gate asserts here (docs/reference/introspection.md):

1. a real Operator comes up and EVERY registered introspection provider
   reports a non-error stats dict through /debug/vars,
2. /debug/statusz renders (human surface) and /debug/vars parses (JSON
   surface) over live HTTP on the metrics server,
3. the live /metrics scrape passes the promtool-style lint
   (metrics.lint_exposition): HELP/TYPE pairing and ordering, label
   escaping, histogram bucket monotonicity and +Inf/_count agreement,
   exemplar comment lines staying scrape-safe.

Fast by design: the small-family lattice, one provisioning pass, one
sampler tick — a broken provider or a malformed series fails CI in
seconds instead of riding to the next soak.
"""

from __future__ import annotations

import json
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.metrics import lint_exposition
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                  cloud=FakeCloud(clock), clock=clock)
    # drive one real pass so counters move (a vacuously-empty smoke would
    # pass with every provider broken-but-zero)
    for i in range(8):
        op.cluster.add_pod(Pod(name=f"smoke-{i}",
                               requests={"cpu": "500m", "memory": "1Gi"}))
    op.settle(max_rounds=20)
    op.sampler.sample_once()

    failures = []
    server = start_server(op, 0)
    port = server.server_address[1]
    try:
        base = f"http://127.0.0.1:{port}"
        # 1. /debug/vars: parses, and every registered provider reports
        doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/vars?series=1", timeout=10).read())
        registered = set(introspect.registry().names())
        reported = set(doc.get("providers", {}))
        if registered - reported:
            failures.append(f"providers missing from /debug/vars: "
                            f"{sorted(registered - reported)}")
        for name, stats in doc.get("providers", {}).items():
            if not isinstance(stats, dict) or not stats:
                failures.append(f"provider {name}: empty stats")
            elif "error" in stats:
                failures.append(f"provider {name}: {stats['error']}")
        if not doc.get("series"):
            failures.append("/debug/vars?series=1 carries no ring series")
        # 2. /debug/statusz renders every provider section
        sz = urllib.request.urlopen(f"{base}/debug/statusz",
                                    timeout=10).read().decode()
        if not sz.startswith("karpenter-tpu statusz"):
            failures.append("statusz: unexpected header")
        for name in sorted(registered):
            if f"== {name} ==" not in sz:
                failures.append(f"statusz: provider {name} not rendered")
        # 3. the live scrape passes the wire-format lint
        scrape = urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10).read().decode()
        problems = lint_exposition(scrape)
        failures.extend(f"metrics lint: {p}" for p in problems)
        for series in ("karpenter_pods_state", "karpenter_build_info",
                       "karpenter_slo_latency_budget_burn"):
            if series not in scrape:
                failures.append(f"metrics: {series} missing from scrape")
    finally:
        server.shutdown()
    n = len(introspect.registry().names())
    if failures:
        print("introspection smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"introspection smoke: OK ({n} providers, statusz+vars parse, "
          f"metrics lint clean)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
