"""Interruption-throughput benchmark.

Analog of the reference's interruption benchmark (reference
pkg/controllers/interruption/interruption_benchmark_test.go:61-75: drain
100 / 1k / 5k / 15k SQS messages through the controller, measuring
messages/sec). Here the queue is the in-memory FakeQueue with the same
receive-10 / delete-on-handled semantics, the claims are registered spot
capacity, and the message mix exercises all four parsed schemas (spot
interruption, rebalance recommendation, scheduled change, instance
state-change).

Usage: python tools/bench_interruption.py [--api-mode] [depths...]
Prints one JSON line per depth: messages/sec through a full
receive→parse→handle→delete drain, plus handled/ICE'd counts.
``--api-mode`` drives the same drain through the apiserver seam
(claims created via the typed client, informer-fed mirror, writer
deletions, events mirrored as wire objects) — the stratum the
reference's controllers always run in.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from karpenter_provider_aws_tpu.apis import NodePool  # noqa: E402
from karpenter_provider_aws_tpu.apis.objects import NodeClaim, NodeClaimPhase  # noqa: E402
from karpenter_provider_aws_tpu.cloud import FakeCloud  # noqa: E402
from karpenter_provider_aws_tpu.interruption import (  # noqa: E402
    FakeQueue, rebalance_recommendation, scheduled_change, spot_interruption,
    state_change,
)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice  # noqa: E402
from karpenter_provider_aws_tpu.operator import Operator, Options  # noqa: E402
from karpenter_provider_aws_tpu.utils.clock import FakeClock  # noqa: E402

DEPTHS = (100, 1_000, 5_000, 15_000)
N_CLAIMS = 200


def build_env(lattice, api_mode: bool = False):
    clock = FakeClock()
    queue = FakeQueue("bench-interruptions")
    kw = {}
    if api_mode:
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        kw["api_server"] = FakeAPIServer(clock=clock)
    env = Operator(options=Options(), lattice=lattice, cloud=FakeCloud(clock),
                   clock=clock, node_pools=[NodePool(name="default")],
                   interruption_queue=queue, **kw)
    zones = lattice.zones
    for i in range(N_CLAIMS):
        claim = NodeClaim(
            name=f"claim-{i}", node_pool="default",
            phase=NodeClaimPhase.INITIALIZED,
            provider_id=f"fake:///{zones[i % len(zones)]}/i-{i:08x}",
            instance_type="m5.xlarge", zone=zones[i % len(zones)],
            capacity_type="spot")
        if api_mode:
            env.kube.create_nodeclaim(claim)
        else:
            env.cluster.add_claim(claim)
    if api_mode:
        env.sync.sync_once()   # informer-feed the mirror
    return env


def seed_messages(env, depth: int) -> None:
    """Round-robin message mix over the claim fleet: 70% spot interruption,
    10% each rebalance / scheduled change / state change (the reference's
    four EventBridge schemas)."""
    for i in range(depth):
        iid = f"i-{i % N_CLAIMS:08x}"
        r = i % 10
        if r < 7:
            body = spot_interruption(iid)
        elif r == 7:
            body = rebalance_recommendation(iid)
        elif r == 8:
            body = scheduled_change(iid)
        else:
            body = state_change(iid, "stopping")
        env.interruption_queue.send(body)


def drain(env) -> int:
    """reconcile() until the queue is empty; returns messages handled.
    In API mode the informer pump runs inside the timed loop — the
    deletions/ICE state flowing back into the mirror is part of what
    the stratum costs."""
    handled = 0
    while len(env.interruption_queue):
        n = env.interruption.reconcile()
        if env.sync is not None:
            env.sync.sync_once()
        if n == 0:
            break
        handled += n
    return handled


def run(depth: int, lattice, api_mode: bool = False) -> dict:
    env = build_env(lattice, api_mode=api_mode)
    seed_messages(env, depth)
    t0 = time.perf_counter()
    handled = drain(env)
    wall = time.perf_counter() - t0
    ice = sum(1 for _ in env.unavailable.entries())
    return {
        "metric": f"interruption_throughput_{depth}"
                  + ("_api" if api_mode else ""),
        "value": round(handled / wall, 1),
        "unit": "msgs/sec",
        "detail": {
            "messages": depth,
            "handled": handled,
            "remaining": len(env.interruption_queue),
            "wall_ms": round(wall * 1000.0, 1),
            "ice_entries": ice,
            "stratum": "api" if api_mode else "direct",
            "claims_drained": sum(
                1 for c in env.cluster.snapshot_claims()
                if c.deletion_timestamp is not None),
        },
    }


def main() -> None:
    args = sys.argv[1:]
    api_mode = "--api-mode" in args
    depths = [int(a) for a in args if a != "--api-mode"] or list(DEPTHS)
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5", "r5")])
    for depth in depths:
        print(json.dumps(run(depth, lattice, api_mode=api_mode)),
              flush=True)


if __name__ == "__main__":
    main()
