#!/usr/bin/env python
"""CI smoke for the vmapped consolidation engine (ci.sh gate).

Boots a real Operator, churns it to an over-provisioned steady state
(oversized nodes pinned non-empty by one tiny anti-affine pod each),
and asserts the engine actually carries the consolidation search end
to end (docs/reference/consolidation.md):

1. VMAPPED: >=2 nodes consolidate via the batched device path —
   ``vmapped_whatifs`` > 0 with candidate sets batched per dispatch,
   and ZERO host-ladder fallbacks (every candidate problem stayed
   inside the vmapped envelope);
2. REFEREE: every accepted removal passed the host-FFD cost referee
   (``referee_checks`` > 0, accepted plans within the <=2% envelope —
   a referee that never ran would make the envelope vacuous);
3. BUDGET PACING: with the pool's disruption budget pinned to 0, the
   search probes but refuses — ``not-consolidatable-budget`` skips
   recorded, zero nodes touched — and consolidating resumes the pass
   after the budget opens to 1-at-a-time;
4. ZERO-LEG CACHE: pending-only churn after the fleet settles re-runs
   the search entirely from the probe cache (``fp_unchanged`` grows,
   ``vmapped_whatifs`` does not);
5. SURFACES: the ``consolidation`` introspection provider reports over
   live HTTP, the kpctl top CONSOLIDATION row renders, and
   ``kpctl explain node`` answers "why was this node NOT consolidated"
   with a taxonomy code.

Fast by design: small-family lattice, 6 nodes, FakeClock.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_NODES = 6


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.apis.objects import (DisruptionBudget,
                                                         NodePool,
                                                         PodAffinityTerm)
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                    build_lattice)
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    failures = []
    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    pool = NodePool(name="default")
    pool.disruption.consolidation_policy = "WhenUnderutilized"
    pool.disruption.consolidate_after = 5.0
    # phase 1: budget CLOSED — the engine must probe yet refuse
    pool.disruption.budgets = [DisruptionBudget(nodes="0")]
    # spot fleet: replacements are spot too, so the spot->spot gate +
    # 15-type flexibility floor are on the accept path
    op = Operator(options=Options(registration_delay=0.5,
                                  spot_to_spot_consolidation=True),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                  node_pools=[pool])
    engine = op.disruption.engine

    # over-provision: one 3-cpu anti-affine pod per node forces 6
    # oversized nodes; swapping them for 250m pods leaves every node
    # non-empty (emptiness can't claim them) but wildly underutilized —
    # exactly the consolidation method's territory
    anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                            label_selector=(("app", "spread"),), anti=True)]
    for i in range(N_NODES):
        op.cluster.add_pod(Pod(name=f"big-{i}", labels={"app": "spread"},
                               requests={"cpu": "3", "memory": "6Gi"},
                               pod_affinity=list(anti)))
    op.settle(max_rounds=30)
    if len(op.cluster.nodes) != N_NODES:
        failures.append(f"seed did not build {N_NODES} nodes "
                        f"({len(op.cluster.nodes)})")
    for i in range(N_NODES):
        op.cluster.delete_pod(f"big-{i}")
        op.cluster.add_pod(Pod(name=f"tiny-{i}", labels={"app": "spread"},
                               requests={"cpu": "250m",
                                         "memory": "256Mi"},
                               pod_affinity=list(anti)))
    op.settle(max_rounds=10)
    if len(op.cluster.nodes) != N_NODES:
        failures.append("tiny pods did not land on the existing fleet")
    clock.step(6.0)   # past consolidate_after

    # phase 1: budget 0 — probes run, pacing refuses, nothing moves
    for _ in range(3):
        op.run_once(force_provision=False)
        clock.step(0.5)
    stats = engine.stats()
    if stats.get("vmapped_whatifs", 0) < 1:
        failures.append("budget-0 phase never dispatched a probe batch")
    if stats.get("skip_not_consolidatable_budget", 0) < 1:
        failures.append("budget pacing never recorded a "
                        "not-consolidatable-budget skip")
    if stats.get("nodes_consolidated", 0) != 0 or op.disruption._in_flight:
        failures.append("a node was disrupted under a 0-node budget")
    budget_skips = stats.get("skip_not_consolidatable_budget", 0)

    # phase 2: budget opens to 1-at-a-time — consolidation proceeds,
    # paced, until the fleet is tight
    pool.disruption.budgets = [DisruptionBudget(nodes="1")]
    for _ in range(40):
        op.run_once(force_provision=bool(op.cluster.pending_pods()))
        clock.step(0.5)
        if engine.counters["nodes_consolidated"] >= 2 \
                and not op.disruption._in_flight \
                and not op.cluster.pending_pods():
            break
    op.settle(max_rounds=10)
    stats = engine.stats()
    if stats.get("nodes_consolidated", 0) < 2:
        failures.append(
            f"engine consolidated {stats.get('nodes_consolidated', 0):g} "
            f"nodes, expected >=2 (accepted={stats.get('accepted', 0):g}, "
            f"ledger={engine.ledger_doc()})")
    if stats.get("host_fallbacks", 0) != 0:
        failures.append(f"candidates left the vmapped envelope: "
                        f"{stats.get('host_fallbacks'):g} host fallbacks")
    if stats.get("vmapped_whatifs", 0) < 2:
        failures.append("the batched device path barely engaged")
    if stats.get("batched_candidates", 0) <= stats.get("vmapped_whatifs", 0):
        failures.append("dispatches did not batch >1 candidate set")
    if stats.get("referee_checks", 0) < 1:
        failures.append("the savings referee never ran")
    if stats.get("savings_per_hour", 0) <= 0:
        failures.append("accepted consolidations recorded no savings")
    if op.cluster.pending_pods():
        failures.append(f"{len(op.cluster.pending_pods())} pods stranded "
                        "pending after consolidation")

    # phase 3: close the budget again and age the fleet back into
    # eligibility; the warmup pass dispatches one fresh batch (and codes
    # every candidate not-consolidatable-budget -> the ledger the explain
    # stanza reads), then pending-only churn must re-run the search
    # entirely from the probe cache: zero device legs, zero snapshots
    pool.disruption.budgets = [DisruptionBudget(nodes="0")]
    clock.step(6.0)
    for _ in range(2):
        op.run_once(force_provision=False)
        clock.step(0.5)
    pre = engine.stats()
    op.cluster.add_pod(Pod(name="impossible",
                           requests={"cpu": "4000", "memory": "64Ti"}))
    for _ in range(2):
        op.run_once(force_provision=True)
        clock.step(0.5)
    post = engine.stats()
    if post.get("fp_unchanged", 0) <= pre.get("fp_unchanged", 0):
        failures.append(
            "pending-only churn never hit the zero-leg probe cache "
            f"(fp_unchanged {pre.get('fp_unchanged', 0):g} -> "
            f"{post.get('fp_unchanged', 0):g})")
    if post.get("vmapped_whatifs", 0) > pre.get("vmapped_whatifs", 0):
        failures.append("pending-only churn paid a fresh device dispatch")

    # surfaces: provider + CONSOLIDATION row + explain node, live HTTP
    op.sampler.sample_once()
    server = start_server(op, 0)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/vars", timeout=10).read())
        co = doc.get("providers", {}).get("consolidation", {})
        if co.get("vmapped_whatifs", 0) < 1:
            failures.append(f"consolidation provider dark over HTTP: {co}")
        sys.path.insert(0, str(Path(__file__).resolve().parent))
        import kpctl
        top = "\n".join(kpctl._render_top(doc, base))
        row = next((ln for ln in top.splitlines()
                    if ln.startswith("CONSOLIDATION")), "")
        if not row:
            failures.append("kpctl top renders no CONSOLIDATION row")
        elif "dispatches" not in row or "referee" not in row:
            failures.append(f"CONSOLIDATION row malformed: {row}")
        # a node the engine skipped answers over /debug/explain + kpctl
        ledger = engine.ledger_doc()
        if ledger:
            name, entry = next(iter(ledger.items()))
            ed = json.loads(urllib.request.urlopen(
                f"{base}/debug/explain?node={name}", timeout=10).read())
            if ed.get("code") != entry["code"]:
                failures.append(f"explain?node= disagrees with the "
                                f"engine ledger: {ed} vs {entry}")
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = kpctl.main(["--server", base, "explain", "node",
                                 name])
            if rc != 0 or entry["code"] not in out.getvalue():
                failures.append(f"kpctl explain node failed (rc={rc}): "
                                f"{out.getvalue()!r}")
        else:
            failures.append("engine ledger empty — no skip decision to "
                            "explain (harness bug)")
    finally:
        server.shutdown()

    if failures:
        print("consolidation smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"consolidation smoke: OK "
          f"(nodes_consolidated={stats['nodes_consolidated']:g}, "
          f"savings=${stats['savings_per_hour']:.2f}/hr, "
          f"dispatches={post['vmapped_whatifs']:g} "
          f"({post['batched_candidates']:g} sets), "
          f"cached={post['fp_unchanged']:g}, host_fallbacks=0, "
          f"referee={post['referee_checks']:g} checks/"
          f"{post['referee_rejects']:g} rejects, "
          f"budget_skips={budget_skips:g})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
