#!/usr/bin/env python
"""CI smoke for the decision-explainability surface (ci.sh explain gate).

Boots a real Operator under a short ``squall`` weather scenario, ICEs a
whole instance family out of the market, and drives passes with one pod
that can ONLY land on that family — then asserts the explain stack tells
the truth about it (docs/reference/explain.md):

1. ``/debug/explain?pod=...`` over LIVE HTTP attributes the pending pod
   to the **ice** elimination stage (code ``ice-hold``), with the
   eliminated offerings named,
2. ``kpctl explain pod`` renders the elimination waterfall against the
   same live server (exit 0, the ice row present),
3. the FailedScheduling dedup holds: many passes over the same stuck
   pod publish ONE event for the (pod, reason-code) pair,
4. the ``explain`` introspection provider reports through /debug/vars —
   the same per-pass reason-code histogram soak artifacts embed — with
   ``reason_ice_hold`` > 0 and the elimination counters moving.

Fast by design: small-family lattice, ~10 weather ticks, a handful of
passes on FakeClock.
"""

from __future__ import annotations

import contextlib
import io
import json
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.interruption.queue import FakeQueue
    from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                    build_lattice)
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock
    from karpenter_provider_aws_tpu.weather import WeatherSimulator, named

    failures = []
    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    queue = FakeQueue("explain-smoke")
    op = Operator(options=Options(registration_delay=0.5),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                  interruption_queue=queue)
    scenario = named("squall")
    sim = WeatherSimulator(scenario, lattice, clock=clock,
                           pricing=op.pricing_provider, cloud=op.cloud,
                           unavailable=op.unavailable, queue=queue,
                           solver=op.solver, metrics=op.metrics).start()

    # the deliberately ICE'd-out pod: its node selector admits ONLY the
    # c5 family, and every c5 offering is held out of the market
    for z in lattice.zones:
        for ct in lattice.capacity_types:
            for t in [n for n in lattice.names if n.startswith("c5.")]:
                op.unavailable.mark_unavailable("smoke-ice", ct, t, z)
    op.cluster.add_pod(Pod(
        name="iced-pod", requests={"cpu": "500m"},
        node_selector={"karpenter.k8s.aws/instance-family": "c5"}))

    serial = 0
    for _ in range(10):
        serial += 1
        op.cluster.add_pod(Pod(name=f"bg-{serial}",
                               requests={"cpu": "500m", "memory": "1Gi"}))
        # re-assert the smoke's ICE hold each tick (the 10 s cleanup may
        # thaw TTL'd entries; the weather scenario churns its own)
        for z in lattice.zones:
            for ct in lattice.capacity_types:
                for t in [n for n in lattice.names if n.startswith("c5.")]:
                    op.unavailable.mark_unavailable("smoke-ice", ct, t, z)
        op.run_once(force_provision=True)
        clock.step(scenario.tick_seconds)
        sim.advance()
    sim.stop()
    op.sampler.sample_once()

    if not any(p.name == "iced-pod" for p in op.cluster.pending_pods()):
        failures.append("the ICE'd-out pod is not pending — the smoke's "
                        "premise broke")
    # FailedScheduling dedup: many passes, ONE event for (pod, code)
    evs = [e for e in op.recorder.events(reason="FailedScheduling")
           if e.object_name == "iced-pod"]
    if len(evs) != 1:
        failures.append(f"FailedScheduling dedup broke: {len(evs)} events "
                        "for one stuck (pod, reason-code)")

    server = start_server(op, 0)
    port = server.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        # 1. /debug/explain over live HTTP attributes the pod to ICE
        doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/explain?pod=iced-pod", timeout=10).read())
        if doc.get("code") != "ice-hold":
            failures.append(f"expected code ice-hold, got {doc.get('code')} "
                            f"({doc.get('reason')})")
        group = doc.get("group") or {}
        if group.get("blame") != "ice":
            failures.append(f"expected ledger blame 'ice', got "
                            f"{group.get('blame')!r}")
        ice_row = next((s for s in group.get("stages", [])
                        if s.get("stage") == "ice"), None)
        if ice_row is None or not ice_row.get("eliminated"):
            failures.append(f"ice stage did not eliminate offerings: "
                            f"{ice_row}")
        elif not ice_row.get("examples"):
            failures.append("ice stage carries no example offerings")
        # the ring's pass list serves too (kpctl explain pass)
        ring_doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/explain", timeout=10).read())
        if not ring_doc.get("passes"):
            failures.append("/debug/explain lists no passes")
        if ring_doc.get("reasons", {}).get("ice-hold", 0) <= 0:
            failures.append(f"ring reasons missing ice-hold: "
                            f"{ring_doc.get('reasons')}")

        # 2. kpctl explain pod renders the waterfall against live HTTP
        sys.path.insert(0, str(Path(__file__).resolve().parent))
        import kpctl
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = kpctl.main(["--server", base, "explain", "pod",
                             "iced-pod"])
        rendered = out.getvalue()
        if rc != 0:
            failures.append(f"kpctl explain pod exited {rc}")
        if "eliminated by ice" not in rendered:
            failures.append("kpctl explain pod did not render the ice "
                            f"elimination row:\n{rendered}")

        # 3. the explain provider (what soak artifacts embed) reports
        vars_doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/vars", timeout=10).read())
        ex = vars_doc.get("providers", {}).get("explain", {})
        if ex.get("reason_ice_hold", 0) <= 0:
            failures.append(f"explain provider histogram missing "
                            f"reason_ice_hold: {ex}")
        if not any(k.startswith("elim_") and v > 0
                   for k, v in ex.items() if isinstance(v, (int, float))):
            failures.append(f"explain provider elimination counters "
                            f"never moved: {ex}")
    finally:
        server.shutdown()

    if failures:
        print("explain smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"explain smoke: OK (iced-pod attributed to the ice stage "
          f"[{ice_row['eliminated']} offerings, e.g. "
          f"{ice_row['examples'][0]}], 1 deduped FailedScheduling event, "
          f"kpctl explain renders, reason histogram "
          f"ice-hold={ex['reason_ice_hold']:g})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
