#!/usr/bin/env python
"""Explain-capture overhead bench (docs/reference/explain.md).

Runs the SAME operator churn loop twice — once with the provisioner's
incremental builder capturing constraint-elimination ledgers
(explain=True, the production default) and once with capture off — and
records the end-to-end per-pass p50 delta. The acceptance bar is the
PR 7 profiler's bound: < 1% e2e p50 regression from explain capture.

    python tools/bench_explain.py [--pods 4000] [--passes 30] \
           [--out EXPLAIN_r11_overhead.json]

Both runs share one process and warm JAX compile caches; the measured
window starts AFTER a warmup pass, and the capture-ON run goes FIRST so
any residual warm-up cost lands on the explain side (overhead reads as
an upper bound, the PROF_r08 discipline).
"""

from __future__ import annotations

import argparse
import gc
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_loop(explain: bool, n_pods: int, n_passes: int) -> dict:
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.solver.incremental import (
        IncrementalProblemBuilder)
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    clock = FakeClock()
    op = Operator(options=Options(registration_delay=0.5),
                  lattice=build_lattice(), cloud=FakeCloud(clock),
                  clock=clock)
    op.provisioner.inc_builder = IncrementalProblemBuilder(explain=explain)
    serial = 0
    for _ in range(n_pods):
        serial += 1
        op.cluster.add_pod(Pod(name=f"b{serial}",
                               requests={"cpu": "250m", "memory": "512Mi"}))
    # warmup: the first pass pays compile + cold caches on both sides
    op.provisioner.provision_once()
    clock.step(1.0)
    times = []
    for i in range(n_passes):
        # ~1% churn per pass: the steady-state shape the delta path and
        # the ledger copy-on-write patching actually serve
        for _ in range(max(n_pods // 100, 1)):
            serial += 1
            op.cluster.add_pod(Pod(name=f"b{serial}",
                                   requests={"cpu": "250m",
                                             "memory": "512Mi"}))
        gc.collect()
        t0 = time.perf_counter()
        op.provisioner.provision_once()
        times.append(time.perf_counter() - t0)
        clock.step(1.0)
    times.sort()
    stats = op.provisioner.explain.stats()
    return {
        "explain": explain,
        "passes": n_passes,
        "e2e_p50_ms": round(times[len(times) // 2] * 1000.0, 3),
        "e2e_p90_ms": round(times[int(len(times) * 0.9)] * 1000.0, 3),
        "ring_passes": stats.get("passes", 0),
        "incremental_builds": op.provisioner.inc_builder.incremental_builds,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=4000)
    ap.add_argument("--passes", type=int, default=30)
    ap.add_argument("--out", default="EXPLAIN_r11_overhead.json")
    args = ap.parse_args()

    on = run_loop(True, args.pods, args.passes)
    off = run_loop(False, args.pods, args.passes)
    delta_pct = (100.0 * (on["e2e_p50_ms"] - off["e2e_p50_ms"])
                 / max(off["e2e_p50_ms"], 1e-9))
    doc = {
        "bench": "explain_capture_overhead",
        "pods": args.pods,
        "capture_on": on, "capture_off": off,
        "e2e_p50_delta_pct": round(delta_pct, 3),
        "bound_pct": 1.0,
        "within_bound": delta_pct < 1.0,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"explain overhead: on={on['e2e_p50_ms']}ms "
          f"off={off['e2e_p50_ms']}ms delta={delta_pct:+.2f}% "
          f"(bound <1%) -> {args.out}")
    return 0 if doc["within_bound"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
