#!/usr/bin/env python
"""Import REAL EC2 data from the reference's generated Go tables into the
framework's JSON catalog format.

The reference compiles scraped reality into Go sources (hack/code/
generators → pkg/fake/zz_generated.describe_instance_types.go hardware
fixtures, pkg/providers/pricing/zz_generated.pricing_aws.go on-demand
prices, pkg/providers/instancetype/zz_generated.{bandwidth,vpclimits}.go
network tables). This tool parses those DATA tables (facts about EC2, not
code) and emits the JSON schema lattice/realdata.py loads, so the solver
can run over real instance types, real ENI/pod-density limits, and real
prices instead of the synthetic catalog.

Usage:
  python tools/import_reference_data.py \
      --reference /root/reference \
      --out karpenter_provider_aws_tpu/lattice/data/reference_catalog.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys


def _s(pattern: str, block: str, default=None):
    m = re.search(pattern, block)
    return m.group(1) if m else default


def _i(pattern: str, block: str, default=0):
    v = _s(pattern, block)
    return int(v) if v is not None else default


def parse_instance_types(path: pathlib.Path) -> dict:
    """pkg/fake/zz_generated.describe_instance_types.go →
    {name: hardware dict}. Each InstanceTypeInfo literal becomes one
    entry; nested info blocks are matched within the entry's extent."""
    text = path.read_text()
    # split on the InstanceType field; each chunk runs to the next one
    chunks = re.split(r'\n\s*InstanceType:\s+aws\.String\("', text)[1:]
    out = {}
    for chunk in chunks:
        name = chunk[: chunk.index('"')]
        block = chunk
        arch = _s(r'SupportedArchitectures:\s+aws\.StringSlice\(\[\]string\{"([^"]+)"', block, "x86_64")
        gpu = re.search(
            r'GpuInfo:.*?Name:\s+aws\.String\("([^"]+)"\).*?'
            r'Manufacturer:\s+aws\.String\("([^"]+)"\).*?'
            r'Count:\s+aws\.Int64\((\d+)\).*?SizeInMiB:\s+aws\.Int64\((\d+)\)',
            block, re.S)
        accel = re.search(
            r'InferenceAcceleratorInfo:.*?Name:\s+aws\.String\("([^"]+)"\).*?'
            r'Manufacturer:\s+aws\.String\("([^"]+)"\).*?Count:\s+aws\.Int64\((\d+)\)',
            block, re.S)
        # trn1's NeuronInfo rides the same InferenceAccelerator shape in
        # newer fixtures; the pinned one models Trainium via GpuInfo-less
        # InferenceAcceleratorInfo too
        out[name] = {
            "name": name,
            "arch": "arm64" if arch == "arm64" else "amd64",
            "cpuManufacturer": (re.search(
                r'ProcessorInfo:.*?Manufacturer:\s+aws\.String\("([^"]+)"\)',
                block, re.S).group(1).lower()
                if re.search(r'ProcessorInfo:.*?Manufacturer', block, re.S)
                else "intel"),
            "hypervisor": _s(r'Hypervisor:\s+aws\.String\("([^"]+)"\)', block, ""),
            "bareMetal": _s(r'BareMetal:\s+aws\.Bool\((\w+)\)', block, "false") == "true",
            "vcpus": _i(r'DefaultVCpus:\s+aws\.Int64\((\d+)\)', block),
            "memoryMiB": _i(r'MemoryInfo:\s+&ec2\.MemoryInfo\{\s*SizeInMiB:\s+aws\.Int64\((\d+)\)', block),
            "enis": _i(r'MaximumNetworkInterfaces:\s+aws\.Int64\((\d+)\)', block),
            "ipv4PerEni": _i(r'Ipv4AddressesPerInterface:\s+aws\.Int64\((\d+)\)', block),
            "localNvmeGb": _i(r'InstanceStorageInfo:.*?TotalSizeInGB:\s+aws\.Int64\((\d+)\)', block),
            "efaCount": _i(r'MaximumEfaInterfaces:\s+aws\.Int64\((\d+)\)', block),
            "gpuName": gpu.group(1) if gpu else None,
            "gpuManufacturer": gpu.group(2).lower() if gpu else None,
            "gpuCount": int(gpu.group(3)) if gpu else 0,
            "gpuMemoryMiB": int(gpu.group(4)) if gpu else 0,
            "acceleratorName": accel.group(1) if accel else None,
            "acceleratorManufacturer": (accel.group(2).lower()
                                        if accel else None),
            "acceleratorCount": int(accel.group(3)) if accel else 0,
        }
    return out


def parse_prices(path: pathlib.Path, region: str = "us-east-1") -> dict:
    """zz_generated.pricing_aws.go → {type: $/hr} for one region."""
    text = path.read_text()
    m = re.search(r'"%s":\s*\{(.*?)\n\t\},' % re.escape(region), text, re.S)
    if m is None:
        raise SystemExit(f"region {region} not in {path}")
    return {t: float(p) for t, p in
            re.findall(r'"([^"]+)":\s*([0-9.]+)', m.group(1))}


def parse_bandwidth(path: pathlib.Path) -> dict:
    text = path.read_text()
    return {t: int(b) for t, b in
            re.findall(r'"([^"]+)":\s+(\d+),', text)}


def parse_vpclimits(path: pathlib.Path) -> dict:
    """zz_generated.vpclimits.go → {type: {enis, ipv4PerEni,
    podEniCount}} (BranchInterface = security-groups-for-pods trunking)."""
    text = path.read_text()
    out = {}
    for m in re.finditer(
            r'"([^"]+)":\s*\{\s*Interface:\s*(\d+),\s*'
            r'IPv4PerInterface:\s*(\d+),\s*'
            r'IsTrunkingCompatible:\s*(\w+),\s*'
            r'BranchInterface:\s*(\d+),', text):
        name, enis, ipv4, trunk, branch = m.groups()
        out[name] = {"enis": int(enis), "ipv4PerEni": int(ipv4),
                     "podEniCount": int(branch) if trunk == "true" else 0}
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--reference", default="/root/reference")
    p.add_argument("--region", default="us-east-1")
    p.add_argument("--out", default=str(
        pathlib.Path(__file__).resolve().parent.parent /
        "karpenter_provider_aws_tpu" / "lattice" / "data" /
        "reference_catalog.json"))
    args = p.parse_args(argv)

    ref = pathlib.Path(args.reference)
    hw = parse_instance_types(
        ref / "pkg" / "fake" / "zz_generated.describe_instance_types.go")
    prices = parse_prices(
        ref / "pkg" / "providers" / "pricing" / "zz_generated.pricing_aws.go",
        args.region)
    bandwidth = parse_bandwidth(
        ref / "pkg" / "providers" / "instancetype" /
        "zz_generated.bandwidth.go")
    vpc = parse_vpclimits(
        ref / "pkg" / "providers" / "instancetype" /
        "zz_generated.vpclimits.go")

    # the reference hardcodes Trainium counts pending DescribeInstanceTypes
    # support (types.go:281-291 awsNeurons) — mirror the same facts
    TRN1_NEURONS = {"trn1.2xlarge": 1, "trn1.32xlarge": 16,
                    "trn1n.32xlarge": 16}
    types = []
    for name, t in sorted(hw.items()):
        if name in TRN1_NEURONS and not t["acceleratorCount"]:
            t = {**t, "acceleratorName": "Trainium",
                 "acceleratorManufacturer": "aws",
                 "acceleratorCount": TRN1_NEURONS[name]}
        v = vpc.get(name, {})
        t = dict(t)
        # vpclimits is the authoritative ENI table (the fixture's
        # NetworkInfo can disagree for multi-card types)
        if v:
            t["enis"] = v["enis"]
            t["ipv4PerEni"] = v["ipv4PerEni"]
            t["podEniCount"] = v.get("podEniCount", 0)
        else:
            t["podEniCount"] = 0
        t["networkBandwidthMbps"] = bandwidth.get(name, 0)
        t["odPrice"] = prices.get(name, 0.0)
        types.append(t)

    doc = {
        "source": "reference zz_generated tables",
        "region": args.region,
        "types": types,
    }
    out = pathlib.Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    print(f"wrote {out}: {len(types)} types "
          f"({sum(1 for t in types if t['odPrice'] > 0)} priced, "
          f"{sum(1 for t in types if t.get('podEniCount'))} trunking)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
