#!/usr/bin/env python
"""CI smoke for the continuous-profiling layer (docs/reference/profiling.md).

What the ci.sh gate asserts here:

1. an operator boots WITH profiling on (sampling profiler running,
   contention accounting live) and a real provisioning pass is driven,
2. over live HTTP, /debug/pprof/profile serves NON-EMPTY folded stacks
   (and the Chrome form parses), /debug/pprof/contention reports the
   instrumented hot locks with non-zero acquisitions,
   /debug/pprof/device parses, and /debug/pprof/captures parses,
3. the live /metrics scrape — now carrying the
   karpenter_lock_wait_seconds histogram family — still lints clean
   (metrics.lint_exposition), and honors Accept-Encoding: gzip,
4. the profiler's self-measured overhead stays under the 5% bound.

Fast by design: small-family lattice, one pass, ~a second of 100 Hz
sampling — a broken endpoint or a mis-rendered histogram fails CI in
seconds instead of riding to the next soak.
"""

from __future__ import annotations

import gzip
import json
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

EXPECT_LOCKS = ("cluster_state", "solver_solve", "writer", "batcher_bucket")


def main() -> int:
    from karpenter_provider_aws_tpu import introspect
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.metrics import lint_exposition
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                  cloud=FakeCloud(clock), clock=clock)
    prof = introspect.enable_profiling(hz=100)
    for i in range(8):
        op.cluster.add_pod(Pod(name=f"smoke-{i}",
                               requests={"cpu": "500m", "memory": "1Gi"}))
    op.settle(max_rounds=20)
    # let the daemon sampler watch the (now idle-ish) process briefly so
    # the folded store is non-vacuous even on a fast machine
    deadline = time.monotonic() + 5.0
    while prof.samples < 20 and time.monotonic() < deadline:
        time.sleep(0.05)

    failures = []
    server = start_server(op, 0)
    port = server.server_address[1]
    try:
        base = f"http://127.0.0.1:{port}"
        # 1. non-empty folded stacks over live HTTP
        folded = urllib.request.urlopen(
            f"{base}/debug/pprof/profile", timeout=10).read().decode()
        stacks = [ln for ln in folded.splitlines()
                  if ln and not ln.startswith("#")]
        if not stacks:
            failures.append("/debug/pprof/profile: empty folded stacks")
        chrome = json.loads(urllib.request.urlopen(
            f"{base}/debug/pprof/profile?format=chrome", timeout=10).read())
        if not chrome.get("traceEvents"):
            failures.append("profile chrome export: no traceEvents")
        # 2. contention counters present for the instrumented hot locks
        cont = json.loads(urllib.request.urlopen(
            f"{base}/debug/pprof/contention", timeout=10).read())
        locks = cont.get("locks", {})
        for name in EXPECT_LOCKS:
            if name not in locks:
                failures.append(f"contention: lock {name!r} not reported")
            elif not locks[name].get("acquisitions"):
                failures.append(f"contention: lock {name!r} has zero "
                                "acquisitions after a real pass")
        for path in ("/debug/pprof/device", "/debug/pprof/captures"):
            try:
                json.loads(urllib.request.urlopen(
                    f"{base}{path}", timeout=10).read())
            except Exception as e:
                failures.append(f"{path}: {type(e).__name__}: {e}")
        # 3. the scrape (with karpenter_lock_wait_seconds) lints clean,
        #    plain AND gzipped
        scrape = urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10).read().decode()
        if "karpenter_lock_wait_seconds" not in scrape:
            failures.append("metrics: karpenter_lock_wait_seconds missing")
        failures.extend(f"metrics lint: {p}"
                        for p in lint_exposition(scrape))
        req = urllib.request.Request(
            f"{base}/metrics", headers={"Accept-Encoding": "gzip"})
        resp = urllib.request.urlopen(req, timeout=10)
        if resp.headers.get("Content-Encoding") != "gzip":
            failures.append("/metrics ignored Accept-Encoding: gzip")
        else:
            # the scrape may drift between reads (counters move), so
            # don't byte-compare — the decompressed body must itself be
            # a lint-clean exposition (catches corrupt/truncated gzip)
            gz_scrape = gzip.decompress(resp.read()).decode()
            failures.extend(f"gzipped metrics lint: {p}"
                            for p in lint_exposition(gz_scrape))
        req = urllib.request.Request(
            f"{base}/debug/vars?series=1",
            headers={"Accept-Encoding": "gzip"})
        resp = urllib.request.urlopen(req, timeout=10)
        if resp.headers.get("Content-Encoding") != "gzip":
            failures.append("/debug/vars?series=1 ignored "
                            "Accept-Encoding: gzip")
        else:
            json.loads(gzip.decompress(resp.read()))
        # 4. self-measured overhead under the documented bound
        pstats = prof.stats()
        if pstats["overhead_pct"] >= 5.0:
            failures.append(
                f"profiler overhead {pstats['overhead_pct']:.2f}% >= 5%")
    finally:
        server.shutdown()
        prof.stop()
        introspect.set_profiler(None)
    if failures:
        print("profiling smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"profiling smoke: OK ({prof.samples} samples, "
          f"{len(stacks)} folded stacks, "
          f"{len(locks)} locks accounted, "
          f"overhead {prof.stats()['overhead_pct']:.2f}%, "
          f"gzip + lint clean)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
