#!/usr/bin/env python
"""CI smoke for the device-resident reconcile microloop (ci.sh gate).

Boots a real Operator on a FORCED 8-device virtual CPU mesh (the same
XLA host-platform sizing the sharded smoke and the test suite use),
drives a seed wave plus small-churn reconcile passes, and asserts the
microloop actually carries the steady state end to end:

1. ENGAGED: every delta pass rode the microloop (``micro_solves`` ==
   ``delta_solves`` > 0) — a microloop silently aborting to the
   standard ladder every pass would otherwise read as a vacuous green;
2. LEG BOUND: on every delta pass the link legs recorded by the
   solver's accounting stay within the bound — ≤2 (one dirty upload,
   one conditional plan fetch) on passes without a tail-bin merge, ≤4
   when the mesh merge refinement re-ran;
3. SKIPPED SYNCS: passes whose pending set did not change produce an
   unchanged plan, and the changed-plan fingerprint suppresses the
   plan fetch (``micro_skipped_syncs`` > 0; a stuck unschedulable pod
   keeps the problem non-empty across those passes);
4. PARITY: on sampled churn passes the microloop-produced plan matches
   a SINGLE-DEVICE full-rebuild referee solve of the same cluster
   inputs byte-exactly (canonical plan JSON, not just cost).

Fast by design: small-family lattice, ~100 pods — mostly shard_map
compile time, not a soak.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# BEFORE jax initializes: force the 8-device virtual CPU mesh
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

MESH_DEVICES = 8
CHURN_PASSES = 10
NOCHURN_PASSES = 3
LEGS_BOUND = 2
LEGS_BOUND_MERGE = 4


def main() -> int:
    from karpenter_provider_aws_tpu.apis import Pod, serde
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.solver import Solver, build_problem
    from karpenter_provider_aws_tpu.utils.clock import FakeClock
    import random

    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    op = Operator(options=Options(registration_delay=1.0,
                                  mesh=str(MESH_DEVICES)),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock)
    referee = Solver(lattice)    # single-device full-rebuild referee
    rng = random.Random(14)
    shapes = [{"cpu": "250m", "memory": "512Mi"},
              {"cpu": "500m", "memory": "1Gi"},
              {"cpu": "1", "memory": "2Gi"}]
    failures = []

    def canon(plan) -> str:
        return json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)

    # full pass: a 48-pod wave, settle to capacity
    for i in range(48):
        op.cluster.add_pod(Pod(name=f"seed-{i}",
                               requests=shapes[i % len(shapes)]))
    op.settle(max_rounds=30)
    if op.cluster.pending_pods():
        failures.append(f"seed wave did not settle: "
                        f"{len(op.cluster.pending_pods())} pending")

    solver = op.solver
    serial = 0
    parity_checked = 0
    delta_pass_legs = []
    for pass_i in range(CHURN_PASSES):
        for _ in range(rng.randint(2, 4)):
            serial += 1
            op.cluster.add_pod(Pod(name=f"churn-{serial}",
                                   requests=shapes[serial % len(shapes)]))
        bound = [p.name for p in op.cluster.snapshot_pods()
                 if p.node_name is not None]
        for name in rng.sample(bound, min(len(bound), rng.randint(1, 2))):
            op.cluster.delete_pod(name)

        referee_problem = None
        if pass_i % 4 == 3:
            referee_problem = build_problem(
                op.cluster.pending_pods(), list(op.node_pools.values()),
                solver.lattice,
                existing=op.cluster.existing_bins(solver.lattice),
                daemonset_pods=op.cluster.daemonset_pods(),
                bound_pods=op.cluster.bound_pods())
        pre = dict(solver.pipeline_stats)
        result = op.provisioner.provision_once()
        post = solver.pipeline_stats
        if post["delta_solves"] > pre["delta_solves"] \
                and post["micro_solves"] > pre["micro_solves"]:
            legs = post["micro_last_legs"]
            merged = post["micro_merge_solves"] > pre["micro_merge_solves"]
            # a merge bin-table regrow retry re-stages and re-fetches:
            # +2 accounted legs per regrow, excused from the bound
            regrows = (post["micro_merge_regrows"]
                       - pre["micro_merge_regrows"])
            bound_now = (LEGS_BOUND_MERGE if merged else LEGS_BOUND) \
                + 2 * regrows
            delta_pass_legs.append(legs)
            if legs > bound_now:
                failures.append(
                    f"pass {pass_i}: {legs} link legs exceeds the "
                    f"{'merge ' if merged else ''}bound {bound_now}")
        if referee_problem is not None and result.plan is not None \
                and result.plan.solver_path == "device":
            # builder-level parity (multiset + cost — pod ordering
            # inside groups may differ between the incremental and the
            # scratch build; byte identity is asserted same-problem
            # below)
            ref = referee.solve(referee_problem)
            plan = result.plan
            got = sorted((n.instance_type, n.zone, len(n.pods))
                         for n in plan.new_nodes)
            want = sorted((n.instance_type, n.zone, len(n.pods))
                          for n in ref.new_nodes)
            if got != want:
                failures.append(
                    f"pass {pass_i}: microloop plan diverged from the "
                    f"single-device full-rebuild referee "
                    f"({got} vs {want})")
            if abs(plan.new_node_cost - ref.new_node_cost) > 1e-6:
                failures.append(
                    f"pass {pass_i}: cost {plan.new_node_cost} != "
                    f"referee {ref.new_node_cost}")
            parity_checked += 1
        op.settle(max_rounds=10)

    # byte-exact parity, same problem: the mesh microloop's plan of a
    # scratch-built problem must equal the single-device full-staging
    # referee's byte for byte — the microloop changes bytes moved,
    # never the answer
    pending = op.cluster.pending_pods()
    if not pending:
        serial += 1
        op.cluster.add_pod(Pod(name=f"churn-{serial}",
                               requests=shapes[0]))
        pending = op.cluster.pending_pods()
    byte_prob = build_problem(
        pending, list(op.node_pools.values()), solver.lattice,
        existing=op.cluster.existing_bins(solver.lattice),
        daemonset_pods=op.cluster.daemonset_pods(),
        bound_pods=op.cluster.bound_pods())
    if canon(solver.solve_delta(byte_prob)) != canon(referee.solve(byte_prob)):
        failures.append("mesh microloop plan is not byte-identical to "
                        "the single-device referee on the same problem")

    # skipped-sync stanza: one impossible pod keeps the problem alive
    # and IDENTICAL across passes — the fingerprint must suppress the
    # plan fetch on the repeat passes
    op.cluster.add_pod(Pod(name="impossible",
                           requests={"cpu": "4000", "memory": "64Ti"}))
    pre_skip = solver.pipeline_stats["micro_skipped_syncs"]
    for _ in range(1 + NOCHURN_PASSES):
        op.provisioner.provision_once()
    skipped = solver.pipeline_stats["micro_skipped_syncs"] - pre_skip
    if skipped < 1:
        failures.append(
            f"fingerprint never suppressed a plan fetch across "
            f"{NOCHURN_PASSES} unchanged passes (skipped={skipped})")

    st = solver.stats()
    if st.get("mesh_devices", 0) != MESH_DEVICES:
        failures.append(f"planned mesh did not reach the solver: "
                        f"{st.get('mesh_devices')}")
    if st.get("delta_solves", 0) == 0:
        failures.append("delta path never engaged (delta_solves=0) — "
                        "last gate reason: "
                        f"{op.provisioner.inc_builder.last_reason!r}")
    if st.get("micro_solves", 0) == 0:
        failures.append("microloop never engaged (micro_solves=0)")
    if st.get("micro_solves", 0) != st.get("delta_solves", 1):
        failures.append(
            f"microloop did not carry every delta pass "
            f"(micro_solves={st.get('micro_solves')} != "
            f"delta_solves={st.get('delta_solves')}; "
            f"aborts={st.get('micro_aborts')})")
    if not delta_pass_legs:
        failures.append("no delta pass recorded link legs (harness bug)")
    if parity_checked == 0:
        failures.append("no parity pass executed (harness bug)")
    if st.get("overlapped_admission", 0) == 0:
        failures.append("admission bookkeeping never overlapped the "
                        "in-flight dispatch")
    # the journal coalescer fed the passes (provisioner stats surface)
    pstats = op.provisioner.stats()
    if pstats.get("journal_takes", 0) == 0:
        failures.append("journal coalescer never fed a pass")

    if failures:
        print("microloop smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"microloop smoke: OK (micro_solves={st['micro_solves']}, "
          f"delta_solves={st['delta_solves']}, "
          f"legs_per_delta_pass={delta_pass_legs}, "
          f"skipped_syncs={skipped}, "
          f"merge_solves={st['micro_merge_solves']}, "
          f"merge_skips={st['micro_merge_skips']}, "
          f"overlapped={st['overlapped_admission']}, "
          f"parity passes={parity_checked})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
