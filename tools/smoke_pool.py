#!/usr/bin/env python
"""CI smoke for the solver failover pool (ci.sh pool gate).

Boots a real Operator against a 2-sidecar unix-socket pool
(parallel/pool.py SolverPool; docs/reference/solver-pool.md), kills one
sidecar mid-churn, and asserts the four things the pool exists to prove:

1. passes KEEP LANDING on the survivor: failovers > 0, the survivor's
   per-endpoint solve count grows, and the local rung never engages
   while a sidecar is healthy (local_solves == 0, no pool-exhausted
   degradation — "host_ffd never becomes the common rung"),
2. a junk-talking endpoint classifies as a sidecar failure and fails
   over (no JSONDecodeError out of a pass),
3. the breaker state is VISIBLE over live HTTP: the kpctl top POOL row
   renders the open breaker, and the karpenter_solver_pool_* gauges ride
   a /metrics scrape that lints clean,
4. the dead sidecar restarted → the half-open probation probe RE-CLOSES
   the breaker (FakeClock-stepped probation) and delegation resumes.

Fast by design: small-family lattice, a handful of passes — the hang
mode's full matrix lives in tests/test_pool.py; this gate is the
end-to-end wire proof.
"""

from __future__ import annotations

import json
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def main() -> int:
    import tempfile

    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.cli import start_server
    from karpenter_provider_aws_tpu.cloud import FakeCloud
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.metrics import lint_exposition
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.parallel.sidecar import ChaosSidecar
    from karpenter_provider_aws_tpu.solver import Solver
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    failures = []
    clock = FakeClock()
    lattice = build_lattice([s for s in build_catalog()
                             if s.family in ("m5", "c5")])
    pool_dir = tempfile.mkdtemp(prefix="smoke-pool-")
    s0 = ChaosSidecar(Solver(lattice),
                      f"unix:{pool_dir}/sidecar0.sock").start()
    s1 = ChaosSidecar(Solver(lattice),
                      f"unix:{pool_dir}/sidecar1.sock").start()
    # deadline wide enough for the first pass's XLA compile (kill/junk
    # failures are fast-fail, so the smoke's failover phases never wait
    # it out; the hang matrix with short deadlines lives in test_pool)
    op = Operator(options=Options(registration_delay=0.5,
                                  solver_address=f"{s0.address},{s1.address}",
                                  solver_solve_deadline=10.0),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock)
    serial = 0

    def churn(n_passes: int) -> None:
        nonlocal serial
        for _ in range(n_passes):
            for _ in range(2):
                serial += 1
                op.cluster.add_pod(Pod(name=f"pl{serial}",
                                       requests={"cpu": "500m",
                                                 "memory": "1Gi"}))
            op.run_once(force_provision=True)
            clock.step(2.0)

    # phase 1: both sidecars healthy — delegation, no failovers
    churn(3)
    pst = op.solver.pool_stats()
    if pst["delegated_solves"] == 0:
        failures.append("no pass delegated to the pool while healthy")
    if pst["failovers"] != 0:
        failures.append(f"failovers={pst['failovers']} with a healthy pool")

    # phase 2: kill sidecar 0 mid-churn — survivor carries every pass
    s0.kill()
    ep1_before = pst["ep1_solves"]
    churn(4)
    pst = op.solver.pool_stats()
    if pst["failovers"] == 0:
        failures.append("sidecar killed but the pool never failed over")
    if pst["ep1_solves"] <= ep1_before:
        failures.append("passes did not land on the surviving sidecar")
    if pst["local_solves"] != 0:
        failures.append(f"local rung engaged {pst['local_solves']}x "
                        "while a sidecar was healthy")
    if op.solver.degraded_counts.get("pool-exhausted"):
        failures.append("pool-exhausted degradation with a healthy "
                        "endpoint in the pool")
    if pst["ep0_state"] != 2:
        failures.append(f"dead sidecar's breaker not open "
                        f"(state={pst['ep0_state']})")

    # phase 3: junk-talking survivor endpoint — still no decode error
    # out of a pass (the junk classifies as a sidecar failure); with
    # ep0 dead AND ep1 junking this is a full blackout: the local rung
    # is the correct final answer
    s1.set_junk(True)
    try:
        churn(1)
    except Exception as e:   # noqa: BLE001 - any escape is the failure
        failures.append(f"junk response escaped the pass: "
                        f"{type(e).__name__}: {e}")
    s1.set_junk(False)
    pst = op.solver.pool_stats()
    if pst["local_solves"] == 0:
        failures.append("full blackout (dead + junk) did not engage "
                        "the local final rung")
    if not op.solver.degraded_counts.get("pool-exhausted"):
        failures.append("blackout pass not counted pool-exhausted "
                        f"(degraded_counts={op.solver.degraded_counts})")

    # phase 4: the live HTTP surfaces, with the breaker still open
    server = start_server(op, 0)
    port = server.server_address[1]
    try:
        base = f"http://127.0.0.1:{port}"
        doc = json.loads(urllib.request.urlopen(
            f"{base}/debug/vars", timeout=10).read())
        sp = doc.get("providers", {}).get("solver_pool", {})
        if not sp or sp.get("endpoints") != 2:
            failures.append(f"solver_pool provider wrong over HTTP: {sp}")
        import kpctl
        top = "\n".join(kpctl._render_top(doc, base))
        pool_rows = [ln for ln in top.splitlines()
                     if ln.startswith("POOL")]
        if not pool_rows:
            failures.append("kpctl top renders no POOL row")
        elif "open" not in pool_rows[0]:
            failures.append(f"POOL row hides the open breaker: "
                            f"{pool_rows[0]!r}")
        scrape = urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10).read().decode()
        for series in ("karpenter_solver_pool_endpoints",
                       "karpenter_solver_pool_healthy_endpoints",
                       "karpenter_solver_pool_failovers",
                       "karpenter_solver_pool_breaker_state"):
            if series not in scrape:
                failures.append(f"/metrics missing {series}")
        if 'karpenter_solver_pool_breaker_state{endpoint="' not in scrape:
            failures.append("breaker-state gauge carries no endpoint label")
        lint = lint_exposition(scrape)
        if lint:
            failures.append(f"live scrape lint: {lint[:3]}")
    finally:
        server.shutdown()

    # phase 5: restart the dead sidecar → probation elapses on the
    # stepped clock → the half-open probe re-closes the breaker and
    # delegation resumes on it
    s0.restart()
    clock.step(120.0)
    op.solver.check_endpoints()
    pst = op.solver.pool_stats()
    if pst["ep0_state"] != 0:
        failures.append(f"restarted sidecar's breaker did not re-close "
                        f"(state={pst['ep0_state']})")
    ep0_before = pst["ep0_solves"]
    churn(3)
    pst = op.solver.pool_stats()
    if pst["ep0_solves"] <= ep0_before:
        failures.append("no pass landed on the recovered sidecar")
    if pst["healthy"] != 2:
        failures.append(f"pool not fully healthy at exit "
                        f"({pst['healthy']}/2)")

    op.solver.close()
    s0.kill()
    s1.kill()
    if failures:
        print("pool smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"pool smoke: OK (delegated={pst['delegated_solves']}, "
          f"failovers={pst['failovers']}, "
          f"local={pst['local_solves']}, "
          f"breakers closed,closed, "
          f"recovered ep0 solves={pst['ep0_solves']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
