"""graftlint rules: one fixture-testable checker class per invariant.

Every rule consumes a parsed module (``check_module(tree, relpath,
source)``) and returns ``Violation``s, so tests can compile violating
and clean snippets from strings without touching the repo tree
(tests/test_lint.py). File scoping lives in ``applies_to`` — the runner
filters, the checker itself never does, which is what makes the
fixtures honest.

The rules are deliberately name-heuristic where they have to be (a
Python AST cannot know an object's type): a ``with`` target counts as a
lock when its terminal identifier looks like one (``_lock``,
``_solve_lock``, ``_locks[kind]``, ``_cond``), and taint tracking in
the frozen-envelope rule is lexical, not flow-sensitive. False
negatives are possible; false positives go to the baseline with a
stated reason (baseline.py).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

PACKAGE = "karpenter_provider_aws_tpu"


@dataclass(frozen=True)
class Violation:
    rule: str
    file: str          # repo-relative posix path
    line: int
    context: str       # enclosing def qualname, or "<module>"
    call: str          # the resolved offending call/symbol
    message: str

    def __str__(self) -> str:
        return (f"{self.file}:{self.line}: [{self.rule}] {self.message} "
                f"(in {self.context})")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for an attribute chain rooted at a Name, else None
    (string-literal receivers like ``", ".join`` resolve to None — they
    can never be lock handles or module calls)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def module_aliases(tree: ast.AST) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(module aliases, from-imported names): ``import time as _time``
    maps ``_time`` -> ``time``; ``from datetime import datetime`` maps
    ``datetime`` -> ``datetime.datetime`` — so a renamed import cannot
    dodge a rule."""
    mods: Dict[str, str] = {}
    names: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mods[a.asname] = a.name
                else:
                    mods[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                names[a.asname or a.name] = f"{node.module}.{a.name}"
    return mods, names


def resolve_call(func: ast.AST, mods: Dict[str, str],
                 names: Dict[str, str]) -> Optional[str]:
    """The canonical dotted name of a call target with import aliases
    substituted: ``_time.monotonic`` -> ``time.monotonic``."""
    d = dotted(func)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    if head in names:
        d = names[head] + (("." + rest) if rest else "")
    elif head in mods:
        d = mods[head] + (("." + rest) if rest else "")
    return d


class _ContextVisitor(ast.NodeVisitor):
    """Base visitor tracking the enclosing class/def qualname."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def context(self) -> str:
        return ".".join(self._stack) or "<module>"

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


class Rule:
    name = "rule"

    def applies_to(self, relpath: str) -> bool:
        raise NotImplementedError

    def check_module(self, tree: ast.AST, relpath: str,
                     source: str = "") -> List[Violation]:
        raise NotImplementedError


# ---- rule 1: clock discipline ---------------------------------------------

class ClockRule(Rule):
    """No raw ``time.time()``/``time.monotonic()``/``time.sleep()``/
    ``datetime.now()`` outside ``utils/clock.py``: everything on a
    FakeClock-reachable path must read the injected ``utils/clock``
    Clock, or deterministic-stratum tests and ``--weather`` replay can
    observe wall time. Genuinely wall-clock-only sites (process uptime,
    artifact timestamps) are baselined with a reason.

    ``time.perf_counter`` stays legal: interval self-measurement
    (profiler overhead, lock wait timing) is about the real host, never
    about simulated time."""

    name = "clock-discipline"
    BANNED = {
        "time.time", "time.monotonic", "time.sleep",
        "datetime.now", "datetime.utcnow",
        "datetime.datetime.now", "datetime.datetime.utcnow",
    }
    EXEMPT = {f"{PACKAGE}/utils/clock.py"}

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(PACKAGE + "/") and relpath not in self.EXEMPT

    def check_module(self, tree, relpath, source=""):
        mods, names = module_aliases(tree)
        rule = self

        class V(_ContextVisitor):
            out: List[Violation] = []

            def visit_Call(self, node):
                d = resolve_call(node.func, mods, names)
                if d in rule.BANNED:
                    self.out.append(Violation(
                        rule.name, relpath, node.lineno, self.context, d,
                        f"raw wall-clock call {d}() — route through the "
                        "injected utils/clock Clock (or baseline a "
                        "wall-clock-only site with a reason)"))
                self.generic_visit(node)

        v = V()
        v.out = []
        v.visit(tree)
        return v.out


# ---- rule 2: lock discipline ----------------------------------------------

_LOCKISH = re.compile(r"(^|_)(lock|locks|rlock|mutex|cond|condition)$", re.I)
_SOLVE_LOCKISH = re.compile(r"solve_lock", re.I)


def _lock_name(expr: ast.AST) -> Optional[str]:
    """The terminal identifier of a ``with`` target that might be a
    lock: ``self._lock`` -> ``_lock``, ``self._locks[kind]`` ->
    ``_locks``, ``lock`` -> ``lock``."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


class LockRule(Rule):
    """No blocking call lexically inside a ``with <instrumented-lock>``
    body (PR 7/8 spent two PRs profiling convoys out of exactly these
    spans), and no ``stats()`` method acquiring the solver solve lock
    (the PR 5 pin: a snapshot must never queue behind a device solve).

    Blocking means: any ``.sleep()`` (including clock sleeps — a
    FakeClock step under a lock is still a design smell), ``.result()``
    (Future waits), ``urlopen``/``requests.*`` (HTTP), and
    ``.block_until_ready()`` (device dispatch sync). Calls inside
    nested ``def``/``lambda`` bodies run later, outside the hold, and
    are not flagged."""

    name = "lock-discipline"
    _REQUESTS = re.compile(r"^requests\.(get|post|put|patch|delete|head|"
                           r"request|Session)\b")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(PACKAGE + "/")

    @classmethod
    def _blocking(cls, d: str) -> bool:
        return (d == "time.sleep" or d.endswith(".sleep")
                or d.endswith(".result")
                or d == "urlopen" or d.endswith(".urlopen")
                or d.endswith(".block_until_ready")
                or bool(cls._REQUESTS.match(d)))

    def check_module(self, tree, relpath, source=""):
        mods, names = module_aliases(tree)
        rule = self

        class V(_ContextVisitor):
            def __init__(self):
                super().__init__()
                self.out: List[Violation] = []
                self._held: List[str] = []   # lock-ish with nesting
                self._in_stats = 0

            def visit_FunctionDef(self, node):
                # a nested def's body executes outside the lexical hold
                held, self._held = self._held, []
                self._in_stats += node.name == "stats"
                super().visit_FunctionDef(node)
                self._in_stats -= node.name == "stats"
                self._held = held

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                held, self._held = self._held, []
                self.generic_visit(node)
                self._held = held

            def visit_With(self, node):
                locks = [n for n in (_lock_name(i.context_expr)
                                     for i in node.items)
                         if n and _LOCKISH.search(n)]
                for n in locks:
                    if self._in_stats and _SOLVE_LOCKISH.search(n):
                        self.out.append(Violation(
                            rule.name, relpath, node.lineno, self.context,
                            f"stats:{n}",
                            "stats() acquires the solver solve lock — a "
                            "snapshot must never queue behind an in-flight "
                            "device solve"))
                self._held.extend(locks)
                self.generic_visit(node)
                del self._held[len(self._held) - len(locks):]

            visit_AsyncWith = visit_With

            def visit_Call(self, node):
                d = resolve_call(node.func, mods, names)
                if d:
                    if self._held and rule._blocking(d):
                        self.out.append(Violation(
                            rule.name, relpath, node.lineno, self.context, d,
                            f"blocking call {d}() while holding lock "
                            f"{self._held[-1]!r} — move it outside the "
                            "hold (the out-of-lock fan-out discipline)"))
                    if self._in_stats and d.endswith(".acquire") \
                            and _SOLVE_LOCKISH.search(d):
                        self.out.append(Violation(
                            rule.name, relpath, node.lineno, self.context,
                            f"stats:{d}",
                            "stats() acquires the solver solve lock"))
                self.generic_visit(node)

        v = V()
        v.visit(tree)
        return v.out


# ---- rule 3: determinism --------------------------------------------------

class DeterminismRule(Rule):
    """``weather/`` and ``solver/`` must be pure functions of their
    seeds: no module-level ``random.*`` (the process-global RNG any
    import can perturb), no unseeded ``Random()``, no ``numpy.random``
    module functions, no ``datetime.now()``. The weather contract —
    every decision a pure function of (scenario, seed, tick) — and the
    solver's replayable plans both die the moment shared RNG state
    leaks in."""

    name = "determinism"
    SCOPES = (f"{PACKAGE}/weather/", f"{PACKAGE}/solver/")
    _DATETIME = {"datetime.now", "datetime.utcnow",
                 "datetime.datetime.now", "datetime.datetime.utcnow"}

    def __init__(self, scopes: Optional[Tuple[str, ...]] = None):
        if scopes is not None:
            self.SCOPES = scopes

    def applies_to(self, relpath: str) -> bool:
        return any(relpath.startswith(s) for s in self.SCOPES)

    def check_module(self, tree, relpath, source=""):
        mods, names = module_aliases(tree)
        rule = self

        class V(_ContextVisitor):
            def __init__(self):
                super().__init__()
                self.out: List[Violation] = []

            def _flag(self, node, d, msg):
                self.out.append(Violation(
                    rule.name, relpath, node.lineno, self.context, d, msg))

            def visit_Call(self, node):
                d = resolve_call(node.func, mods, names)
                if d:
                    if d in rule._DATETIME:
                        self._flag(node, d,
                                   f"{d}() in a determinism-critical "
                                   "module — wall time is not a function "
                                   "of (scenario, seed, tick)")
                    elif d in ("random.Random", "random.SystemRandom"):
                        if not node.args and not node.keywords:
                            self._flag(node, d,
                                       "unseeded Random() — derive the "
                                       "seed from (scenario, seed, tick)")
                    elif d.startswith("random."):
                        self._flag(node, d,
                                   f"module-level {d}() uses the "
                                   "process-global RNG — use a seeded "
                                   "Random instance")
                    elif d.startswith(("numpy.random.", "np.random.")) \
                            and not d.endswith((".default_rng",
                                                ".Generator")):
                        self._flag(node, d,
                                   f"{d}() uses numpy's global RNG — "
                                   "use a seeded Generator")
                self.generic_visit(node)

        v = V()
        v.visit(tree)
        return v.out


# ---- rule 4: frozen-envelope discipline -----------------------------------

class FrozenEnvelopeRule(Rule):
    """Watch/informer handler code must not mutate event envelopes:
    since PR 8 every stored envelope is ONE frozen object shared by the
    store, the history ring, and every subscriber queue — a handler
    mutating it would corrupt every other consumer. Mutation requires a
    ``copy.deepcopy`` thaw first (deepcopy returns a private mutable
    copy by design).

    A handler is any function in the scoped modules whose parameters
    include both ``obj`` and ``old`` (the ``Handler`` signature in
    kube/informer.py) or whose name starts with ``_on_``. Taint is
    lexical: the two envelope params, plus any name assigned from a
    subscript/attribute of a tainted name; a deepcopy assignment
    clears the taint."""

    name = "frozen-envelope"
    SCOPES = (f"{PACKAGE}/kube/informer.py", f"{PACKAGE}/operator/sync.py",
              f"{PACKAGE}/kube/eventsink.py")
    MUTATORS = {"append", "extend", "insert", "pop", "popitem", "remove",
                "clear", "update", "setdefault", "sort", "reverse",
                "add", "discard"}
    _THAWS = {"copy.deepcopy", "deepcopy"}

    def __init__(self, scopes: Optional[Tuple[str, ...]] = None):
        if scopes is not None:
            self.SCOPES = scopes

    def applies_to(self, relpath: str) -> bool:
        return relpath in self.SCOPES

    @staticmethod
    def _root(node: ast.AST) -> Optional[str]:
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    @staticmethod
    def _own_exprs(st: ast.stmt):
        """The statement's OWN expressions (test/iter/value/targets...),
        never the statement lists nested under it — those recurse
        separately so taint state is updated in source order."""
        for _field, val in ast.iter_fields(st):
            for v in (val if isinstance(val, list) else [val]):
                if isinstance(v, ast.expr):
                    yield v
                elif isinstance(v, ast.withitem):
                    yield v.context_expr
                    if v.optional_vars is not None:
                        yield v.optional_vars

    def check_module(self, tree, relpath, source=""):
        rule = self
        mods, names = module_aliases(tree)
        out: List[Violation] = []

        def check_handler(fn: ast.FunctionDef, qual: str) -> None:
            params = {a.arg for a in (fn.args.args + fn.args.kwonlyargs)}
            tainted: Set[str] = params & {"obj", "old"}
            if not tainted:
                return

            def flag(node, call, msg):
                out.append(Violation(rule.name, relpath, node.lineno,
                                     qual, call, msg))

            def check_expr(expr: ast.expr) -> None:
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in rule.MUTATORS \
                            and rule._root(node.func.value) in tainted:
                        flag(node,
                             f"{rule._root(node.func.value)}."
                             f"{node.func.attr}",
                             f"mutator .{node.func.attr}() on a frozen "
                             "event envelope — deepcopy-thaw first")

            def scan(stmts: List[ast.stmt]) -> None:
                # SOURCE ORDER: taint transfer must see statements in
                # execution order, or a later rebind would retroactively
                # launder an earlier mutation (and vice versa) — the
                # reason this is not an ast.walk
                for st in stmts:
                    if isinstance(st, (ast.Assign, ast.AnnAssign)):
                        targets = (st.targets
                                   if isinstance(st, ast.Assign)
                                   else [st.target])
                        val = st.value
                        if val is not None:
                            check_expr(val)
                        for t in targets:
                            if isinstance(t, (ast.Subscript, ast.Attribute)) \
                                    and rule._root(t) in tainted:
                                flag(st, f"{rule._root(t)}[...]=",
                                     "item/attribute assignment on a "
                                     "frozen event envelope — "
                                     "deepcopy-thaw first")
                        name_targets = {t.id for t in targets
                                        if isinstance(t, ast.Name)}
                        is_thaw = (isinstance(val, ast.Call) and
                                   resolve_call(val.func, mods, names)
                                   in rule._THAWS)
                        if is_thaw or val is None:
                            tainted.difference_update(name_targets)
                        elif rule._root(val) in tainted:
                            tainted.update(name_targets)
                        else:
                            tainted.difference_update(name_targets)
                    elif isinstance(st, ast.AugAssign):
                        check_expr(st.value)
                        if rule._root(st.target) in tainted:
                            flag(st, f"{rule._root(st.target)}+=",
                                 "augmented assignment on a frozen event "
                                 "envelope — deepcopy-thaw first")
                    elif isinstance(st, ast.Delete):
                        for t in st.targets:
                            if isinstance(t, ast.Subscript) \
                                    and rule._root(t) in tainted:
                                flag(st, f"del {rule._root(t)}[...]",
                                     "del on a frozen event envelope — "
                                     "deepcopy-thaw first")
                    else:
                        for e in rule._own_exprs(st):
                            check_expr(e)
                        for field in ("body", "orelse", "finalbody"):
                            sub = getattr(st, field, None)
                            if sub:
                                scan(sub)
                        for h in getattr(st, "handlers", None) or ():
                            scan(h.body)

            scan(fn.body)

        class V(_ContextVisitor):
            def visit_FunctionDef(self, node):
                self._stack.append(node.name)
                if node.name.startswith("_on_") or \
                        {"obj", "old"} <= {a.arg for a in node.args.args}:
                    check_handler(node, self.context)
                self.generic_visit(node)
                self._stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

        V().visit(tree)
        return out


# ---- rule 5: metrics discipline -------------------------------------------

class MetricsRule(Rule):
    """Every ``karpenter_*`` series name a registry call uses anywhere
    in the package must be DECLARED in metrics.py (the one catalog
    dashboards port from) and PRESENT in the regenerated
    docs/reference/metrics.md — an undeclared series is invisible to
    the docs generator and to ``wire_core_metrics`` consumers; a
    declared-but-undocumented one means the docs are stale."""

    name = "metrics-discipline"
    METRICS_PY = f"{PACKAGE}/metrics.py"
    _KINDS = {"counter", "gauge", "histogram", "get"}

    def __init__(self, declared: Optional[Set[str]] = None,
                 docs_text: Optional[str] = None):
        self.declared = declared if declared is not None else set()
        self.docs_text = docs_text if docs_text is not None else ""

    @staticmethod
    def collect_declared(metrics_source: str) -> Set[str]:
        """Series names declared by metrics.py: the literal first arg of
        every counter/gauge/histogram registration call."""
        tree = ast.parse(metrics_source)
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "gauge", "histogram") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
        return out

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith(PACKAGE + "/")
                and relpath != self.METRICS_PY)

    def check_module(self, tree, relpath, source=""):
        out: List[Violation] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._KINDS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if not name.startswith("karpenter_"):
                continue
            ctx = "<module>"
            if name not in self.declared:
                out.append(Violation(
                    self.name, relpath, node.lineno, ctx, name,
                    f"series {name} is not declared in metrics.py — "
                    "add it to wire_core_metrics/wire_lattice_metrics"))
            elif self.docs_text and name not in self.docs_text:
                out.append(Violation(
                    self.name, relpath, node.lineno, ctx, name,
                    f"series {name} is missing from docs/reference/"
                    "metrics.md — run tools/gen_docs.py"))
        return out


# ---- rule 6: reason-code discipline ---------------------------------------

class ReasonRule(Rule):
    """Every unschedulable reason-code string literal must be DECLARED
    in solver/taxonomy.py — the bounded enum events, metrics labels,
    ``NodePlan.unschedulable``, and the sidecar wire all carry
    (docs/reference/explain.md). Same declaration-lockstep discipline
    as the metrics rule: an undeclared literal is invisible to
    ``code_of`` (it parses as "uncoded") and to the docs table.

    Flagged sites: the first argument of any ``reason(...)`` /
    ``taxonomy.reason(...)`` call (the taxonomy's constructor — the
    assert there catches it at runtime, this catches it at lint time),
    and any LITERAL ``code=`` keyword (metric label / explain field).
    Variables are never flagged — the taxonomy constructor's assert
    owns the dynamic path."""

    name = "reason-code"
    TAXONOMY_PY = f"{PACKAGE}/solver/taxonomy.py"

    def __init__(self, declared: Optional[Set[str]] = None):
        self.declared = declared if declared is not None else set()

    @staticmethod
    def collect_declared(taxonomy_source: str) -> Set[str]:
        """Codes declared by solver/taxonomy.py: every module-level
        ``NAME = "literal"`` string constant assignment — EXCEPT the
        ``UNCODED`` parse-failure sentinel, which is deliberately not a
        member of the taxonomy (reason('uncoded', ...) must stay a lint
        error exactly like any other undeclared literal)."""
        tree = ast.parse(taxonomy_source)
        out: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and not any(isinstance(t, ast.Name)
                                and t.id == "UNCODED"
                                for t in node.targets):
                out.add(node.value.value)
        return out

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith(PACKAGE + "/")
                and relpath != self.TAXONOMY_PY)

    def check_module(self, tree, relpath, source=""):
        mods, names = module_aliases(tree)
        out: List[Violation] = []

        class V(_ContextVisitor):
            def visit_Call(v, node):
                d = resolve_call(node.func, mods, names)
                tail = d.rsplit(".", 1)[-1] if d else None
                if tail == "reason" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and node.args[0].value not in self.declared:
                    out.append(Violation(
                        self.name, relpath, node.lineno, v.context,
                        node.args[0].value,
                        f"reason code {node.args[0].value!r} is not "
                        "declared in solver/taxonomy.py — add the "
                        "constant (and the docs table entry)"))
                for kw in node.keywords:
                    if kw.arg == "code" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str) \
                            and kw.value.value not in self.declared:
                        out.append(Violation(
                            self.name, relpath, node.lineno, v.context,
                            kw.value.value,
                            f"code= label literal {kw.value.value!r} is "
                            "not declared in solver/taxonomy.py"))
                v.generic_visit(node)

        V().visit(tree)
        return out


# ---- rule 7: bounded-resource discipline ----------------------------------

class BoundedResourceRule(Rule):
    """Every bounded buffer a production module constructs —
    ``deque(maxlen=...)`` is the repo's ring/queue idiom — must be
    visible to the saturation observatory (introspect/headroom.py):
    the module either defines a ``headroom_probe`` method/function
    (the convention every instrumented structure follows — the operator
    wires it into the HeadroomRegistry) or calls ``register_probe``
    directly. A bound without a probe is a silent cliff: the structure
    fills, drops, and nothing forecast it (docs/reference/headroom.md).

    The check is module-granular by design: a module that exposes ONE
    probe for several internal rings (slo.py's latency+cost pair) is
    compliant — the probe contract reports the fullest. A genuinely
    probe-free bound (a test fake's history buffer) goes to the
    baseline with a reason, same as every other rule."""

    name = "bounded-resource"
    _DEQUE = {"collections.deque", "deque"}

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(PACKAGE + "/")

    @staticmethod
    def _has_probe(tree: ast.AST, mods, names) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "headroom_probe":
                return True
            if isinstance(node, ast.Call):
                d = resolve_call(node.func, mods, names)
                if d and d.rsplit(".", 1)[-1] == "register_probe":
                    return True
        return False

    def check_module(self, tree, relpath, source=""):
        mods, names = module_aliases(tree)
        rule = self
        probed = self._has_probe(tree, mods, names)

        class V(_ContextVisitor):
            def __init__(self):
                super().__init__()
                self.out: List[Violation] = []

            def visit_Call(self, node):
                d = resolve_call(node.func, mods, names)
                bounded = False
                if d in rule._DEQUE:
                    # deque(iterable, maxlen) positional, or maxlen= kw
                    # with a non-None bound (maxlen=None is unbounded —
                    # a different problem, not this rule's)
                    if len(node.args) >= 2:
                        bounded = True
                    for kw in node.keywords:
                        if kw.arg == "maxlen" and not (
                                isinstance(kw.value, ast.Constant)
                                and kw.value.value is None):
                            bounded = True
                if bounded and not probed:
                    self.out.append(Violation(
                        rule.name, relpath, node.lineno, self.context,
                        "deque(maxlen)",
                        "bounded buffer with no headroom probe — give "
                        "the module a headroom_probe() (or call "
                        "register_probe) so the saturation observatory "
                        "can forecast it, or baseline with a reason"))
                self.generic_visit(node)

        v = V()
        v.visit(tree)
        return v.out


def default_rules(repo_root) -> List[Rule]:
    """The seven project rules, wired against the real metrics catalog,
    docs, and reason taxonomy (run.py's configuration)."""
    from pathlib import Path
    root = Path(repo_root)
    declared: Set[str] = set()
    docs_text = ""
    mp = root / PACKAGE / "metrics.py"
    if mp.exists():
        declared = MetricsRule.collect_declared(mp.read_text())
    docs = root / "docs" / "reference" / "metrics.md"
    if docs.exists():
        docs_text = docs.read_text()
    codes: Set[str] = set()
    tp = root / PACKAGE / "solver" / "taxonomy.py"
    if tp.exists():
        codes = ReasonRule.collect_declared(tp.read_text())
    return [ClockRule(), LockRule(), DeterminismRule(),
            FrozenEnvelopeRule(),
            MetricsRule(declared=declared, docs_text=docs_text),
            ReasonRule(declared=codes),
            BoundedResourceRule()]
