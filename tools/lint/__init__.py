"""graftlint: the project-invariant static-analysis suite.

Zero-dependency AST checkers for the correctness properties the repo's
regression tests bled for but convention alone enforces (docs/reference/
linting.md): clock discipline (no raw wall-clock calls outside
utils/clock.py), lock discipline (no blocking calls under an
instrumented lock; stats() never takes the solve lock), determinism
(weather/ and solver/ never touch the global RNG or wall time), the
frozen-envelope contract (watch handlers never mutate event objects
without a deepcopy thaw), and metrics discipline (every karpenter_*
series used in code is declared in metrics.py and documented).

    python tools/lint/run.py --check     # the ci.sh gate

The runtime half — the lock-order witness that turns the same lock
discipline into a standing deadlock detector — lives in
karpenter_provider_aws_tpu/introspect/contention.py.
"""
