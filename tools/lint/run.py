#!/usr/bin/env python
"""graftlint runner: the ci.sh static-analysis gate.

    python tools/lint/run.py --check              # gate: exit 1 on any
                                                  # unbaselined violation,
                                                  # stale baseline entry,
                                                  # or reasonless entry
    python tools/lint/run.py --update-baseline    # accept current
                                                  # violations (new
                                                  # entries get an EMPTY
                                                  # reason — --check
                                                  # stays red until a
                                                  # human writes one)
    python tools/lint/run.py --root DIR           # lint another tree
                                                  # (tests use tmp trees)

Scans ``karpenter_provider_aws_tpu/**/*.py`` under ``--root`` (tools/
and tests/ are intentionally out of scope: soak/bench drive wall time
and the global RNG legitimately). Rules: docs/reference/linting.md.
"""

from __future__ import annotations

import argparse
import ast
import sys
from pathlib import Path
from typing import List, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from lint import baseline as baseline_mod          # noqa: E402
from lint.rules import PACKAGE, Violation, default_rules   # noqa: E402

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def iter_modules(root: Path):
    pkg = root / PACKAGE
    for p in sorted(pkg.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p, p.relative_to(root).as_posix()


def run_checks(root: Path, rules=None) -> Tuple[List[Violation], List[str]]:
    """(violations, parse errors) across the package tree."""
    rules = rules if rules is not None else default_rules(root)
    violations: List[Violation] = []
    errors: List[str] = []
    for path, rel in iter_modules(root):
        applicable = [r for r in rules if r.applies_to(rel)]
        if not applicable:
            continue
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            errors.append(f"{rel}: {e}")
            continue
        for r in applicable:
            violations.extend(r.check_module(tree, rel, src))
    violations.sort(key=lambda v: (v.file, v.line, v.rule))
    return violations, errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="gate mode (the default behavior; spelled out "
                         "in ci.sh for clarity)")
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="tree to lint (default: this repo)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline path (default: tools/lint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every violation")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write unbaselined violations into the baseline "
                         "(new entries carry an empty reason — fill it "
                         "in or --check stays red)")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    violations, errors = run_checks(root)
    for e in errors:
        print(f"graftlint: parse error: {e}", file=sys.stderr)

    entries = [] if args.no_baseline else baseline_mod.load(args.baseline)
    unbaselined, used, stale = baseline_mod.apply(violations, entries)
    base_problems = baseline_mod.problems(entries, stale)

    if args.update_baseline:
        new = []
        seen = set()
        for v in unbaselined:
            key = (v.rule, v.file, v.call)
            if key in seen:
                continue
            seen.add(key)
            new.append({"rule": v.rule, "file": v.file, "call": v.call,
                        "reason": ""})
        baseline_mod.save(args.baseline, used + new)
        print(f"graftlint: baseline updated — {len(used)} kept, "
              f"{len(new)} added (empty reasons: fill them in), "
              f"{len(stale)} stale dropped")
        return 0

    for v in unbaselined:
        print(str(v))
    for p in base_problems:
        print(f"graftlint: {p}")
    n_checked = sum(1 for _ in iter_modules(root))
    status = "clean" if not (unbaselined or base_problems or errors) \
        else "FAIL"
    print(f"graftlint: {n_checked} modules, "
          f"{len(violations)} violations ({len(violations) - len(unbaselined)}"
          f" baselined), {len(base_problems)} baseline problems — {status}")
    return 0 if status == "clean" else 1


if __name__ == "__main__":
    raise SystemExit(main())
