"""graftlint baseline: the allowlist of accepted violations.

A baseline entry suppresses matching violations — it is how a genuinely
wall-clock-only site (process uptime, the CLI serve deadline) coexists
with the clock rule. Every entry must carry a non-empty ``reason``
(``--check`` fails otherwise: an allowlist nobody can audit is worse
than none), and every entry must still match at least one live
violation (a stale entry means the violation was fixed — delete the
entry, don't let the allowlist rot).

Matching is identity-based, not line-based: ``(rule, file, call[,
context])`` — line numbers churn on every edit; the thing being allowed
does not. An entry that omits ``context`` matches the call anywhere in
the file (one entry covers the three serve-deadline sites in cli.py).

Format (tools/lint/baseline.json):

    {"version": 1,
     "entries": [{"rule": "clock-discipline",
                  "file": "karpenter_provider_aws_tpu/cli.py",
                  "call": "time.monotonic",
                  "reason": "why this is allowed"}]}
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

from .rules import Violation

VERSION = 1


def load(path) -> List[Dict]:
    p = Path(path)
    if not p.exists():
        return []
    doc = json.loads(p.read_text())
    if doc.get("version") != VERSION:
        raise ValueError(f"{path}: unsupported baseline version "
                         f"{doc.get('version')!r} (want {VERSION})")
    entries = doc.get("entries", [])
    for e in entries:
        for k in ("rule", "file"):
            if not e.get(k):
                raise ValueError(f"{path}: baseline entry missing {k!r}: {e}")
    return entries


def save(path, entries: List[Dict]) -> None:
    doc = {"version": VERSION,
           "entries": sorted(entries, key=lambda e: (
               e["rule"], e["file"], e.get("call", ""),
               e.get("context", "")))}
    Path(path).write_text(json.dumps(doc, indent=1) + "\n")


def entry_matches(entry: Dict, v: Violation) -> bool:
    if entry["rule"] != v.rule or entry["file"] != v.file:
        return False
    if entry.get("call") not in (None, v.call):
        return False
    if entry.get("context") not in (None, v.context):
        return False
    return True


def apply(violations: List[Violation], entries: List[Dict]
          ) -> Tuple[List[Violation], List[Dict], List[Dict]]:
    """Partition into (unbaselined violations, used entries, stale
    entries). An entry may cover many violations; a violation is
    suppressed by the first entry that matches it."""
    used: List[Dict] = []
    used_ids = set()
    unbaselined: List[Violation] = []
    for v in violations:
        for e in entries:
            if entry_matches(e, v):
                if id(e) not in used_ids:
                    used_ids.add(id(e))
                    used.append(e)
                break
        else:
            unbaselined.append(v)
    stale = [e for e in entries if id(e) not in used_ids]
    return unbaselined, used, stale


def problems(entries: List[Dict], stale: List[Dict]) -> List[str]:
    """--check failures that come from the baseline itself."""
    out = []
    for e in entries:
        if not str(e.get("reason", "")).strip():
            out.append(f"baseline entry {e.get('rule')}:{e.get('file')}"
                       f":{e.get('call', '*')} has no reason — every "
                       "allowlisted violation must say why")
    for e in stale:
        out.append(f"stale baseline entry {e.get('rule')}:{e.get('file')}"
                   f":{e.get('call', '*')} matches no current violation "
                   "— delete it (the violation was fixed)")
    return out
