#!/usr/bin/env bash
# One-command CI gate: generated-artifact drift, graftlint, introspection
# smoke, subsystem smokes, tier-1 tests, bench smoke.
#
#     bash tools/ci.sh            # the full gate (exit != 0 on any failure)
#     bash tools/ci.sh --fast     # drift + smokes + tier-1 only (skip bench)
#
# Mirrors what the reference's `make presubmit` (verify + test) gates:
#
#   1. drift  — deploy/crds/*.yaml and docs/reference/*.md must match what
#               tools/gen_crds.py / tools/gen_docs.py generate from code
#               (the codegen-lockstep contract tests/test_schema.py and
#               tests/test_tools.py also assert, surfaced here as its own
#               gate so a red run names the stale file directly)
#   2. lint   — graftlint (tools/lint/run.py --check): the project-
#               invariant static-analysis suite (docs/reference/
#               linting.md) — clock/lock/determinism/frozen-envelope/
#               metrics discipline; fails on any unbaselined violation
#               or stale/reasonless baseline entry
#   3. smoke  — introspection + metrics wire format: start an operator,
#               assert /debug/statusz and /debug/vars parse with every
#               registered provider reporting, and run the promtool-style
#               lint over the live /metrics scrape
#               (tools/smoke_introspect.py)
#   4. churn  — steady-state delta-solve gate (tools/smoke_delta.py):
#               boots an operator, drives a full pass + 20 small-churn
#               passes, asserts the incremental build + delta solve
#               actually engaged (counter > 0) and the plans match the
#               full-rebuild referee
#   5. sharded— mesh-production-path gate (tools/smoke_sharded.py):
#               boots the operator on a forced 8-device virtual CPU
#               mesh (XLA host-platform sizing, as the multichip
#               dry-run does), drives churn passes, asserts the mesh
#               engaged (devices > 1 in solver stats, sharded solves
#               carried passes), the delta path rode the mesh
#               (delta_solves > 0), and sampled plans match a
#               single-device referee solve exactly
#   6. micro  — device-resident microloop gate (tools/smoke_microloop.py):
#               operator churn at <5% churn on the forced 8-way virtual
#               mesh — every delta pass rides the microloop
#               (micro_solves == delta_solves), link legs per pass stay
#               within the bound (≤2; ≤4 when the fused tail-bin merge
#               re-ran), unchanged-plan passes skip the plan fetch
#               (fingerprint), and sampled plans are byte-identical to a
#               single-device full-rebuild referee
#   7. prof   — continuous-profiling gate (tools/smoke_profile.py):
#               boots an operator with the sampling profiler on, drives
#               a pass over live HTTP, asserts non-empty folded stacks,
#               contention counters for every instrumented hot lock,
#               the gzip negotiation, and the live scrape (with the new
#               karpenter_lock_wait_seconds family) linting clean
#   8. write  — API-stratum write-path gate (tools/smoke_writepath.py):
#               boots an API-mode operator, drives a churn burst through
#               ApiWriter, asserts the bulk/coalesced write path engaged
#               (counters > 0), zero fan-out envelope copies, the
#               watch-fed mirror converging to the store, and the live
#               /metrics scrape (karpenter_api_* series) linting clean
#   9. weather— adversarial-weather gate (tools/smoke_weather.py): the
#               60 s `squall` scenario on FakeClock — the degradation
#               ladder must engage (degraded_total > 0), the SLO burn
#               must recover below 1.0 after the storm, invariants hold
#               (no pending pods / leaks / stranded messages, junk
#               bodies counted as malformed), and two runs with the
#               same seed must record identical weather timelines (and
#               the lock-order witness reports zero cycles at exit)
#  10. pool   — solver-pool failover gate (tools/smoke_pool.py): an
#               operator against a 2-sidecar unix-socket pool, one
#               sidecar killed mid-churn — passes keep landing on the
#               survivor (failovers > 0, the local rung never engages
#               while a sidecar is healthy), a junk-talking endpoint
#               classifies as sidecar failure, breaker state renders in
#               the kpctl top POOL row and the karpenter_solver_pool_*
#               gauges over live HTTP (scrape lints clean), and the
#               restarted sidecar's breaker re-closes via the half-open
#               probe
#  11. explain— decision-explainability gate (tools/smoke_explain.py):
#               an operator under a short squall with one deliberately
#               ICE'd-out pod — /debug/explain over live HTTP must
#               attribute the pending pod to the ice elimination stage,
#               `kpctl explain pod` must render the waterfall, the
#               FailedScheduling dedup must hold, and the explain
#               provider's reason-code histogram must report
#  12. handoff— zero-downtime operator handoff gate
#               (tools/smoke_handoff.py): TWO real OS processes on a
#               shared FileLeaseStore + replication stream — the leader
#               is SIGKILLed mid-churn, the warm standby must promote
#               within the lease window with a rotated fence token and
#               CARRY passes on its replicated mirror (delta solves
#               engage, new pods get capacity, zero duplicate launches
#               for already-bound pods), with the LEADER/HANDOFF kpctl
#               rows, karpenter_operator_* gauges, and a cycle-free
#               lock-order witness in BOTH processes
#  13. consol — vmapped consolidation gate
#               (tools/smoke_consolidation.py): an operator churned to
#               an over-provisioned steady state must consolidate >=2
#               nodes via the batched device path (vmapped dispatches
#               carrying >1 candidate set, zero host-ladder fallbacks),
#               with the host-FFD savings referee and disruption-budget
#               pacing both observably engaged, pending-only churn
#               served from the zero-leg probe cache, and the
#               CONSOLIDATION kpctl row + `consolidation` provider +
#               `kpctl explain node` live over HTTP
#  14. headroom— saturation-observatory gate (tools/smoke_headroom.py):
#               an API-mode operator with a deliberately tiny watch
#               queue bound and an idle watcher — the forecaster must
#               rank the tightened queue first-to-break over live HTTP
#               BEFORE its first overflow, the high-water capture must
#               fire exactly once per episode, the probe must reuse the
#               apiserver's own drop counter after the overflow, and
#               `kpctl headroom` must render (and degrade error-shaped)
#  15. tier-1 — the full non-slow test suite on the CPU backend
#  16. bench  — `bench.py --smoke`: one fast config through the real
#               harness, so a broken solve path can never ride in on a
#               green unit-test run

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}
PY=${PYTHON:-python}
FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

echo "=== ci [1/16] generated-artifact drift ==="
$PY tools/gen_crds.py --check
tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT
$PY tools/gen_docs.py --out-dir "$tmp" >/dev/null
stale=0
for f in instance-types.md metrics.md settings.md compatibility.md; do
    if ! diff -u "docs/reference/$f" "$tmp/$f"; then
        echo "STALE docs/reference/$f — run: $PY tools/gen_docs.py"
        stale=1
    fi
done
[ "$stale" = 0 ] || exit 1
echo "drift: clean"

echo "=== ci [2/16] graftlint (project-invariant static analysis) ==="
$PY tools/lint/run.py --check

echo "=== ci [3/16] introspection smoke + metrics lint ==="
$PY tools/smoke_introspect.py

echo "=== ci [4/16] steady-state delta churn smoke ==="
$PY tools/smoke_delta.py

echo "=== ci [5/16] sharded mesh smoke ==="
$PY tools/smoke_sharded.py

echo "=== ci [6/16] device-resident microloop smoke ==="
$PY tools/smoke_microloop.py

echo "=== ci [7/16] continuous-profiling smoke ==="
$PY tools/smoke_profile.py

echo "=== ci [8/16] write-path smoke ==="
$PY tools/smoke_writepath.py

echo "=== ci [9/16] adversarial-weather smoke ==="
$PY tools/smoke_weather.py

echo "=== ci [10/16] solver-pool failover smoke ==="
$PY tools/smoke_pool.py

echo "=== ci [11/16] decision-explainability smoke ==="
$PY tools/smoke_explain.py

echo "=== ci [12/16] zero-downtime handoff smoke ==="
$PY tools/smoke_handoff.py

echo "=== ci [13/16] vmapped consolidation smoke ==="
$PY tools/smoke_consolidation.py

echo "=== ci [14/16] saturation-headroom smoke ==="
$PY tools/smoke_headroom.py

echo "=== ci [15/16] tier-1 tests ==="
$PY -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider

if [ "$FAST" = 1 ]; then
    echo "=== ci [16/16] bench smoke: SKIPPED (--fast) ==="
else
    echo "=== ci [16/16] bench smoke ==="
    $PY bench.py --smoke
fi

echo "ci gate: OK"
