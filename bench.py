"""Benchmarks: the five BASELINE configs + the FFD-beat config + the
high-G wave-split degradation config + the pipeline-overlap config, e2e.

Runs on the REAL EC2 catalog by default (759 types imported from the
reference's own data tables — instance-types.md joined with the
zz_generated pricing/bandwidth/vpclimits tables; real hardware shapes,
real ENI pod density, real us-east-1 prices, data-carried per-AZ spot).
``--catalog synthetic`` restores the synthetic lattice; either way a
``cfg5_50k_synthetic_continuity`` row keeps round-over-round comparisons
alive on the other catalog.

cfg6 is the BEAT row: a mixed accelerator + tiny-pod wave where the
solver's type narrowing (_accel_bin_cap + _wave_bin_cap) packs strictly
cheaper than the reference heuristic; its referee packs the UNCAPPED
problem (narrow=False — exactly the problem the reference's scheduler
would see), so ``cost_vs_ffd_oracle`` < 1.0 there is a genuine recorded
win, not self-parity. EVERY fresh-capacity row carries the same
evidence as a sub-metric: ``cost_vs_ffd_oracle`` stays the parity check
(FFD on the SAME narrowed problem), and ``cost_vs_uncapped_ffd``
records what the plan costs relative to the reference heuristic's own
build (existing-node configs skip it — their honest comparison is the
total-cost repack parity).

Per config this measures BOTH:
- ``e2e_p50_ms``  — build_problem (tensorization) + solve + decode, the
  full host-visible latency of one scheduling pass, and
- ``device_p50_ms`` — the device call (pack kernel + the single fused
  device→host result transfer).

Cost parity uses the sequential FFD referee — the native C++ one
(native/ffd.cc, same per-pod algorithm as the reference's Go scheduler
loop; covers the full feature surface incl. affinity classes and
existing bins, so ALL FIVE configs referee natively), with the Python
oracle (solver/oracle.py) as fallback when no toolchain is available.
BASELINE envelope: ≤2% cost regression (``cost_vs_ffd_oracle`` ≤ 1.02).

Prints ONE JSON line per config; the LAST line is the north-star config 5
(50k pods × full catalog, target <200 ms p50).
"""

import argparse
import json
import time

import numpy as np

TARGET_MS = 200.0
# the tunneled-TPU link's per-call latency swings tens of ms call-to-call;
# a p50 over 15 samples is stable where 7 still wobbled
ITERS = 15


def _pools_default():
    from karpenter_provider_aws_tpu.apis import NodePool
    return [NodePool(name="default")]


def config1_parity():
    """100 generic pods, cpu/mem requests only, single NodePool."""
    from karpenter_provider_aws_tpu.apis import Pod
    shapes = [("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi"), ("2", "4Gi")]
    pods = [Pod(name=f"p{i}", requests={"cpu": shapes[i % 4][0], "memory": shapes[i % 4][1]})
            for i in range(100)]
    return pods, _pools_default(), []


def config2_selectors_taints():
    """5k pods with nodeSelector + taints/tolerations across 3 NodePools."""
    from karpenter_provider_aws_tpu.apis import NodePool, Operator, Pod, Requirement
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    from karpenter_provider_aws_tpu.apis.objects import Taint, Toleration
    pools = [
        NodePool(name="default"),
        NodePool(name="batch", taints=[Taint(key="dedicated", value="batch")],
                 labels={"team": "batch"}),
        NodePool(name="arm", weight=10, requirements=[
            Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))]),
    ]
    rng = np.random.default_rng(2)
    pods = []
    for i in range(5000):
        r = rng.random()
        cpu = int(rng.choice([250, 500, 1000, 2000]))
        mem = int(rng.choice([512, 1024, 2048, 4096]))
        req = {"cpu": f"{cpu}m", "memory": f"{mem}Mi"}
        if r < 0.55:
            pods.append(Pod(name=f"gen{i}", requests=req))
        elif r < 0.8:
            cat = str(rng.choice(["m", "c", "r"]))
            pods.append(Pod(name=f"sel{i}", requests=req,
                            node_selector={wk.LABEL_INSTANCE_CATEGORY: cat}))
        else:
            pods.append(Pod(name=f"tol{i}", requests=req,
                            node_selector={"team": "batch"},
                            tolerations=[Toleration(key="dedicated", value="batch")]))
    return pods, pools, []


def config3_affinity_spread():
    """10k pods with podAntiAffinity + topologySpread (zone/hostname)."""
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    from karpenter_provider_aws_tpu.apis.objects import (PodAffinityTerm,
                                                         TopologySpreadConstraint)
    pods = []
    # 200 singleton services: hostname anti-affinity, one replica per node
    for i in range(200):
        pods.append(Pod(
            name=f"anti{i}", requests={"cpu": "500m", "memory": "1Gi"},
            labels={"app": "singleton"},
            pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME, anti=True,
                                          label_selector=(("app", "singleton"),))]))
    # 7 deployments zone-spread (maxSkew 1), 1400 replicas each
    for d in range(7):
        for i in range(1400):
            pods.append(Pod(
                name=f"zs{d}-{i}", requests={"cpu": "1", "memory": "2Gi"},
                labels={"app": f"web{d}"},
                topology_spread=[TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.LABEL_ZONE,
                    label_selector=((("app", f"web{d}")),))]))
    return pods, _pools_default(), []


def config4_consolidation_repack(lattice=None):
    """500 under-utilized nodes → repack; spot + on-demand price mix.

    The disruption controller's what-if shape (reference
    test/suites/scale/deprovisioning_test.go): the candidates' pods are
    re-offered as pending against the empty candidate nodes; the solve
    shows how few nodes (existing or cheaper-new) can host them.
    """
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.solver.problem import ExistingBin
    if lattice is None:
        lattice = build_lattice()
    # candidate node types: the synthetic trio when present, else (real
    # catalogs) the cheapest general-purpose multi-vCPU types available
    cands = [n for n in ("m5.2xlarge", "m5.xlarge", "c5.2xlarge")
             if n in lattice.name_to_idx]
    if len(cands) < 3:
        from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
        gpuish = [RESOURCE_AXES.index(a) for a in RESOURCE_AXES
                  if "gpu" in a or "neuron" in a or "gaudi" in a]
        pool = [(s_.od_price, s_.name) for s_ in lattice.specs
                if s_.od_price > 0 and s_.vcpus >= 4
                and not any(lattice.capacity[lattice.name_to_idx[s_.name], ax]
                            for ax in gpuish)]
        cands = [n for _, n in sorted(pool)[:3]] or list(lattice.names[:3])
    rng = np.random.default_rng(4)
    existing = []
    pods = []
    for i in range(500):
        itype = str(rng.choice(cands))
        cap = "spot" if rng.random() < 0.5 else "on-demand"
        zone = lattice.zones[int(rng.integers(len(lattice.zones)))]
        ti = lattice.name_to_idx[itype]
        existing.append(ExistingBin(
            name=f"node-{i}", node_pool="default", instance_type=itype,
            zone=zone, capacity_type=cap,
            used=np.zeros_like(lattice.alloc[ti])))
        # ~20% utilization: 3 small pods per 8-vCPU node
        for j in range(3):
            pods.append(Pod(name=f"p{i}-{j}",
                            requests={"cpu": "500m", "memory": "1Gi"}))
    return pods, _pools_default(), existing


def config5_full_scale():
    """50k pending pods × full catalog, GPU/Neuron + pinned capacity."""
    from karpenter_provider_aws_tpu.apis import NodePool, Operator, Pod, Requirement
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    rng = np.random.default_rng(0)
    pods = []
    shapes = []
    for s in range(30):
        cpu = int(rng.choice([100, 250, 500, 1000, 2000, 4000]))
        mem = int(rng.choice([256, 512, 1024, 2048, 4096, 8192]))
        sel = {}
        r = rng.random()
        if r < 0.2:
            sel[wk.LABEL_INSTANCE_CATEGORY] = str(rng.choice(["m", "c", "r"]))
        elif r < 0.3:
            sel[wk.LABEL_CAPACITY_TYPE] = "on-demand"
        elif r < 0.35:
            sel[wk.LABEL_ARCH] = "arm64"
        shapes.append(({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}, sel))
    counts = rng.multinomial(48600, np.ones(30) / 30)
    for s, ((req, sel), n) in enumerate(zip(shapes, counts)):
        pods += [Pod(name=f"s{s}-{i}", requests=req, node_selector=sel) for i in range(n)]
    pods += [Pod(name=f"gpu-{i}", requests={"cpu": "4", "memory": "16Gi", "nvidia.com/gpu": 1})
             for i in range(1000)]
    pods += [Pod(name=f"neuron-{i}", requests={"cpu": "4", "memory": "8Gi",
                                               "aws.amazon.com/neuron": 1})
             for i in range(400)]
    pools = [
        NodePool(name="default"),
        NodePool(name="arm", weight=10, requirements=[
            Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))]),
        NodePool(name="gpu", weight=20, requirements=[
            Requirement(wk.LABEL_INSTANCE_GPU_COUNT, Operator.GT, ("0",))]),
    ]
    return pods, pools, []


def config6_ffd_beat():
    """The beat scenario: a tiny-pod (pods-axis-bound) wave + a 1-GPU
    accelerator wave + mid-size co-tenants. Sequential FFD (the
    reference) grows the tiny-pod bins to maximum density and prices at
    the huge types that carry 737 pods, and stacks the GPU wave onto
    upsized multi-GPU nodes; the solver's _wave_bin_cap/_accel_bin_cap
    narrowing seals both waves at their per-pod / per-unit optimal
    types. The run_config caller referees this config against the
    UNCAPPED problem (narrow=False), i.e. the exact problem the
    reference's scheduler packs."""
    from karpenter_provider_aws_tpu.apis import Pod
    pods = [Pod(name=f"w{i}", requests={"cpu": "50m", "memory": "96Mi"})
            for i in range(20000)]
    pods += [Pod(name=f"m{i}", requests={"cpu": "1", "memory": "2Gi"})
             for i in range(2000)]
    pods += [Pod(name=f"g{i}", requests={"cpu": "2", "memory": "8Gi",
                                         "nvidia.com/gpu": 1})
             for i in range(400)]
    return pods, _pools_default(), []


def config7_highG_wave_split():
    """The adversarial-diversity wave: ≥4,096 DISTINCT scheduling
    signatures, so grouping cannot collapse the batch, the group axis
    overflows the largest compiled bucket, and the solve exercises the
    wave-split planner (docs/concepts/degradation.md). Unique cpu
    requests defeat signature dedup exactly the way a pathologically
    heterogeneous tenant mix would; the row records wave-split latency
    and its cost envelope vs the sequential FFD referee."""
    from karpenter_provider_aws_tpu.apis import Pod
    pods = [Pod(name=f"hg{i}",
                requests={"cpu": f"{100 + i}m",
                          "memory": f"{256 + (i % 8) * 64}Mi"})
            for i in range(4608)]
    return pods, _pools_default(), []


def config9_sharded_16k():
    """The multi-chip SCALE row (VERDICT: parallel/sharded.py was only
    ever exercised at ≤2,400 pods). 16,500 mixed-shape pods — small,
    mid, and category-selector waves — solved over the pod-axis sharded
    mesh (shard_map DP + ICI psum reductions, tail-bin merge), refereed
    for the ≤2% envelope against the SINGLE-device solve of the same
    problem."""
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    n_each = 6600
    pods = [Pod(name=f"ss{i}", requests={"cpu": "500m", "memory": "1Gi"})
            for i in range(n_each)]
    pods += [Pod(name=f"sm{i}", requests={"cpu": "2", "memory": "4Gi"})
             for i in range(n_each)]
    pods += [Pod(name=f"sl{i}", requests={"cpu": "4", "memory": "8Gi"},
                 node_selector={wk.LABEL_INSTANCE_CATEGORY: "c"})
             for i in range(n_each // 2)]
    return pods, _pools_default(), []


def run_sharded_config(make, lattice, solver, iters=5):
    """The cfg9 sharded-scale row: Solver.solve(mesh=...) end to end.

    Shards over every visible device (capped at 8, the virtual-mesh
    size the tests pin); ``mesh_devices`` is recorded so a single-chip
    run is legible as such rather than silently reading like a
    multi-chip result. Parity referees against the single-device solve
    of the SAME problem — the honest envelope for a partitioned pack."""
    import jax

    from karpenter_provider_aws_tpu.parallel import solver_mesh
    from karpenter_provider_aws_tpu.solver import build_problem

    pods, pools, existing = make()
    n_pods = len(pods)
    n_dev = min(8, len(jax.devices()))
    mesh = solver_mesh(n_dev)
    problem = build_problem(pods, pools, lattice, existing=existing)

    single = solver.solve(problem)                    # referee + warmup
    t_first = time.perf_counter()
    plan = solver.solve(problem, mesh=mesh)           # sharded warmup
    first_ms = (time.perf_counter() - t_first) * 1000.0
    placed = sum(len(x.pods) for x in plan.new_nodes) + \
        sum(len(v) for v in plan.existing_assignments.values())
    assert placed + len(plan.unschedulable) == n_pods

    e2e_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        problem = build_problem(pods, pools, lattice, existing=existing)
        plan = solver.solve(problem, mesh=mesh)
        e2e_ms.append((time.perf_counter() - t0) * 1000.0)
    e2e_p50 = float(np.percentile(e2e_ms, 50))
    ratio = (plan.new_node_cost / single.new_node_cost
             if single.new_node_cost > 0 else 1.0)
    detail = {
        "pods": n_pods,
        "groups": problem.G,
        "mesh_devices": n_dev,
        "new_nodes": plan.num_new_nodes,
        "unschedulable": len(plan.unschedulable),
        "e2e_p50_ms": round(e2e_p50, 3),
        "compile_ms": round(max(first_ms - e2e_p50, 0.0), 3),
        "pods_per_sec": round(n_pods / (e2e_p50 / 1000.0), 1),
        "plan_cost_per_hour": round(plan.new_node_cost, 2),
        "single_device_cost_per_hour": round(single.new_node_cost, 2),
        "cost_vs_single_device": round(ratio, 4),
        "within_envelope": ratio <= 1.02,
    }
    return e2e_p50, detail


def config11_200k_sharded():
    """The 200k-pod mesh-production row (ISSUE 12 acceptance): 4× the
    north-star pod count, the scale where the per-shard bin tables are
    what keeps the solve on device at all (a single device's bin-table
    ceiling is the 8192 bucket; 8 shards split the fleet). Mixed-shape
    selector waves like cfg5, no accelerator pods (they pin capacity
    the FFD referee would also pin — the row measures scale, not the
    narrowing beat)."""
    from karpenter_provider_aws_tpu.apis import NodePool, Pod
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    rng = np.random.default_rng(12)
    shapes = []
    for _s in range(32):
        cpu = int(rng.choice([100, 250, 500, 1000, 2000, 4000]))
        mem = int(rng.choice([256, 512, 1024, 2048, 4096, 8192]))
        sel = {}
        r = rng.random()
        if r < 0.2:
            sel[wk.LABEL_INSTANCE_CATEGORY] = str(rng.choice(["m", "c", "r"]))
        elif r < 0.3:
            sel[wk.LABEL_CAPACITY_TYPE] = "on-demand"
        shapes.append(({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}, sel))
    counts = rng.multinomial(200_000, np.ones(len(shapes)) / len(shapes))
    pods = []
    for s, ((req, sel), n) in enumerate(zip(shapes, counts)):
        pods += [Pod(name=f"xx{s}-{i}", requests=req, node_selector=sel)
                 for i in range(n)]
    return pods, _pools_default(), []


def run_mesh_scale(make, lattice, solver, iters=3):
    """The mesh-production scale row: a mesh-native Solver (no per-call
    mesh argument — the boot-planned mesh IS the path) at 200k pods,
    refereed for the ≤2% cost envelope against the host FFD oracle of
    the SAME problem, with conservation asserted and the delta-cache /
    imbalance evidence recorded."""
    from karpenter_provider_aws_tpu.solver import build_problem

    pods, pools, existing = make()
    n_pods = len(pods)
    problem = build_problem(pods, pools, lattice, existing=existing)

    t_first = time.perf_counter()
    plan = solver.solve(problem)                      # mesh warmup+compile
    first_ms = (time.perf_counter() - t_first) * 1000.0
    placed = sum(len(x.pods) for x in plan.new_nodes) + \
        sum(len(v) for v in plan.existing_assignments.values())
    assert placed + len(plan.unschedulable) == n_pods

    e2e_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        problem = build_problem(pods, pools, lattice, existing=existing)
        plan = solver.solve(problem)
        e2e_ms.append((time.perf_counter() - t0) * 1000.0)
    e2e_p50 = float(np.percentile(e2e_ms, 50))

    ref_cost, _, referee = _run_referee(problem)
    ratio = plan.new_node_cost / ref_cost if ref_cost > 0 else 1.0
    st = solver.stats()
    detail = {
        "pods": n_pods,
        "groups": problem.G,
        "mesh_devices": plan.mesh_devices,
        "new_nodes": plan.num_new_nodes,
        "unschedulable": len(plan.unschedulable),
        "e2e_p50_ms": round(e2e_p50, 3),
        "compile_ms": round(max(first_ms - e2e_p50, 0.0), 3),
        "pods_per_sec": round(n_pods / (e2e_p50 / 1000.0), 1),
        "plan_cost_per_hour": round(plan.new_node_cost, 2),
        "ffd_cost_per_hour": round(ref_cost, 2),
        "cost_vs_ffd_oracle": round(ratio, 4),
        "within_envelope": ratio <= 1.02,
        "referee": referee,
        "shard_imbalance": st.get("mesh_shard_imbalance", 0.0),
        "stage_p50_ms": {k: round(v, 3)
                         for k, v in plan.stage_ms.items()},
    }
    return e2e_p50, detail


def run_mesh_parity(mesh):
    """Mesh-vs-single-device plan parity on the capped (full-dissolve)
    config: every shard's slice under-fills its bin, the merge dissolves
    them all, and the refinement re-pack must be BYTE-IDENTICAL to the
    single-device plan — recorded, not just unit-tested
    (tests/test_mesh.py pins the same claim)."""
    import json as _json

    from karpenter_provider_aws_tpu.apis import NodePool, Pod, serde
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.solver import Solver, build_problem

    big = build_lattice([s for s in build_catalog()
                         if s.name == "m5.4xlarge"])
    pods = [Pod(name=f"t{i}", requests={"cpu": "1", "memory": "2Gi"})
            for i in range(16)]
    problem = build_problem(pods, [NodePool(name="default")], big)
    single = Solver(big).solve(problem)
    meshed = Solver(big, mesh=mesh).solve(problem)

    def canon(p):
        return _json.dumps(serde.plan_semantic_dict(p), sort_keys=True)

    return {
        "config": "capped_full_dissolve_16pods_m5.4xlarge",
        "mesh_devices": meshed.mesh_devices,
        "byte_identical": canon(meshed) == canon(single),
        "single_cost_per_hour": round(single.new_node_cost, 2),
        "mesh_cost_per_hour": round(meshed.new_node_cost, 2),
    }


def run_sharded_artifact(catalog="real", devices=8,
                         out="MULTICHIP_r06.json"):
    """The MULTICHIP_r06 recording (`bench.py --sharded`): the 200k-pod
    mesh row, the mesh-vs-single-device byte-parity row, and the
    delta-on-mesh steady-state row (cfg10's harness on a mesh-native
    solver), written as one artifact. main() pins the virtual-CPU mesh
    sizing unless JAX_PLATFORMS is already exported as a non-cpu
    backend (export it explicitly to record on real chips); the
    artifact's "backend" field records which one actually ran."""
    import jax

    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.parallel import plan_mesh
    from karpenter_provider_aws_tpu.solver import Solver

    if catalog == "synthetic":
        lattice, catalog_name = build_lattice(), "synthetic"
    else:
        from karpenter_provider_aws_tpu.lattice.realdata import load_catalog
        path = None if catalog == "real" else catalog
        lattice = build_lattice(load_catalog(path, require_price=True))
        catalog_name = "real:" + (catalog if path else "reference")

    mesh_plan = plan_mesh(str(devices))
    solver = Solver(lattice, mesh=mesh_plan.mesh)
    doc = {
        "round": "MULTICHIP_r06",
        "catalog": catalog_name,
        "mesh_devices": mesh_plan.devices,
        "backend": jax.default_backend(),
        "rows": {},
    }

    p50, detail = run_mesh_scale(config11_200k_sharded, lattice, solver)
    doc["rows"]["cfg11_200k_sharded"] = detail
    print(json.dumps({"metric": "e2e_p50_latency_cfg11_200k_sharded",
                      "value": round(p50, 3), "unit": "ms",
                      "detail": detail}), flush=True)

    parity = run_mesh_parity(mesh_plan.mesh)
    doc["rows"]["mesh_vs_single_device_parity"] = parity
    print(json.dumps({"metric": "mesh_vs_single_device_parity",
                      "detail": parity}), flush=True)

    # the delta-on-mesh row: cfg10's steady-state harness, verbatim, on
    # the mesh-native solver — delta_solves == passes and per-pass
    # upload bytes ≪ full staging are the acceptance evidence
    d_p50, d_detail = run_steady_state_config(lattice, solver)
    d_detail["mesh_devices"] = mesh_plan.devices
    d_detail["delta_rode_mesh"] = (
        d_detail["delta_solves"] == d_detail["passes"])
    doc["rows"]["cfg12_delta_on_mesh"] = d_detail
    print(json.dumps({"metric": "e2e_p50_latency_cfg12_delta_on_mesh",
                      "value": round(d_p50, 3), "unit": "ms",
                      "detail": d_detail}), flush=True)

    ok = (detail["within_envelope"] and parity["byte_identical"]
          and d_detail["delta_rode_mesh"])
    doc["acceptance_ok"] = bool(ok)
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"wrote {out} (acceptance_ok={ok})", flush=True)
    return 0 if ok else 1


def build_bench_problem():
    """Back-compat hook (tests + driver round 1): the config-5 problem."""
    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.solver import build_problem
    pods, pools, existing = config5_full_scale()
    lattice = build_lattice()
    return lattice, build_problem(pods, pools, lattice, existing=existing), len(pods)


def _retained_cost(problem, used_names):
    """$/hr of the existing nodes still holding pods after a repack."""
    lat = problem.lattice
    total = 0.0
    for b in problem.existing:
        if b.name not in used_names:
            continue
        ti = lat.name_to_idx[b.instance_type]
        zi = lat.zones.index(b.zone)
        ci = lat.capacity_types.index(b.capacity_type)
        p = float(lat.price[ti, zi, ci])
        if np.isfinite(p):
            total += p
    return total


def _run_referee(problem):
    """ONE referee pack per config: native C++ where in scope, else the
    Python oracle. Returns (new_node_cost, names of existing bins that
    received pods, referee kind)."""
    try:
        from karpenter_provider_aws_tpu.native import native_ffd_pack
        ref = native_ffd_pack(problem)
        # an incomplete native pack (leftover pods) would understate the
        # baseline cost and report a false regression — fall back instead
        if ref is not None and ref.leftover == 0:
            used = ({problem.existing[i].name
                     for i in np.nonzero(ref.e_npods)[0]}
                    if problem.E else set())
            return ref.new_node_cost, used, "native"
    except Exception:
        pass
    from karpenter_provider_aws_tpu.solver.oracle import ffd_oracle
    oracle = ffd_oracle(problem)
    used = {problem.existing[b.existing_idx].name
            for b in oracle.bins if b.is_existing and b.pods}
    return oracle.new_node_cost, used, "python"


def _repack_parity(problem, plan, referee_result):
    """Non-vacuous cfg4 parity: total cost of the repacked cluster
    (retained existing nodes + any new nodes), plan vs the shared referee
    result from the SAME repack problem."""
    oracle_new_cost, oracle_used, referee = referee_result
    plan_cost = plan.new_node_cost + _retained_cost(
        problem, set(plan.existing_assignments))
    oracle_cost = oracle_new_cost + _retained_cost(problem, oracle_used)
    ratio = plan_cost / oracle_cost if oracle_cost > 0 else 1.0
    return (round(ratio, 4), len(oracle_used), round(plan_cost, 2),
            round(oracle_cost, 2), referee)


def _stage_p50(stage_samples):
    """Per-stage p50 (ms) over a config's iterations; stages missing
    from a sample (e.g. 'build' when the resident cache served the
    upload) count as 0 so the medians stay comparable across modes."""
    keys = sorted({k for s in stage_samples for k in s})
    return {k: round(float(np.percentile(
        [s.get(k, 0.0) for s in stage_samples], 50)), 3) for k in keys}


_RTT_BUF = None


def _rtt_probe() -> float:
    """One minimal device call + 1 KiB device→host transfer, in ms."""
    global _RTT_BUF
    import jax.numpy as jnp
    if _RTT_BUF is None:
        _RTT_BUF = jnp.zeros((1024,), jnp.uint8)
        np.asarray(_RTT_BUF + 1)  # warm the trace
    t0 = time.perf_counter()
    np.asarray(_RTT_BUF + 1)
    return (time.perf_counter() - t0) * 1000.0


def measure_link_rtt() -> float:
    """p50 link RTT. On a tunneled TPU this fixed per-call cost dominates
    small solves AND DRIFTS tens of ms across a run — run_config therefore
    interleaves probes with its iterations so each config's normalization
    uses the link weather it actually experienced."""
    return float(np.percentile([_rtt_probe() for _ in range(7)], 50))


def pallas_parity_check(lattice) -> dict:
    """Prove the Pallas finalization where it actually runs: at the 8192-
    bin bucket on THIS backend, the streaming kernel and the XLA form must
    pick identical (price, flat type×zone×captype index) per bin over the
    real lattice's masked prices (the tie-break contract in
    ops/offering_argmin.py). Returns a bench-detail dict."""
    from karpenter_provider_aws_tpu.ops.offering_argmin import (
        _ZCP, cheapest_offering_pallas, cheapest_offering_xla, probe,
    )
    import jax.numpy as jnp
    T, Z, C = lattice.T, lattice.Z, lattice.C
    if not probe() or Z * C > _ZCP:
        return {"checked": False,
                "reason": "pallas unavailable on backend" if Z * C <= _ZCP
                else f"Z*C={Z*C} exceeds kernel lane tile"}
    B = 8192
    Tp = -(-T // 128) * 128
    rng = np.random.default_rng(7)
    tm = np.zeros((B, Tp), np.float32)
    tm[:, :T] = rng.random((B, T)) < 0.3
    tm[:, rng.integers(T)] = 1.0   # no all-empty rows
    zc = np.zeros((B, _ZCP), np.float32)
    zc[:, : Z * C] = rng.random((B, Z * C)) < 0.6
    zc[:, 0] = 1.0
    p2 = np.full((Tp, _ZCP), np.inf, np.float32)
    p2[:T, : Z * C] = np.where(lattice.available, lattice.price,
                               np.inf).reshape(T, Z * C)
    pv, pi = cheapest_offering_pallas(jnp.asarray(tm), jnp.asarray(zc),
                                      jnp.asarray(p2))
    xv, xi = cheapest_offering_xla(jnp.asarray(tm), jnp.asarray(zc),
                                   jnp.asarray(p2))
    pv, pi, xv, xi = (np.asarray(a) for a in (pv, pi, xv, xi))
    finite = np.isfinite(xv)
    prices_equal = bool(np.array_equal(pv, xv, equal_nan=True))
    # identical choice = same (type, zone, captype) wherever any offering
    # exists; where none does both report +inf and the index is moot
    choices_equal = bool(np.array_equal(pi[finite], xi[finite]))
    return {"checked": True, "bins": B,
            "prices_identical": prices_equal,
            "choices_identical": choices_equal}


def run_config(key, make, lattice, solver, uncapped_referee=False,
               also_uncapped=False, iters=ITERS):
    from karpenter_provider_aws_tpu.solver import build_problem
    pods, pools, existing = make()
    n_pods = len(pods)

    # warmup: settle buckets + compile. The first solve is timed so the
    # row can report its COMPILE share separately (first_ms − steady
    # p50): e2e_p50 below never mixes cold XLA compile with steady-state
    # latency, and the cold cost stays auditable per row.
    t_first = time.perf_counter()
    problem = build_problem(pods, pools, lattice, existing=existing)
    plan = solver.solve(problem)
    first_ms = (time.perf_counter() - t_first) * 1000.0
    scheduled = sum(len(x.pods) for x in plan.new_nodes) + \
        sum(len(v) for v in plan.existing_assignments.values())
    assert scheduled + len(plan.unschedulable) == n_pods

    e2e_ms, dev_ms, rtt_ms, stage_samples = [], [], [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        problem = build_problem(pods, pools, lattice, existing=existing)
        plan = solver.solve(problem)
        e2e_ms.append((time.perf_counter() - t0) * 1000.0)
        dev_ms.append(plan.device_seconds * 1000.0)
        stage_samples.append(plan.stage_ms)
        # interleaved link probe: the RTT THIS sample rode on
        rtt_ms.append(_rtt_probe())
    e2e_p50 = float(np.percentile(e2e_ms, 50))
    dev_p50 = float(np.percentile(dev_ms, 50))
    rtt_p50 = float(np.percentile(rtt_ms, 50))
    # PER-SAMPLE normalization: median of (sample - its adjacent probe).
    # Subtracting medians of two separate distributions overstates algo
    # time whenever the link wobbles between solve and probe; pairing
    # cancels the weather sample-by-sample.
    e2e_algo = float(np.percentile(
        [max(e - r, 0.0) for e, r in zip(e2e_ms, rtt_ms)], 50))
    dev_algo = float(np.percentile(
        [max(d - r, 0.0) for d, r in zip(dev_ms, rtt_ms)], 50))

    # the beat config referees the UNCAPPED problem — what the
    # reference's scheduler would pack — so a <1.0 ratio is a recorded
    # win over the reference heuristic, not parity with ourselves
    referee_problem = (build_problem(pods, pools, lattice,
                                     existing=existing, narrow=False)
                       if uncapped_referee else problem)
    referee_result = _run_referee(referee_problem)
    ref_cost, _, referee = referee_result
    if ref_cost > 0:
        cost_ratio = round(plan.new_node_cost / ref_cost, 4)
    else:
        # repack configs can land everything on existing capacity: both
        # the plan and the referee open zero new nodes
        cost_ratio = 1.0 if plan.new_node_cost == 0 else float("inf")

    detail = {
        "pods": n_pods,
        "groups": problem.G,
        "existing_nodes": problem.E,
        "new_nodes": plan.num_new_nodes,
        "unschedulable": len(plan.unschedulable),
        "device_p50_ms": round(dev_p50, 3),
        "e2e_p50_ms": round(e2e_p50, 3),
        "device_link_rtt_ms": round(rtt_p50, 3),
        # RTT-normalized views: what the ALGORITHM costs once the link's
        # per-call latency (paired probe per sample) is subtracted
        "device_algo_ms": round(dev_algo, 3),
        "e2e_algo_ms": round(e2e_algo, 3),
        "pods_per_sec": round(n_pods / (e2e_p50 / 1000.0), 1),
        # cold-start share: the first (compile-paying) solve minus the
        # steady p50 — kept OUT of e2e_p50 so compile latency and
        # steady-state latency can never blur (--warm-start + the
        # persistent compile cache are what shrink this number)
        "compile_ms": round(max(first_ms - e2e_p50, 0.0), 3),
        "plan_cost_per_hour": round(plan.new_node_cost, 2),
        "cost_vs_ffd_oracle": cost_ratio,
        "referee": referee,
        # per-stage p50 of the solve (solver/pipeline.py STAGES) — the
        # overlap evidence: pipelined runs show "download" shrunk to the
        # residual wait while build/upload stay constant
        "stage_p50_ms": _stage_p50(stage_samples),
        "pipelined": plan.pipelined,
    }
    if plan.solver_path != "device":
        # degradation-ladder provenance (the high-G row): which rung
        # produced the plan and how many waves the group axis split into
        detail["solver_path"] = plan.solver_path
        detail["waves"] = plan.waves
    if uncapped_referee:
        detail["referee_problem"] = "uncapped"
        detail["ffd_cost_per_hour"] = round(ref_cost, 2)
        if np.isfinite(cost_ratio):
            detail["saved_vs_ffd_pct"] = round((1.0 - cost_ratio) * 100, 2)
    if also_uncapped and not existing:
        # the beat, ON the parity row: cost_vs_ffd_oracle above proves
        # the narrowed plan packs as well as FFD packs the SAME problem;
        # this extra referee packs the UN-narrowed problem — what the
        # reference's scheduler would actually build — so the ratio is
        # the recorded win over the reference heuristic on this config.
        # Existing-node configs are excluded: a new-node-only ratio would
        # ignore retained-node cost (0/anything reads as a bogus 100%
        # win); their honest comparison is the total-cost repack parity
        # below. When the MAIN referee already packed uncapped, reuse it
        # rather than packing the same 50k-pod problem twice.
        if uncapped_referee:
            un_cost, un_ref = ref_cost, referee
        else:
            un_cost, _, un_ref = _run_referee(
                build_problem(pods, pools, lattice, existing=existing,
                              narrow=False))
        if un_cost > 0:
            un_ratio = round(plan.new_node_cost / un_cost, 4)
            detail["cost_vs_uncapped_ffd"] = un_ratio
            detail["uncapped_ffd_cost_per_hour"] = round(un_cost, 2)
            detail["saved_vs_uncapped_ffd_pct"] = round(
                (1.0 - un_ratio) * 100, 2)
            detail["uncapped_referee"] = un_ref
    if existing:
        detail["nodes_still_used"] = len(plan.existing_assignments)
        detail["nodes_emptied"] = problem.E - len(plan.existing_assignments)
        (detail["repack_cost_vs_oracle"], detail["oracle_nodes_retained"],
         detail["repack_cost_per_hour"], detail["oracle_repack_cost_per_hour"],
         detail["repack_referee"]) = _repack_parity(problem, plan,
                                                    referee_result)
    return e2e_p50, detail


# the overlap-efficiency gate (cfg8): the pipelined wave-split e2e p50
# must beat the sequential one by at least this margin. The wave-split
# workload pays one link round trip PER WAVE sequentially; the
# double-buffered pipeline hides the upload leg of every wave but the
# first, so a pipeline that stops overlapping shows up here as a
# recorded regression, auditable round over round in the bench JSON.
OVERLAP_MARGIN_REQUIRED_PCT = 5.0


def run_overlap_config(make, lattice, solver, iters=5):
    """The overlap-efficiency row: the SAME wave-split workload solved
    sequentially and pipelined on the SAME solver, back to back under
    the same link weather. Returns (pipelined_e2e_p50, detail) with the
    margin, per-mode per-stage timings, the prefetch counter, and a
    byte-identity check of the two plans — the parity claim measured,
    not just unit-tested."""
    import json as _json

    from karpenter_provider_aws_tpu.apis import serde
    from karpenter_provider_aws_tpu.solver import build_problem
    pods, pools, existing = make()

    def canon(plan):
        # the shared semantic surface (serde.plan_semantic_dict):
        # timings + pipelining/mesh provenance legitimately differ
        # between modes, and deviceRetries is link weather — a
        # transient fault in one mode must not read as a determinism
        # regression
        return _json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)

    # counter snapshots so the recorded evidence is THIS row's overlap,
    # not the whole bench run's (cfg1-7 also ran pipelined)
    pre_prefetched = solver.pipeline_stats["prefetched_waves"]
    pre_cache = solver._resident.stats()
    out = {}
    try:
        for mode, flag in (("sequential", False), ("pipelined", True)):
            solver.set_pipeline(flag)
            plan = solver.solve(build_problem(pods, pools, lattice,
                                              existing=existing))  # warm
            e2e, rtt, stage_samples = [], [], []
            for _ in range(iters):
                t0 = time.perf_counter()
                problem = build_problem(pods, pools, lattice,
                                        existing=existing)
                plan = solver.solve(problem)
                e2e.append((time.perf_counter() - t0) * 1000.0)
                stage_samples.append(plan.stage_ms)
                rtt.append(_rtt_probe())
            out[mode] = {
                "e2e_p50_ms": round(float(np.percentile(e2e, 50)), 3),
                "link_rtt_p50_ms": round(float(np.percentile(rtt, 50)), 3),
                "stage_p50_ms": _stage_p50(stage_samples),
                "waves": plan.waves,
                "plan_canon": canon(plan),
            }
    finally:
        solver.set_pipeline(True)

    seq, pipe = out["sequential"], out["pipelined"]
    margin_pct = round((1.0 - pipe["e2e_p50_ms"] / seq["e2e_p50_ms"]) * 100.0,
                       2) if seq["e2e_p50_ms"] > 0 else 0.0
    detail = {
        "pods": len(pods),
        "waves": pipe["waves"],
        "sequential_e2e_p50_ms": seq["e2e_p50_ms"],
        "pipelined_e2e_p50_ms": pipe["e2e_p50_ms"],
        "sequential_stage_p50_ms": seq["stage_p50_ms"],
        "pipelined_stage_p50_ms": pipe["stage_p50_ms"],
        "link_rtt_p50_ms": pipe["link_rtt_p50_ms"],
        "prefetched_waves": (solver.pipeline_stats["prefetched_waves"]
                             - pre_prefetched),
        "resident_cache": {k: v - pre_cache[k]
                           for k, v in solver._resident.stats().items()},
        # the parity claim, measured on the bench workload itself
        "plans_byte_identical": seq["plan_canon"] == pipe["plan_canon"],
        # the overlap-efficiency assertion, recorded so the trajectory
        # is auditable: a pipeline that stops overlapping flips this
        "overlap_margin_pct": margin_pct,
        "overlap_margin_required_pct": OVERLAP_MARGIN_REQUIRED_PCT,
        "overlap_within_margin": margin_pct >= OVERLAP_MARGIN_REQUIRED_PCT,
    }
    return pipe["e2e_p50_ms"], detail


# the steady-state delta row's target (ROADMAP item 2): with <5% of the
# pods churned between passes, the incremental build + delta solve must
# land under this, measured on the ALGORITHM share (paired link-RTT
# probe subtracted, like every *_algo_ms in this file — on the tunneled
# TPU the fixed ~97 ms link legs dwarf any host/device work and would
# say nothing about the delta path)
DELTA_TARGET_MS = 20.0
DELTA_PASSES = 12
DELTA_CHURN_FRACTION = 0.015   # ~1.5% leave + ~1.5% arrive per pass (<5%)


def config10_steady_state():
    """The steady-state reconcile shape: a 20k-pod cluster of ~24
    deployment-style shapes over the real catalog, with partially-used
    existing nodes. Every pass <5% of the pods churn (binds drain some,
    new replicas arrive) — the exact workload the incremental builder +
    delta solve exist for."""
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    rng = np.random.default_rng(10)
    shapes = []
    for s in range(24):
        cpu = int(rng.choice([250, 500, 1000, 2000]))
        mem = int(rng.choice([512, 1024, 2048, 4096]))
        sel = ({wk.LABEL_INSTANCE_CATEGORY: str(rng.choice(["m", "c", "r"]))}
               if rng.random() < 0.25 else {})
        shapes.append(({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}, sel))
    counts = rng.multinomial(20000, np.ones(24) / 24)
    pods = []
    for s, ((req, sel), n) in enumerate(zip(shapes, counts)):
        pods += [Pod(name=f"st{s}-{i}", requests=req, node_selector=sel)
                 for i in range(n)]
    return pods, _pools_default(), shapes


def run_steady_state_config(lattice, solver):
    """cfg10_steady_state_delta: ONE full solve, then DELTA_PASSES
    reconcile passes with <5% pod churn driven through the incremental
    builder (solver/incremental.py) and Solver.solve_delta. Records the
    delta p50 (raw + RTT-normalized), per-pass upload bytes, dirty-group
    counts, and plan parity vs a from-scratch rebuild + solve of the
    same pass — the evidence for ROADMAP item 2's <20 ms bar."""
    from karpenter_provider_aws_tpu.apis import Pod
    from karpenter_provider_aws_tpu.solver import build_problem
    from karpenter_provider_aws_tpu.solver.incremental import (
        IncrementalProblemBuilder)
    from karpenter_provider_aws_tpu.solver.problem import ExistingBin
    from karpenter_provider_aws_tpu.state.cluster import DirtySet

    pods, pools, shapes = config10_steady_state()
    rng = np.random.default_rng(11)

    # ~120 partially-used existing nodes over general-purpose types
    gpuish = []
    from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
    gpuish = [RESOURCE_AXES.index(a) for a in RESOURCE_AXES
              if "gpu" in a or "neuron" in a or "gaudi" in a]
    cand_pool = [(s_.od_price, s_.name) for s_ in lattice.specs
                 if s_.od_price > 0 and s_.vcpus >= 8
                 and not any(lattice.capacity[lattice.name_to_idx[s_.name], ax]
                             for ax in gpuish)]
    cands = [n for _, n in sorted(cand_pool)[:4]] or list(lattice.names[:4])
    existing = []
    for i in range(120):
        itype = cands[int(rng.integers(len(cands)))]
        ti = lattice.name_to_idx[itype]
        used = (lattice.alloc[ti] * 0.2).astype(np.float32)
        existing.append(ExistingBin(
            name=f"node-{i}", node_pool="default", instance_type=itype,
            zone=lattice.zones[int(rng.integers(len(lattice.zones)))],
            capacity_type="on-demand", used=used))

    builder = IncrementalProblemBuilder()
    rev = 0

    # cold pass: compile + full build (excluded from every p50)
    t_first = time.perf_counter()
    res = builder.build(pods, pools, lattice, existing=list(existing),
                        dirty=DirtySet(since=-1, rev=rev, full=True))
    first_plan = solver.solve(res.problem)
    first_ms = (time.perf_counter() - t_first) * 1000.0

    # steady FULL-rebuild baseline (what every pass cost before the
    # delta path): scratch build + solve of the SAME problem
    full_ms = []
    for _ in range(3):
        t0 = time.perf_counter()
        problem = build_problem(pods, pools, lattice,
                                existing=list(existing))
        solver.solve(problem)
        full_ms.append((time.perf_counter() - t0) * 1000.0)
    full_p50 = float(np.percentile(full_ms, 50))

    pre_bytes = solver._resident.stats()["bytes_shipped"]
    pre_delta = solver.pipeline_stats["delta_solves"]
    delta_ms, delta_rtt, dirty_counts = [], [], []
    build_ms, host_ms = [], []
    parity_ratios, nodes_match = [], True
    fallbacks = []
    serial = 0
    for pass_i in range(DELTA_PASSES):
        # <5% churn: ~1.5% of the pods bind away, ~1.5% new arrive, and
        # a couple of existing bins' usage moves (the bound pods landed)
        k = max(1, int(len(pods) * DELTA_CHURN_FRACTION))
        gone_idx = set(int(i) for i in
                       rng.choice(len(pods), size=k, replace=False))
        removed = [pods[i] for i in gone_idx]
        pods = [p for i, p in enumerate(pods) if i not in gone_idx]
        added = []
        for _ in range(k):
            serial += 1
            req, sel = shapes[int(rng.integers(len(shapes)))]
            added.append(Pod(name=f"churn-{serial}", requests=req,
                             node_selector=sel))
        pods += added
        for b in rng.choice(len(existing), size=2, replace=False):
            u = existing[int(b)].used.copy()
            u[0] += 0.25   # a quarter-cpu of bound pods moved in
            existing[int(b)].used = u
        touched = {p.name: ("gone", None) for p in removed}
        touched.update({p.name: ("pending", p) for p in added})
        dirty = DirtySet(since=builder.rev, rev=builder.rev + 1,
                         pods=set(touched), bins=True)

        t0 = time.perf_counter()
        res = builder.build(pods, pools, lattice,
                            existing=lambda: list(existing),
                            dirty=dirty, touched=touched)
        t_built = time.perf_counter()
        if res.incremental:
            plan = solver.solve_delta(res.problem,
                                      dirty_groups=res.dirty_groups)
        else:
            fallbacks.append(res.reason)
            plan = solver.solve(res.problem)
        t_end = time.perf_counter()
        delta_ms.append((t_end - t0) * 1000.0)
        build_ms.append((t_built - t0) * 1000.0)
        # the share the incremental path actually controls: everything
        # but the device kernel + its result wait
        host_ms.append((t_end - t0 - plan.device_seconds) * 1000.0)
        dirty_counts.append(len(res.dirty_groups))
        delta_rtt.append(_rtt_probe())

        if pass_i in (3, DELTA_PASSES - 1):
            # parity referee: a from-scratch rebuild + solve of the SAME
            # pass must produce the same nodes at the same cost
            scratch = build_problem(pods, pools, lattice,
                                    existing=list(existing))
            ref = solver.solve(scratch)
            parity_ratios.append(
                plan.new_node_cost / ref.new_node_cost
                if ref.new_node_cost > 0 else 1.0)
            nodes_match = nodes_match and (
                sorted((n.instance_type, n.zone, len(n.pods))
                       for n in plan.new_nodes)
                == sorted((n.instance_type, n.zone, len(n.pods))
                          for n in ref.new_nodes))

    delta_p50 = float(np.percentile(delta_ms, 50))
    delta_algo = float(np.percentile(
        [max(d - r, 0.0) for d, r in zip(delta_ms, delta_rtt)], 50))
    stats = solver.stats()
    detail = {
        "pods": len(pods),
        "groups": res.problem.G,
        "existing_nodes": len(existing),
        "passes": DELTA_PASSES,
        "churn_pct": round(2 * DELTA_CHURN_FRACTION * 100, 2),
        "delta_e2e_p50_ms": round(delta_p50, 3),
        "delta_algo_p50_ms": round(delta_algo, 3),
        "delta_build_p50_ms": round(float(np.percentile(build_ms, 50)), 3),
        "delta_host_p50_ms": round(float(np.percentile(host_ms, 50)), 3),
        "full_rebuild_e2e_p50_ms": round(full_p50, 3),
        "speedup_vs_full": round(full_p50 / delta_p50, 2)
        if delta_p50 > 0 else 0.0,
        "compile_ms": round(max(first_ms - full_p50, 0.0), 3),
        "dirty_groups_p50": float(np.percentile(dirty_counts, 50)),
        "delta_solves": solver.pipeline_stats["delta_solves"] - pre_delta,
        "incremental_builds": builder.incremental_builds,
        "full_build_fallbacks": fallbacks,
        "upload_bytes_per_pass": int(
            (solver._resident.stats()["bytes_shipped"] - pre_bytes)
            / max(DELTA_PASSES, 1)),
        "resident_problem_hits": stats.get("resident_problem_hits", 0),
        "plan_cost_parity": round(float(max(parity_ratios)), 4)
        if parity_ratios else None,
        "plan_nodes_match_full_rebuild": nodes_match,
        "delta_target_ms": DELTA_TARGET_MS,
        "delta_within_target": delta_algo <= DELTA_TARGET_MS,
    }
    return delta_p50, detail


# the device-resident microloop row (BENCH_r14, `bench.py --device-delta`):
# per-pass link legs are bounded — one dirty upload plus one CONDITIONAL
# plan fetch on a single device; a mesh pass whose plan moved pays two
# more for the fused tail-bin merge — and the <20 ms bar is judged on
# the PLUMBING share (e2e minus the device kernel wait): on the CPU
# stand-in backend the kernel alone is ~40x the whole budget
# (BENCH_r06: 768 ms), while BENCH_r05 measured the real-device kernel
# at 3-9 ms, so kernel time is refereed separately via the device cost
# model (last_vs_model ≫ 1 = plumbing, not kernel) exactly as ROADMAP
# item 2 prescribes.
MICRO_LEGS_BOUND = 2           # single-device steady pass
MICRO_LEGS_BOUND_MERGE = 4     # mesh pass that re-ran the tail-bin merge
MICRO_LVM_BOUND = 25.0         # last_vs_model sanity bound for the record
MICRO_NOCHURN_EVERY = 4        # every Nth pass churns nothing: the
                               # fingerprint must suppress the plan fetch


def run_microloop_config(lattice, solver, parity_every=1,
                         require_target=True):
    """The BENCH_r14 harness: cfg10's steady-state shape driven through
    the incremental builder + the device-resident microloop, with
    per-pass link legs recorded, no-churn passes interleaved (the
    skipped-sync evidence), byte-exact plan parity against a
    full-rebuild referee SOLVER (its own instance — the comparison can
    never ride the resident state it referees), and the device cost
    model's last_vs_model as the kernel-vs-plumbing referee."""
    from karpenter_provider_aws_tpu.apis import Pod, serde
    from karpenter_provider_aws_tpu.solver import Solver, build_problem
    from karpenter_provider_aws_tpu.solver import costmodel
    from karpenter_provider_aws_tpu.solver.incremental import (
        IncrementalProblemBuilder)
    from karpenter_provider_aws_tpu.solver.problem import ExistingBin
    from karpenter_provider_aws_tpu.state.cluster import DirtySet

    def canon(plan):
        return json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)

    pods, pools, shapes = config10_steady_state()
    rng = np.random.default_rng(14)
    referee = Solver(lattice, mesh=solver.mesh)

    from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
    gpuish = [RESOURCE_AXES.index(a) for a in RESOURCE_AXES
              if "gpu" in a or "neuron" in a or "gaudi" in a]
    cand_pool = [(s_.od_price, s_.name) for s_ in lattice.specs
                 if s_.od_price > 0 and s_.vcpus >= 8
                 and not any(lattice.capacity[lattice.name_to_idx[s_.name], ax]
                             for ax in gpuish)]
    cands = [n for _, n in sorted(cand_pool)[:4]] or list(lattice.names[:4])
    existing = []
    for i in range(120):
        itype = cands[int(rng.integers(len(cands)))]
        ti = lattice.name_to_idx[itype]
        used = (lattice.alloc[ti] * 0.2).astype(np.float32)
        existing.append(ExistingBin(
            name=f"node-{i}", node_pool="default", instance_type=itype,
            zone=lattice.zones[int(rng.integers(len(lattice.zones)))],
            capacity_type="on-demand", used=used))

    builder = IncrementalProblemBuilder()

    # cold pass: compile + full build + microloop priming (excluded)
    t_first = time.perf_counter()
    res = builder.build(pods, pools, lattice, existing=list(existing),
                        dirty=DirtySet(since=-1, rev=0, full=True))
    solver.solve(res.problem)
    solver.solve_delta(res.problem)     # prime the resident problem state
    first_ms = (time.perf_counter() - t_first) * 1000.0

    pass_ms, pass_rtt, pass_plumbing = [], [], []
    pass_legs, merge_passes, pass_regrows = [], [], []
    parity_all = True
    fallbacks = []
    serial = 0
    pre_skipped = solver.pipeline_stats["micro_skipped_syncs"]
    pre_micro = solver.pipeline_stats["micro_solves"]
    pre_delta = solver.pipeline_stats["delta_solves"]
    for pass_i in range(DELTA_PASSES):
        nochurn = (pass_i % MICRO_NOCHURN_EVERY) == (MICRO_NOCHURN_EVERY - 1)
        touched = {}
        if not nochurn:
            k = max(1, int(len(pods) * DELTA_CHURN_FRACTION))
            gone_idx = set(int(i) for i in
                           rng.choice(len(pods), size=k, replace=False))
            removed = [pods[i] for i in gone_idx]
            pods = [p for i, p in enumerate(pods) if i not in gone_idx]
            added = []
            for _ in range(k):
                serial += 1
                req, sel = shapes[int(rng.integers(len(shapes)))]
                added.append(Pod(name=f"churn-{serial}", requests=req,
                                 node_selector=sel))
            pods += added
            for b in rng.choice(len(existing), size=2, replace=False):
                u = existing[int(b)].used.copy()
                u[0] += 0.25
                existing[int(b)].used = u
            touched = {p.name: ("gone", None) for p in removed}
            touched.update({p.name: ("pending", p) for p in added})
        dirty = DirtySet(since=builder.rev, rev=builder.rev + 1,
                         pods=set(touched), bins=not nochurn)

        pre_merge = solver.pipeline_stats["micro_merge_solves"]
        pre_regrow = solver.pipeline_stats["micro_merge_regrows"]
        t0 = time.perf_counter()
        res = builder.build(pods, pools, lattice,
                            existing=lambda: list(existing),
                            dirty=dirty, touched=touched)
        if res.incremental:
            plan = solver.solve_delta(res.problem,
                                      dirty_groups=res.dirty_groups)
        else:
            fallbacks.append(res.reason)
            plan = solver.solve(res.problem)
        t_end = time.perf_counter()
        pass_ms.append((t_end - t0) * 1000.0)
        pass_plumbing.append((t_end - t0 - plan.device_seconds) * 1000.0)
        pass_rtt.append(_rtt_probe())
        if res.incremental:
            # micro_last_legs is only meaningful for delta passes; a
            # full-build fallback never updates it and its re-staging
            # legs are exactly what the fallback list already flags
            pass_legs.append(solver.pipeline_stats["micro_last_legs"])
            merge_passes.append(
                solver.pipeline_stats["micro_merge_solves"] > pre_merge)
            pass_regrows.append(
                solver.pipeline_stats["micro_merge_regrows"] - pre_regrow)

        if pass_i % parity_every == 0:
            # two referees, two claims: (1) the MICROLOOP's — its plan
            # is byte-identical to a full-staging solve of the SAME
            # problem (delta machinery changes bytes moved, never the
            # answer); (2) the BUILDER's — the incrementally-patched
            # problem plans the same node multiset at the same cost as
            # a from-scratch build (pod ordering inside groups may
            # differ, so byte identity is not the builder's contract —
            # solver/incremental.py, tests/test_incremental.py)
            ref_same = referee.solve(res.problem)
            if canon(plan) != canon(ref_same):
                parity_all = False
            scratch = build_problem(pods, pools, lattice,
                                    existing=list(existing))
            ref = referee.solve(scratch)
            if (sorted((n.instance_type, n.zone, len(n.pods))
                       for n in plan.new_nodes)
                    != sorted((n.instance_type, n.zone, len(n.pods))
                              for n in ref.new_nodes)
                    or abs(plan.new_node_cost - ref.new_node_cost) > 1e-6):
                parity_all = False

    skipped = solver.pipeline_stats["micro_skipped_syncs"] - pre_skipped
    micro = solver.pipeline_stats["micro_solves"] - pre_micro
    deltas = solver.pipeline_stats["delta_solves"] - pre_delta
    # a merge bin-table regrow retry re-stages and re-fetches (2 more
    # accounted legs) — behaviorally correct, so the bound stretches by
    # exactly what the regrows paid, never silently
    legs_ok = all(
        legs <= (MICRO_LEGS_BOUND_MERGE if merged else MICRO_LEGS_BOUND)
        + 2 * regrows
        for legs, merged, regrows in zip(pass_legs, merge_passes,
                                         pass_regrows))
    cm = costmodel.model().stats()
    lvm = float(cm.get("last_vs_model", 0.0))
    e2e_p50 = float(np.percentile(pass_ms, 50))
    plumbing_p50 = float(np.percentile(pass_plumbing, 50))
    algo_p50 = float(np.percentile(
        [max(d - r, 0.0) for d, r in zip(pass_ms, pass_rtt)], 50))
    st = solver.stats()
    detail = {
        "pods": len(pods),
        "groups": res.problem.G,
        "existing_nodes": len(existing),
        "passes": DELTA_PASSES,
        "churn_pct": round(2 * DELTA_CHURN_FRACTION * 100, 2),
        "mesh_devices": st.get("mesh_devices", 1),
        "e2e_p50_ms": round(e2e_p50, 3),
        "e2e_algo_p50_ms": round(algo_p50, 3),
        # the share the microloop controls (e2e minus the device kernel
        # wait) — the <20 ms judgement basis on the CPU stand-in, per
        # the MICRO_LEGS_BOUND comment above
        "plumbing_p50_ms": round(plumbing_p50, 3),
        "compile_prime_ms": round(max(first_ms - e2e_p50, 0.0), 3),
        "micro_solves": micro,
        "delta_solves": deltas,
        "micro_engaged_every_delta": micro == deltas,
        "full_build_fallbacks": fallbacks,
        "skipped_syncs": skipped,
        "nochurn_passes": DELTA_PASSES // MICRO_NOCHURN_EVERY,
        "legs_per_pass": pass_legs,
        "legs_max": max(pass_legs) if pass_legs else 0,
        "merge_passes": int(sum(merge_passes)),
        "merge_regrows": int(sum(pass_regrows)),
        "legs_bound": MICRO_LEGS_BOUND,
        "legs_bound_merge": MICRO_LEGS_BOUND_MERGE,
        "legs_within_bound": legs_ok,
        "link_upload_bytes": st["link_upload_bytes"],
        "link_fetch_bytes": st["link_fetch_bytes"],
        "upload_bytes_per_pass": int(st["link_upload_bytes"]
                                     / max(DELTA_PASSES, 1)),
        "last_vs_model": round(lvm, 3),
        "last_vs_model_bound": MICRO_LVM_BOUND,
        "plan_parity_vs_full_rebuild": parity_all,
        "delta_target_ms": DELTA_TARGET_MS,
        "within_target": plumbing_p50 <= DELTA_TARGET_MS,
        # the <20 ms bar binds the single-device device-backend row;
        # the mesh row records its plumbing honestly (8x per-shard host
        # decode on the VIRTUAL mesh is host work a real multi-chip
        # backend does not serialize) but is gated on parity/legs only
        "target_gated": require_target,
    }
    ok = (parity_all and legs_ok
          and (detail["within_target"] or not require_target)
          and micro > 0 and skipped > 0
          and (lvm == 0.0 or lvm <= MICRO_LVM_BOUND))
    return e2e_p50, detail, ok


def run_device_delta_artifact(catalog="real",
                              out="BENCH_r14_device_delta.json"):
    """The BENCH_r14 recording (`bench.py --device-delta`): the
    device-resident microloop's steady-state row on a single device AND
    composed with the forced 8-way virtual mesh, next to the cfg10
    baseline numbers those rows improve on. main() pins the virtual-CPU
    mesh sizing exactly like --sharded; the artifact's "backend" field
    records which backend actually ran."""
    import jax

    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.parallel import plan_mesh
    from karpenter_provider_aws_tpu.solver import Solver

    if catalog == "synthetic":
        lattice, catalog_name = build_lattice(), "synthetic"
    else:
        from karpenter_provider_aws_tpu.lattice.realdata import load_catalog
        path = None if catalog == "real" else catalog
        lattice = build_lattice(load_catalog(path, require_price=True))
        catalog_name = "real:" + (catalog if path else "reference")

    doc = {
        "round": "BENCH_r14",
        "catalog": catalog_name,
        "backend": jax.default_backend(),
        "link_rtt_ms": round(measure_link_rtt(), 3),
        "rows": {},
    }

    p50, detail, ok1 = run_microloop_config(lattice, Solver(lattice))
    doc["rows"]["cfg14_micro_single_device"] = detail
    print(json.dumps({"metric": "e2e_p50_latency_cfg14_micro_single_device",
                      "value": round(p50, 3), "unit": "ms",
                      "detail": detail}), flush=True)

    mesh_plan = plan_mesh("8")
    mp50, mdetail, ok2 = run_microloop_config(
        lattice, Solver(lattice, mesh=mesh_plan.mesh), parity_every=3,
        require_target=False)
    mdetail["mesh_devices"] = mesh_plan.devices
    doc["rows"]["cfg14_micro_on_mesh"] = mdetail
    print(json.dumps({"metric": "e2e_p50_latency_cfg14_micro_on_mesh",
                      "value": round(mp50, 3), "unit": "ms",
                      "detail": mdetail}), flush=True)

    ok = bool(ok1 and ok2 and mdetail["mesh_devices"] > 1)
    doc["acceptance_ok"] = ok
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"wrote {out} (acceptance_ok={ok})", flush=True)
    return 0 if ok else 1


# budget on ALGORITHM-controlled time for the north-star config: e2e p50
# minus the measured link RTT must stay under this, so link weather and
# real regressions are distinguishable in the bench record. Recalibrated
# round 5 for the real-catalog plan shape: the wave/accel narrowing +
# density floor land cfg5 on ~1840 bins at 0.39x the uncapped-FFD cost
# (vs round 4's 1486-bin synthetic plan under the old 80 ms budget);
# measured e2e_algo 72.8-79.2 ms across runs, so 100 ms separates
# weather from regression with real margin while the raw <200 ms p50
# target stays the headline gate. The content-keyed narrowing cache +
# grouping fast path (problem.py) then cut the steady-state host share
# to 61.1 (synthetic) / 75.3 (real) on the chip, and the round-5 host
# work — run-sharing the grouping cache pointer, the unrestricted-axes
# feasibility fast path, __dict__-direct selector scans — landed it at
# 42.3 (real) / 47.3 (synthetic): under half the budget, so weather and
# regression cannot be confused.
CFG5_ALGO_BUDGET_MS = 100.0


# ---- the API-stratum write path (kube/apiserver.py) ------------------------
# Per-pod write+deliver cost at 1k/15k/50k stored pods x 1/32/256 watchers.
# The row's gates: cost flat within WRITEPATH_FLAT_PCT from 1k->50k at every
# fan-out (nothing O(store) may ride the write path), and watch delivery
# allocates ZERO per-watcher envelope copies (the server's
# fanout_envelope_copies counter pins the shared-frozen-event design).
WRITEPATH_SIZES = (1000, 15000, 50000)
WRITEPATH_WATCHERS = (1, 32, 256)
WRITEPATH_OPS = 2000
WRITEPATH_FLAT_PCT = 25.0


def run_writepath_bench(out_path="BENCH_r07_writepath.json"):
    """The write-path row: measures one write verb (patch) end to end —
    store mutation + RV allocation + history append + fan-out delivery
    to every subscriber queue + consumer drain — per pod, as the store
    and the watcher population scale. No jax, no solver: this is the
    API stratum alone, the layer PROF_r08 blamed."""
    import tracemalloc
    from karpenter_provider_aws_tpu.kube.apiserver import FakeAPIServer

    def build_server(n_pods: int) -> FakeAPIServer:
        s = FakeAPIServer()
        for lo in range(0, n_pods, 5000):
            s.bulk([("create", "pods",
                     {"name": f"p{i}", "namespace": "default",
                      "requests": {"cpu": "100m", "memory": "128Mi"}})
                    for i in range(lo, min(lo + 5000, n_pods))])
        return s

    rows = []
    for n_pods in WRITEPATH_SIZES:
        server = build_server(n_pods)
        for n_watch in WRITEPATH_WATCHERS:
            watches = [server.watch("pods", server.last_rv)
                       for _ in range(n_watch)]
            copies0 = server.fanout_envelope_copies
            t0 = time.perf_counter()
            for i in range(WRITEPATH_OPS):
                server.patch("pods", f"p{i % n_pods}", {"priority": i})
            delivered = [sum(1 for ev in w.pop_pending()
                             if ev.type != "BOOKMARK") for w in watches]
            elapsed = time.perf_counter() - t0
            # the same churn COALESCED through the bulk verb (one lock
            # acquisition + one delivery flush per 200-op batch)
            t1 = time.perf_counter()
            for lo in range(0, WRITEPATH_OPS, 200):
                server.bulk([("patch", "pods", f"p{i % n_pods}",
                              {"priority": -i})
                             for i in range(lo, lo + 200)])
            for w in watches:
                w.pop_pending()
            bulk_elapsed = time.perf_counter() - t1
            for w in watches:
                server.stop_watch(w)
            assert all(d == WRITEPATH_OPS for d in delivered), (
                f"watch fan-out lost events: {set(delivered)}")
            rows.append({
                "pods": n_pods, "watchers": n_watch,
                "per_op_us": round(elapsed / WRITEPATH_OPS * 1e6, 2),
                "bulk_per_op_us": round(
                    bulk_elapsed / WRITEPATH_OPS * 1e6, 2),
                "events_delivered": WRITEPATH_OPS * n_watch,
                "fanout_envelope_copies":
                    server.fanout_envelope_copies - copies0,
            })
            print(json.dumps({"metric": "writepath_per_op_us",
                              **rows[-1]}), flush=True)

    # allocation pin: bytes the fan-out allocates per delivery at max
    # fan-out — shared frozen events mean pointer appends, not copies
    # (an envelope deepcopy alone is ~10 KB; the bar is two orders
    # under that)
    server = build_server(1000)
    watches = [server.watch("pods", server.last_rv) for _ in range(256)]
    tracemalloc.start()
    for i in range(200):
        server.patch("pods", f"p{i}", {"priority": i})
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    for w in watches:
        w.pop_pending()
        server.stop_watch(w)
    alloc_bytes_per_delivery = round(peak / (200 * 256), 1)

    # flatness gate: per-op cost from 1k to 50k pods, per fan-out level
    flatness = {}
    ok = True
    for n_watch in WRITEPATH_WATCHERS:
        costs = {r["pods"]: r["per_op_us"] for r in rows
                 if r["watchers"] == n_watch}
        delta_pct = round(
            (costs[WRITEPATH_SIZES[-1]] - costs[WRITEPATH_SIZES[0]])
            / costs[WRITEPATH_SIZES[0]] * 100.0, 1)
        flatness[str(n_watch)] = delta_pct
        if abs(delta_pct) > WRITEPATH_FLAT_PCT:
            ok = False
    copies = sum(r["fanout_envelope_copies"] for r in rows)
    if copies:
        ok = False
    doc = {
        "metric": "writepath_write_deliver_cost",
        "unit": "us/op",
        "rows": rows,
        "flat_1k_to_50k_pct": flatness,
        "flat_budget_pct": WRITEPATH_FLAT_PCT,
        "fanout_envelope_copies_total": copies,
        "alloc_bytes_per_delivery": alloc_bytes_per_delivery,
        "pass": ok,
    }
    print(json.dumps(doc), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"writepath: -> {out_path} (pass={ok})", flush=True)
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--catalog", default="real",
                    help="'real' (bundled reference_catalog.json, the "
                         "default), 'synthetic' (the generated ~750-type "
                         "catalog), or a path to a real-data JSON catalog "
                         "(lattice/realdata.py schema)")
    ap.add_argument("--no-continuity", action="store_true",
                    help="skip the cross-catalog cfg5 continuity row")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: ONE fast config (cfg1, 3 iters, "
                         "synthetic catalog), no Pallas/continuity rows — "
                         "proves the bench harness + solve path end to "
                         "end in well under a minute (tools/ci.sh)")
    ap.add_argument("--sharded", action="store_true",
                    help="mesh-production artifact ONLY (MULTICHIP_r06): "
                         "the 200k-pod 8-way sharded row refereed "
                         "against the FFD oracle, the mesh-vs-single-"
                         "device byte-parity row, and the delta-on-mesh "
                         "steady-state row. Forces the 8-device virtual "
                         "CPU mesh (the multichip dry-run's sizing) "
                         "unless JAX_PLATFORMS is already exported as a "
                         "non-cpu backend — to record on real chips, "
                         "export JAX_PLATFORMS explicitly; the "
                         "artifact's \"backend\" field says which ran.")
    ap.add_argument("--sharded-out", default="MULTICHIP_r06.json",
                    help="artifact path for --sharded")
    ap.add_argument("--device-delta", action="store_true",
                    help="device-resident microloop artifact ONLY "
                         "(BENCH_r14): cfg10's steady-state shape driven "
                         "through the reconcile microloop on a single "
                         "device and on the forced 8-way virtual mesh — "
                         "per-pass link legs bounded, fingerprint-"
                         "suppressed plan fetches counted, byte-exact "
                         "parity vs a full-rebuild referee, last_vs_model "
                         "as the kernel-vs-plumbing referee. Forces the "
                         "virtual CPU mesh exactly like --sharded.")
    ap.add_argument("--device-delta-out", default="BENCH_r14_device_delta.json",
                    help="artifact path for --device-delta")
    ap.add_argument("--writepath", action="store_true",
                    help="API-stratum write-path row ONLY: per-pod "
                         "write+deliver cost at 1k/15k/50k stored pods x "
                         "1/32/256 watchers (flat-within-25%% gate, "
                         "zero-fan-out-copy pin) -> "
                         "BENCH_r07_writepath.json. No solver, no jax.")
    args = ap.parse_args(argv)

    if args.writepath:
        raise SystemExit(run_writepath_bench())

    if args.sharded or args.device_delta:
        # BEFORE the first jax import (nothing above here imports it):
        # size the virtual CPU mesh exactly like the multichip dry-run
        # unless a real non-cpu backend is configured
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if os.environ["JAX_PLATFORMS"] == "cpu":
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
            import jax
            jax.config.update("jax_platforms", "cpu")
        if args.device_delta:
            raise SystemExit(run_device_delta_artifact(
                catalog=args.catalog, out=args.device_delta_out))
        raise SystemExit(run_sharded_artifact(catalog=args.catalog,
                                              out=args.sharded_out))

    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.solver import Solver

    def _make_lattice(catalog):
        if catalog == "synthetic":
            return build_lattice(), "synthetic"
        from karpenter_provider_aws_tpu.lattice.realdata import load_catalog
        path = None if catalog == "real" else catalog
        specs = load_catalog(path, require_price=True)
        return (build_lattice(specs),
                "real:" + (catalog if path else "reference"))

    if args.smoke:
        lattice, catalog_name = _make_lattice("synthetic")
        solver = Solver(lattice)
        e2e_p50, detail = run_config("cfg1_100pods_parity", config1_parity,
                                     lattice, solver, iters=3)
        detail["catalog"] = catalog_name
        detail["smoke"] = True
        print(json.dumps({
            "metric": "e2e_p50_latency_cfg1_100pods_parity",
            "value": round(e2e_p50, 3),
            "unit": "ms",
            "vs_baseline": round(TARGET_MS / e2e_p50, 3),
            "detail": detail,
        }), flush=True)
        return

    lattice, catalog_name = _make_lattice(args.catalog)
    solver = Solver(lattice)
    link_rtt = round(measure_link_rtt(), 3)
    pallas = pallas_parity_check(lattice)

    def _emit(key, make, lattice, solver, uncapped_referee=False,
              cname=None, cfg5=False, pallas_detail=None, iters=ITERS):
        # EVERY row records both views: parity vs FFD on the same
        # problem, and cost vs what the reference heuristic would build
        # (cfg4's all-on-existing repack skips the latter via the
        # un_cost > 0 guard — both sides open zero new nodes)
        e2e_p50, detail = run_config(key, make, lattice, solver,
                                     uncapped_referee=uncapped_referee,
                                     also_uncapped=True, iters=iters)
        detail["start_link_rtt_ms"] = link_rtt
        detail["catalog"] = cname or catalog_name
        if cfg5:
            detail["algo_budget_ms"] = CFG5_ALGO_BUDGET_MS
            detail["algo_within_budget"] = (
                detail["e2e_algo_ms"] <= CFG5_ALGO_BUDGET_MS)
        if pallas_detail is not None:
            detail["pallas_parity"] = pallas_detail
        print(json.dumps({
            "metric": f"e2e_p50_latency_{key}",
            "value": round(e2e_p50, 3),
            "unit": "ms",
            "vs_baseline": round(TARGET_MS / e2e_p50, 3),
            "detail": detail,
        }), flush=True)
        return detail

    for key, make in [
        ("cfg1_100pods_parity", config1_parity),
        ("cfg2_5k_selectors_taints", config2_selectors_taints),
        ("cfg3_10k_affinity_spread", config3_affinity_spread),
        ("cfg4_500node_repack", lambda: config4_consolidation_repack(lattice)),
    ]:
        _emit(key, make, lattice, solver)
    _emit("cfg6_ffd_beat_mixed_waves", config6_ffd_beat, lattice, solver,
          uncapped_referee=True)
    # the high-G degradation row: >4,096 distinct signatures force the
    # wave-split planner; fewer iters — each sample is a multi-wave solve
    _emit("cfg7_highG_wave_split", config7_highG_wave_split, lattice,
          solver, iters=5)

    # the overlap-efficiency row: cfg7's wave-split workload sequential
    # vs pipelined on the same solver; the recorded margin is the
    # auditable proof the double-buffered waves hide per-wave link legs
    ov_p50, ov_detail = run_overlap_config(config7_highG_wave_split,
                                           lattice, solver)
    ov_detail["start_link_rtt_ms"] = link_rtt
    ov_detail["catalog"] = catalog_name
    print(json.dumps({
        "metric": "e2e_p50_latency_cfg8_pipeline_overlap",
        "value": ov_p50,
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / ov_p50, 3) if ov_p50 else 0.0,
        "detail": ov_detail,
    }), flush=True)

    # the multi-chip scale row: the pod-axis sharded solve at 16.5k pods
    # (beyond the test suite's former 2,400-pod ceiling), refereed
    # against the single-device solve; mesh_devices records the real
    # device count so single-chip runs stay legible
    sh_p50, sh_detail = run_sharded_config(config9_sharded_16k, lattice,
                                           solver)
    sh_detail["start_link_rtt_ms"] = link_rtt
    sh_detail["catalog"] = catalog_name
    print(json.dumps({
        "metric": "e2e_p50_latency_cfg9_16k_sharded",
        "value": round(sh_p50, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / sh_p50, 3) if sh_p50 else 0.0,
        "detail": sh_detail,
    }), flush=True)

    # the steady-state delta row: full solve, then DELTA_PASSES small-
    # churn reconciles through the incremental builder + delta solve —
    # the <20 ms bar of ROADMAP item 2, with parity vs full rebuild
    st_p50, st_detail = run_steady_state_config(lattice, solver)
    st_detail["start_link_rtt_ms"] = link_rtt
    st_detail["catalog"] = catalog_name
    print(json.dumps({
        "metric": "e2e_p50_latency_cfg10_steady_state_delta",
        "value": round(st_p50, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / st_p50, 3) if st_p50 else 0.0,
        "detail": st_detail,
    }), flush=True)

    # cross-catalog continuity: the SAME cfg5 problem on the other
    # catalog, so round-over-round comparisons survive the default flip
    if not args.no_continuity:
        other = "synthetic" if catalog_name != "synthetic" else "real"
        olat, oname = _make_lattice(other)
        _emit("cfg5_50k_synthetic_continuity" if other == "synthetic"
              else "cfg5_50k_real_continuity",
              config5_full_scale, olat, Solver(olat), cname=oname,
              cfg5=True)

    # the north-star row stays LAST (the driver reads the final line)
    _emit("cfg5_50k_full_lattice", config5_full_scale, lattice,
          solver, cfg5=True, pallas_detail=pallas)


if __name__ == "__main__":
    main()
