"""Benchmark: BASELINE config 5 — 50k pending pods x full catalog.

Generates a realistic 50k-pod pending set (30+ distinct shapes: generic
cpu/mem mixes, selector-constrained, GPU and Neuron extended resources,
on-demand-pinned), builds the full 707-type lattice, and measures the
device Solve() latency (group tensorization excluded, matching the
reference's own split between watch/cache machinery and its scheduling
pass).

Prints ONE JSON line: p50 device solve latency in ms vs the 200 ms
north-star target (vs_baseline > 1.0 means faster than target).
"""

import json
import time

import numpy as np


def build_bench_problem():
    from karpenter_provider_aws_tpu.apis import NodePool, Operator, Pod, Requirement
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    from karpenter_provider_aws_tpu.lattice import build_lattice
    from karpenter_provider_aws_tpu.solver import build_problem

    lattice = build_lattice()
    rng = np.random.default_rng(0)
    pods = []
    # 30 generic deployment shapes (the bulk of a 50k pending wave)
    shapes = []
    for s in range(30):
        cpu = int(rng.choice([100, 250, 500, 1000, 2000, 4000]))
        mem = int(rng.choice([256, 512, 1024, 2048, 4096, 8192]))
        sel = {}
        r = rng.random()
        if r < 0.2:
            sel[wk.LABEL_INSTANCE_CATEGORY] = str(rng.choice(["m", "c", "r"]))
        elif r < 0.3:
            sel[wk.LABEL_CAPACITY_TYPE] = "on-demand"
        elif r < 0.35:
            sel[wk.LABEL_ARCH] = "arm64"
        shapes.append(({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}, sel))
    counts = rng.multinomial(48600, np.ones(30) / 30)
    for s, ((req, sel), n) in enumerate(zip(shapes, counts)):
        pods += [Pod(name=f"s{s}-{i}", requests=req, node_selector=sel) for i in range(n)]
    # GPU + Neuron tails (extended resources, config 5)
    pods += [Pod(name=f"gpu-{i}", requests={"cpu": "4", "memory": "16Gi", "nvidia.com/gpu": 1})
             for i in range(1000)]
    pods += [Pod(name=f"neuron-{i}", requests={"cpu": "4", "memory": "8Gi",
                                               "aws.amazon.com/neuron": 1})
             for i in range(400)]
    pools = [
        NodePool(name="default"),
        NodePool(name="arm", weight=10, requirements=[
            Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))]),
        NodePool(name="gpu", weight=20, requirements=[
            Requirement(wk.LABEL_INSTANCE_GPU_COUNT, Operator.GT, ("0",))]),
    ]
    problem = build_problem(pods, pools, lattice)
    return lattice, problem, len(pods)


def main():
    from karpenter_provider_aws_tpu.solver import Solver

    lattice, problem, n_pods = build_bench_problem()
    solver = Solver(lattice)

    plan = solver.solve(problem)  # warmup: compile + bucket settle
    scheduled = sum(len(n.pods) for n in plan.new_nodes) + \
        sum(len(v) for v in plan.existing_assignments.values())
    assert scheduled + len(plan.unschedulable) == n_pods

    lat_ms = []
    for _ in range(10):
        p = solver.solve(problem)
        lat_ms.append(p.device_seconds * 1000.0)
    p50 = float(np.percentile(lat_ms, 50))
    target_ms = 200.0

    # full-scale cost parity vs the sequential FFD referee (native C++,
    # same per-pod algorithm as the reference's Go loop; BASELINE <=2%)
    cost_vs_ffd = None
    try:
        from karpenter_provider_aws_tpu.native import native_ffd_pack
        ref = native_ffd_pack(problem)
        if ref is not None and ref.new_node_cost > 0:
            cost_vs_ffd = round(plan.new_node_cost / ref.new_node_cost, 4)
    except Exception:
        pass

    print(json.dumps({
        "metric": "solve_p50_latency_50k_pods_x_707_types",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(target_ms / p50, 3),
        "detail": {
            "pods": n_pods,
            "groups": problem.G,
            "new_nodes": plan.num_new_nodes,
            "unschedulable": len(plan.unschedulable),
            "pods_per_sec": round(n_pods / (p50 / 1000.0), 1),
            "plan_cost_per_hour": round(plan.new_node_cost, 2),
            "cost_vs_ffd_oracle": cost_vs_ffd,
        },
    }))


if __name__ == "__main__":
    main()
