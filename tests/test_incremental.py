"""Incremental steady-state solve: dirty journal, incremental problem
builder, delta-solve parity, SLO warmup window, gz soak artifacts.

The contract under test (docs/concepts/performance.md "Steady-state
reconciles & the compile cache"): the incremental path is a pure
OPTIMIZATION — every problem it produces must be plan-equivalent to a
from-scratch build_problem of the same inputs (cost-exact, same nodes),
and any input it cannot localize must fall back to the full build.
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cache.unavailable import UnavailableOfferings
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.lattice.tensors import masked_view_versioned
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.solver.incremental import (
    IncrementalProblemBuilder)
from karpenter_provider_aws_tpu.solver.oracle import ffd_oracle
from karpenter_provider_aws_tpu.state.cluster import ClusterState, DirtySet
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5")])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


SHAPES = [{"cpu": "250m", "memory": "512Mi"},
          {"cpu": "500m", "memory": "1Gi"},
          {"cpu": "1", "memory": "2Gi"},
          {"cpu": "2", "memory": "4Gi"}]


def _pod(i, shape=None):
    return Pod(name=f"p{i}", requests=shape or SHAPES[i % len(SHAPES)])


# ---------------------------------------------------------------------------
# dirty journal


class TestDirtyJournal:
    def test_mutations_journal_and_localize(self):
        c = ClusterState(FakeClock())
        rev0 = c.state_rev
        c.add_pod(_pod(1))
        c.add_pod(_pod(2))
        d = c.dirty_since(rev0)
        assert not d.full and d.pods == {"p1", "p2"}
        assert not d.bins and not d.volumes and not d.other

    def test_bind_marks_pod_and_bin(self):
        c = ClusterState(FakeClock())
        c.add_pod(_pod(1))
        rev = c.state_rev
        c.bind_pod("p1", "node-a")
        d = c.dirty_since(rev)
        assert "p1" in d.pods and d.bins

    def test_volume_and_daemonset_kinds(self):
        c = ClusterState(FakeClock())
        rev = c.state_rev

        class SC:
            name = "gp3"
            binding_mode = "WaitForFirstConsumer"
            zones = ()
            provisioner = "ebs.csi.aws.com"
        c.add_storage_class(SC())
        assert c.dirty_since(rev).volumes
        rev = c.state_rev
        ds = Pod(name="ds1", requests={"cpu": "100m"}, is_daemonset=True)
        c.add_pod(ds)
        d = c.dirty_since(rev)
        assert d.daemonsets and "ds1" not in d.pods

    def test_stale_and_future_revisions_read_full(self):
        c = ClusterState(FakeClock())
        assert c.dirty_since(c.state_rev + 5).full
        # reset = another life: any held revision reads full
        c.add_pod(_pod(1))
        rev = c.state_rev
        c.reset()
        assert c.dirty_since(rev).full

    def test_add_pod_already_bound_marks_bin(self):
        """A pod first seen ALREADY BOUND (sync relist, external
        scheduler) grows its node's used vector — the journal must mark
        bins or a delta pass reuses stale existing-bin arrays (review
        finding)."""
        c = ClusterState(FakeClock())
        rev = c.state_rev
        c.add_pod(Pod(name="pb", requests={"cpu": "1"}, node_name="node-a"))
        d = c.dirty_since(rev)
        assert "pb" in d.pods and d.bins

    def test_nominated_pods_always_dirty(self):
        clock = FakeClock()
        c = ClusterState(clock)
        c.add_pod(_pod(1))
        c.nominate("p1", "claim-a", ttl=5.0)
        rev = c.state_rev
        # no mutation at all, but the nomination can expire silently
        d = c.dirty_since(rev)
        assert "p1" in d.pods

    def test_touched_pods_classification(self):
        clock = FakeClock()
        c = ClusterState(clock)
        c.add_pod(_pod(1))
        c.add_pod(_pod(2))
        c.bind_pod("p2", "node-a")
        c.add_pod(_pod(3))
        c.nominate("p3", "claim-a", ttl=5.0)
        st = c.touched_pods(["p1", "p2", "p3", "nope"])
        assert st["p1"][0] == "pending"
        assert st["p2"][0] == "bound"
        assert st["p3"][0] == "nominated"
        assert st["nope"][0] == "gone"
        clock.step(10.0)   # nomination expires → pending again
        assert c.touched_pods(["p3"])["p3"][0] == "pending"


# ---------------------------------------------------------------------------
# incremental builder: gates


class TestBuilderGates:
    def _full(self, builder, pods, pools, lattice, existing=()):
        return builder.build(pods, pools, lattice, existing=list(existing),
                             dirty=DirtySet(since=-1, rev=0, full=True))

    def test_cold_then_delta(self, lattice):
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(200)]
        pools = [NodePool(name="default")]
        res = self._full(b, pods, pools, lattice)
        assert not res.incremental and res.reason == "cold"
        new = Pod(name="new1", requests=SHAPES[0])
        res2 = b.build(pods + [new], pools, lattice,
                       dirty=DirtySet(since=0, rev=1, pods={"new1"}),
                       touched={"new1": ("pending", new)})
        assert res2.incremental
        assert res2.problem.count.sum() == 201

    def test_new_signature_rebuilds(self, lattice):
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(50)]
        pools = [NodePool(name="default")]
        self._full(b, pods, pools, lattice)
        odd = Pod(name="odd", requests={"cpu": "7777m", "memory": "3Gi"})
        res = b.build(pods + [odd], pools, lattice,
                      dirty=DirtySet(since=0, rev=1, pods={"odd"}),
                      touched={"odd": ("pending", odd)})
        assert not res.incremental and res.reason == "new-signature"
        # the rebuild compiled the new shape: the NEXT churn of that
        # signature rides the delta path
        odd2 = Pod(name="odd2", requests={"cpu": "7777m", "memory": "3Gi"})
        res2 = b.build(pods + [odd, odd2], pools, lattice,
                       dirty=DirtySet(since=1, rev=2, pods={"odd2"}),
                       touched={"odd2": ("pending", odd2)})
        assert res2.incremental

    def test_volume_daemonset_pool_lattice_gates(self, lattice):
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(40)]
        pools = [NodePool(name="default")]
        self._full(b, pods, pools, lattice)
        d = DirtySet(since=0, rev=1)
        assert not b.build(pods, pools, lattice,
                           dirty=DirtySet(since=0, rev=1, volumes=True)
                           ).incremental
        self._full(b, pods, pools, lattice)
        assert not b.build(pods, pools, lattice,
                           dirty=DirtySet(since=0, rev=1, daemonsets=True)
                           ).incremental
        self._full(b, pods, pools, lattice)
        changed = [NodePool(name="default", labels={"rev": "2"})]
        res = b.build(pods, changed, lattice, dirty=d)
        assert not res.incremental and res.reason == "pools-changed"
        self._full(b, pods, pools, lattice)
        other = build_lattice([s for s in build_catalog()
                               if s.family in ("m5",)])
        assert not b.build(pods, pools, other, dirty=d).incremental

    def test_complex_pods_ineligible(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import (
            TopologySpreadConstraint)
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(10)]
        pods.append(Pod(
            name="spread", requests={"cpu": "1"}, labels={"app": "w"},
            topology_spread=[TopologySpreadConstraint(
                max_skew=1, topology_key=wk.LABEL_ZONE,
                label_selector=(("app", "w"),))]))
        pools = [NodePool(name="default")]
        self._full(b, pods, pools, lattice)
        res = b.build(pods, pools, lattice, dirty=DirtySet(since=0, rev=1))
        assert not res.incremental

    def test_bound_pod_selectors_make_ineligible(self, lattice):
        """A BOUND pod's spread/affinity selector changes how labels
        project into signatures even when no pending pod has one — the
        delta path must stand down (review finding: signature_of matches
        with the empty projection only)."""
        from karpenter_provider_aws_tpu.apis.objects import (
            TopologySpreadConstraint)
        from karpenter_provider_aws_tpu.solver.topology import BoundPod
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(20)]
        pools = [NodePool(name="default")]
        spreader = Pod(name="bound-sp", requests={"cpu": "1"},
                       labels={"app": "w"},
                       topology_spread=[TopologySpreadConstraint(
                           max_skew=1, topology_key=wk.LABEL_ZONE,
                           label_selector=(("app", "w"),))])
        bound = [BoundPod(pod=spreader, node_name="n1", zone="us-east-1a",
                          capacity_type="on-demand", node_labels={})]
        b.build(pods, pools, lattice, bound_pods=bound,
                dirty=DirtySet(since=-1, rev=0, full=True))
        res = b.build(pods, pools, lattice, bound_pods=bound,
                      dirty=DirtySet(since=0, rev=1))
        assert not res.incremental

    def test_touched_bound_pod_with_affinity_rebuilds(self, lattice):
        """A pod first seen BOUND carrying anti-affinity must force a
        full rebuild: only the rebuild compiles bound pods' terms into
        classes that repel matching pending pods (the k8s symmetry rule;
        review finding)."""
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(20)]
        pools = [NodePool(name="default")]
        self._full(b, pods, pools, lattice)
        anti = Pod(name="anti", requests={"cpu": "1"},
                   labels={"app": "solo"}, node_name="node-1",
                   pod_affinity=[PodAffinityTerm(
                       topology_key=wk.LABEL_HOSTNAME, anti=True,
                       label_selector=(("app", "solo"),))])
        res = b.build(pods, pools, lattice,
                      dirty=DirtySet(since=0, rev=1, pods={"anti"},
                                     bins=True),
                      touched={"anti": ("bound", anti)})
        assert not res.incremental and res.reason == "complex-pod-churn"

    def test_count_mismatch_rebuilds(self, lattice):
        b = IncrementalProblemBuilder()
        pods = [_pod(i) for i in range(30)]
        pools = [NodePool(name="default")]
        self._full(b, pods, pools, lattice)
        # a pod vanished from pending with NO journal entry (simulated
        # race): the builder must refuse the delta
        res = b.build(pods[:-1], pools, lattice,
                      dirty=DirtySet(since=0, rev=1))
        assert not res.incremental and res.reason == "count-mismatch"


# ---------------------------------------------------------------------------
# the randomized churn-sequence parity test (the PR's pinned contract)


def _plan_key(oracle):
    """Node-level equivalence key of an ffd_oracle pack: per-bin
    (type, zone, captype, pod count) multiset + existing-bin loads."""
    new = sorted((b.tmask.tobytes(), b.zmask.tobytes(), len(b.pods))
                 for b in oracle.bins if b.pods and not b.is_existing)
    ex = sorted((b.existing_idx, len(b.pods))
                for b in oracle.bins if b.pods and b.is_existing)
    return new, ex, round(oracle.new_node_cost, 6)


class TestChurnSequenceParity:
    @pytest.mark.slow
    def test_200_random_mutations_parity(self, lattice, solver):
        self._run_churn(lattice, solver, steps=200, device_every=40)

    def test_60_random_mutations_parity(self, lattice, solver):
        self._run_churn(lattice, solver, steps=60, device_every=30)

    def _run_churn(self, lattice, solver, steps, device_every):
        rng = np.random.default_rng(42)
        clock = FakeClock()
        cluster = ClusterState(clock)
        pools = {"default": NodePool(name="default")}
        unavailable = UnavailableOfferings(clock)
        from karpenter_provider_aws_tpu.apis.objects import Node
        # a few registered nodes so existing bins participate
        types = [n for n in ("m5.xlarge", "m5.2xlarge", "c5.xlarge")
                 if n in lattice.name_to_idx]
        for i, t in enumerate(types * 2):
            cluster.add_node(Node(
                name=f"node-{i}", provider_id=f"i-{i}", ready=True,
                node_pool="default",
                labels={wk.LABEL_INSTANCE_TYPE: t,
                        wk.LABEL_ZONE: lattice.zones[i % len(lattice.zones)],
                        wk.LABEL_CAPACITY_TYPE: "on-demand"}))
        serial = 0
        for i in range(240):
            serial += 1
            cluster.add_pod(_pod(serial))
        builder = IncrementalProblemBuilder()
        last_rev = -1
        incremental_seen = 0
        for step in range(steps):
            r = rng.random()
            if r < 0.45:
                for _ in range(int(rng.integers(1, 6))):
                    serial += 1
                    cluster.add_pod(_pod(serial))
            elif r < 0.70:
                pending = cluster.pending_pods()
                if pending:
                    victim = pending[int(rng.integers(len(pending)))]
                    if rng.random() < 0.5:
                        cluster.delete_pod(victim.name)
                    else:
                        cluster.bind_pod(victim.name,
                                         f"node-{int(rng.integers(6))}")
            elif r < 0.80:
                bound = [p for p in cluster.snapshot_pods()
                         if p.node_name is not None]
                if bound:
                    cluster.delete_pod(
                        bound[int(rng.integers(len(bound)))].name)
            elif r < 0.90:
                # ICE churn: a new masked view → lattice-changed gate
                t = types[int(rng.integers(len(types)))]
                unavailable.mark_unavailable("ice", "on-demand", t,
                                             lattice.zones[0])
            else:
                # pool template churn → pools-changed gate
                pools["default"].labels["rev"] = f"r{step}"

            view = masked_view_versioned(lattice, unavailable)
            dirty = cluster.dirty_since(last_rev)
            touched = cluster.touched_pods(dirty.pods)
            pending = cluster.pending_pods()
            pool_list = list(pools.values())
            res = builder.build(
                pending, pool_list, view,
                existing=lambda: cluster.existing_bins(view),
                daemonset_pods=cluster.daemonset_pods,
                bound_pods=cluster.bound_pods,
                dirty=dirty, touched=touched)
            last_rev = builder.rev
            if res.incremental:
                incremental_seen += 1

            # the pinned contract: plan-equivalent to a from-scratch
            # rebuild at EVERY step (host FFD referee: deterministic,
            # cost-exact, node-level)
            scratch = build_problem(
                pending, pool_list, view,
                existing=cluster.existing_bins(view),
                daemonset_pods=cluster.daemonset_pods(),
                bound_pods=cluster.bound_pods())
            assert _plan_key(ffd_oracle(res.problem)) == \
                _plan_key(ffd_oracle(scratch)), \
                f"step {step}: incremental problem diverged " \
                f"(incremental={res.incremental}, reason={res.reason!r})"

            if step and step % device_every == 0:
                # device-solve parity on sampled steps: same nodes, same
                # cost through the real solve path
                p1 = (solver.solve_delta(res.problem,
                                         dirty_groups=res.dirty_groups)
                      if res.incremental else solver.solve(res.problem))
                p2 = solver.solve(scratch)
                assert abs(p1.new_node_cost - p2.new_node_cost) < 1e-6
                assert sorted((n.instance_type, n.zone, len(n.pods))
                              for n in p1.new_nodes) == \
                    sorted((n.instance_type, n.zone, len(n.pods))
                           for n in p2.new_nodes)
        # non-vacuous: the delta path must actually have carried steps
        assert incremental_seen > steps // 4, \
            f"only {incremental_seen}/{steps} steps took the delta path"


# ---------------------------------------------------------------------------
# solve_delta counters


class TestSolveDelta:
    def test_counters_and_parity(self, lattice, solver):
        pods = [_pod(i) for i in range(300)]
        pools = [NodePool(name="default")]
        b = IncrementalProblemBuilder()
        res = b.build(pods, pools, lattice,
                      dirty=DirtySet(since=-1, rev=0, full=True))
        solver.solve(res.problem)
        new = Pod(name="d1", requests=SHAPES[1])
        res2 = b.build(pods + [new], pools, lattice,
                       dirty=DirtySet(since=0, rev=1, pods={"d1"}),
                       touched={"d1": ("pending", new)})
        assert res2.incremental
        pre = dict(solver.pipeline_stats)
        plan = solver.solve_delta(res2.problem,
                                  dirty_groups=res2.dirty_groups)
        assert solver.pipeline_stats["delta_solves"] == \
            pre["delta_solves"] + 1
        assert solver.pipeline_stats["delta_dirty_groups"] >= \
            pre["delta_dirty_groups"] + 1
        ref = solver.solve(build_problem(pods + [new], pools, lattice))
        assert abs(plan.new_node_cost - ref.new_node_cost) < 1e-6
        stats = solver.stats()
        assert "delta_solves" in stats
        assert "resident_problem_hits" in stats

    def test_solve_delta_restores_pipeline_flag(self, lattice):
        s = Solver(lattice, pipeline=False)
        pods = [_pod(i) for i in range(20)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        s.solve_delta(problem)
        assert s.pipeline is False


# ---------------------------------------------------------------------------
# SLO warmup window (the cold-compile burn regression)


class TestSloWarmupWindow:
    def test_warmup_drops_cold_samples(self):
        from karpenter_provider_aws_tpu.events import Recorder
        from karpenter_provider_aws_tpu.introspect import SloTracker
        clock = FakeClock()
        rec = Recorder(clock)
        slo = SloTracker(clock, recorder=rec, sustain_seconds=0.0)
        slo.begin_warmup(max_seconds=60.0)
        # the cold-compile first pass: 1.6 s against the 200 ms budget —
        # burn ~8, exactly SOAK_r06's spike
        slo.record_latency(1.6)
        out = slo.update()
        assert out["latency_burn"] < 2.0
        assert not any(e.reason == "SloBudgetBurn" for e in rec.events())
        slo.end_warmup()
        slo.record_latency(1.6)
        clock.step(1.0)
        out = slo.update()
        assert out["latency_burn"] > 2.0   # real signal records again

    def test_warmup_window_expires_on_its_own(self):
        from karpenter_provider_aws_tpu.introspect import SloTracker
        clock = FakeClock()
        slo = SloTracker(clock)
        slo.begin_warmup(max_seconds=10.0)
        assert slo.warmup_active()
        clock.step(11.0)
        assert not slo.warmup_active()
        slo.record_latency(1.6)
        assert slo.update()["latency_burn"] > 2.0

    def test_solver_warmup_on_done_fires(self, lattice):
        s = Solver(lattice)
        fired = []
        t = s.warmup(g_buckets=(16,), b_buckets=(32,), background=True,
                     on_done=lambda: fired.append(True))
        t.join(timeout=120)
        assert fired == [True]


# ---------------------------------------------------------------------------
# persistent compile cache + gz artifacts


class TestBootSatellites:
    def test_enable_persistent_compile_cache(self, tmp_path):
        from karpenter_provider_aws_tpu.solver.solve import (
            enable_persistent_compile_cache)
        assert enable_persistent_compile_cache(str(tmp_path))
        import jax
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)

    def test_compile_cache_dir_option_env(self, monkeypatch):
        from karpenter_provider_aws_tpu.operator.options import Options
        monkeypatch.setenv("COMPILE_CACHE_DIR", "/tmp/kpat-cache")
        assert Options.from_env().compile_cache_dir == "/tmp/kpat-cache"

    def test_monitor_gz_roundtrip(self, tmp_path):
        from karpenter_provider_aws_tpu.debug import load_timeseries

        class _FakeMon:
            pass
        # go through the real Monitor against a minimal operator-shaped
        # object is heavy; exercise write/load directly instead
        from karpenter_provider_aws_tpu.debug import Monitor
        mon = Monitor.__new__(Monitor)
        import threading
        mon.samples = [{"t": 1.0, "nodes": 2, "pending_pods": 0,
                        "cost_per_hour": 1.5}]
        mon._lock = threading.Lock()
        gz = tmp_path / "series.json.gz"
        plain = tmp_path / "series.json"
        mon.write(str(gz))
        mon.write(str(plain))
        # gz really is gzipped
        assert gz.read_bytes()[:2] == b"\x1f\x8b"
        for p in (gz, plain):
            doc = load_timeseries(str(p))
            assert doc["samples"][0]["nodes"] == 2
            assert doc["summary"]["peak_nodes"] == 2
        # suffix lies → sniffing still loads it
        renamed = tmp_path / "renamed.json"
        renamed.write_bytes(gz.read_bytes())
        assert load_timeseries(str(renamed))["samples"]
