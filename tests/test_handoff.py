"""Zero-downtime operator handoff (docs/reference/handoff.md):
state/replication.py snapshot + delta streaming, the fenced cutover
ladder in operator/leaderelection.py, the write barrier in
kube/writer.py, and the OperatorKill weather element."""

import json

import pytest

from karpenter_provider_aws_tpu.apis import Node, NodeClaim, Pod
from karpenter_provider_aws_tpu.apis.objects import Lease as NodeLease
from karpenter_provider_aws_tpu.kube.writer import (
    DirectWriter, FencedWriteError,
)
from karpenter_provider_aws_tpu.operator.leaderelection import (
    FileLeaseStore, LeaderElector, MemoryLeaseStore,
)
from karpenter_provider_aws_tpu.state.cluster import ClusterState
from karpenter_provider_aws_tpu.state.replication import (
    SNAPSHOT_VERSION, ReplicationService, ReplicationSource, StandbyReplica,
)
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    from karpenter_provider_aws_tpu.lattice import (
        build_catalog, build_lattice,
    )
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "t3")])


class _LocalClient:
    """ReplicationClient stand-in that talks straight to the service
    layer (same JSON bodies, no socket) — the transport is covered by
    tools/smoke_handoff.py with two real processes."""

    def __init__(self, service):
        self._service = service
        self.dead = False

    def snapshot(self):
        if self.dead:
            raise ConnectionError("leader unreachable")
        return json.loads(self._service.snapshot(b"{}").decode())

    def delta(self, since):
        if self.dead:
            raise ConnectionError("leader unreachable")
        return json.loads(self._service.delta(
            json.dumps({"since": since}).encode()).decode())


def _pod(name, node=None):
    p = Pod(name=name, requests={"cpu": "1", "memory": "1Gi"})
    if node:
        p.node_name = node
    return p


def _leader_cluster():
    c = ClusterState()
    c.add_pod(_pod("p-0"))
    c.add_pod(_pod("p-1"))
    return c


def _pair(leader_cluster=None):
    src = ReplicationSource(leader_cluster or _leader_cluster())
    client = _LocalClient(ReplicationService(src))
    replica = StandbyReplica(ClusterState(), client)
    return src, client, replica


class TestReplicationStream:
    def test_snapshot_then_delta(self):
        c = _leader_cluster()
        src, client, replica = _pair(c)
        assert replica.sync_once() is True
        assert set(replica.cluster.pods) == {"p-0", "p-1"}
        assert replica.anchor == c.state_rev
        # churn on the leader rides the next delta, not a re-snapshot
        c.add_pod(_pod("p-2"))
        c.delete_pod("p-0")
        assert replica.sync_once() is True
        st = replica.stats()
        assert st["snapshots"] == 1 and st["deltas"] == 1
        assert set(replica.cluster.pods) == {"p-1", "p-2"}
        assert replica.anchor == c.state_rev

    def test_leases_and_pdbs_ride_every_delta(self):
        # leases never journal (their appliers don't _note), so the
        # stream must carry them as full tables on each delta
        c = _leader_cluster()
        src, client, replica = _pair(c)
        replica.sync_once()
        c.add_lease(NodeLease(name="ghost", owner_node=None))
        replica.sync_once()
        assert "ghost" in replica.cluster.leases
        c.delete_lease("ghost")
        replica.sync_once()
        assert "ghost" not in replica.cluster.leases


class TestCutoverLadder:
    """Table-driven: each rung of the standby's apply ladder."""

    CASES = [
        # (mutate_doc, expect_applied, expect_counter, anchor_dropped)
        ("fresh", True, None, False),
        ("stale", False, "stale_anchor_rebuilds", True),
        ("version", False, "version_mismatch_rebuilds", False),
    ]

    @pytest.mark.parametrize("kind,applied,counter,dropped", CASES)
    def test_delta_ladder(self, kind, applied, counter, dropped):
        c = _leader_cluster()
        src, client, replica = _pair(c)
        assert replica.sync_once()
        anchor0 = replica.anchor
        c.add_pod(_pod("p-new"))
        doc = client.delta(anchor0)
        if kind == "stale":
            doc = {"version": SNAPSHOT_VERSION, "full": True,
                   "anchor": doc["anchor"], "since": anchor0, "ticks": 0}
        elif kind == "version":
            doc["version"] = SNAPSHOT_VERSION + 1
        ok = replica._apply_delta(doc)
        assert ok is applied
        if counter:
            assert replica.stats()[counter] == 1
        if dropped:
            assert replica.anchor == -1
        elif not applied:
            # version mismatch keeps the last-good anchor AND state
            assert replica.anchor == anchor0
            assert "p-new" not in replica.cluster.pods

    def test_stale_anchor_resnapshots_in_the_same_poll(self):
        c = _leader_cluster()
        src, client, replica = _pair(c)
        assert replica.sync_once()
        # an anchor from another life of the mirror: the journal cannot
        # answer it, the source says full, the SAME sync re-snapshots
        replica.anchor = 10 ** 9
        c.add_pod(_pod("p-2"))
        assert replica.sync_once() is True
        st = replica.stats()
        assert st["stale_anchor_rebuilds"] == 1
        assert st["snapshots"] == 2
        assert "p-2" in replica.cluster.pods
        assert replica.anchor == c.state_rev

    def test_version_mismatch_snapshot_refused(self):
        src, client, replica = _pair()
        doc = client.snapshot()
        doc["version"] = SNAPSHOT_VERSION + 1
        assert replica._apply_snapshot(doc) is False
        assert replica.anchor == -1
        assert not replica.cluster.pods
        assert "snapshot-version-mismatch" in replica.last_reason


class TestPromotionGate:
    def test_no_snapshot_blocks_promotion(self):
        src, client, replica = _pair()
        client.dead = True
        assert replica.promotion_ready() is False
        assert replica.stats()["promotions_blocked"] == 1
        # and the elector leaves the lease on the floor
        store = MemoryLeaseStore()
        elector = LeaderElector(store, "standby", 15.0, FakeClock(),
                                promotion_gate=replica.promotion_ready)
        assert elector.try_acquire_or_renew() is False
        assert store.get() is None
        assert elector.promotions_blocked == 1

    def test_anchored_replica_promotes_stale(self):
        src, client, replica = _pair()
        assert replica.sync_once()
        client.dead = True
        assert replica.promotion_ready() is True
        assert replica.stats()["stale_promotions"] == 1

    def test_fresh_sync_promotes(self):
        src, client, replica = _pair()
        assert replica.sync_once()
        assert replica.promotion_ready() is True
        assert replica.stats()["stale_promotions"] == 0


class TestFencing:
    def _electors(self, tmp_path):
        clock = FakeClock()
        store = FileLeaseStore(str(tmp_path / "lease.json"))
        a = LeaderElector(store, "op-a", 15.0, clock)
        b = LeaderElector(store, "op-b", 15.0, clock)
        return clock, store, a, b

    def test_fence_rotates_on_takeover_not_renewal(self, tmp_path):
        clock, store, a, b = self._electors(tmp_path)
        assert a.try_acquire_or_renew()
        assert a.fence == 1
        clock.step(5.0)
        assert a.try_acquire_or_renew()
        assert a.fence == 1           # renewal keeps the token
        clock.step(20.0)           # a stops renewing (killed)
        assert b.try_acquire_or_renew()
        assert b.fence == 2           # takeover bumps it

    def test_zombie_writes_rejected(self, tmp_path):
        clock, store, a, b = self._electors(tmp_path)
        assert a.try_acquire_or_renew()
        cluster = ClusterState()
        writer = DirectWriter(cluster, clock)
        writer.set_fence(a.fence_guard())
        claim = NodeClaim(name="c-0", node_pool="default",
                          instance_type="m5.large", zone="us-east-1a",
                          capacity_type="on-demand")
        writer.create_claim(claim)    # fence held: write passes
        clock.step(20.0)
        assert b.try_acquire_or_renew()   # rotates the fence under a
        # the zombie's election thread never ticked, but the guard
        # re-reads the store: every queued side effect bounces
        with pytest.raises(FencedWriteError) as exc:
            writer.create_claim(NodeClaim(
                name="c-1", node_pool="default",
                instance_type="m5.large", zone="us-east-1a",
                capacity_type="on-demand"))
        assert "fenced-write-rejected" in exc.value.reason
        assert "c-1" not in cluster.claims
        assert writer.stats()["fenced_reject"] == 1
        # a bulk verb bounces identically
        with pytest.raises(FencedWriteError):
            writer.bind_pods([(_pod("p-z"), "n-0")])

    def test_reacquire_after_expiry_restores_writes(self, tmp_path):
        clock, store, a, b = self._electors(tmp_path)
        assert a.try_acquire_or_renew()
        writer = DirectWriter(ClusterState(), clock)
        writer.set_fence(a.fence_guard())
        clock.step(20.0)
        assert b.try_acquire_or_renew()
        clock.step(20.0)           # b dies too; a takes back over
        assert a.try_acquire_or_renew()
        assert a.fence == 3
        writer.create_claim(NodeClaim(
            name="c-2", node_pool="default",
            instance_type="m5.large", zone="us-east-1a",
            capacity_type="on-demand"))


class TestFileLeaseStoreCrashSafety:
    CORRUPT_BODIES = [
        b"",                                   # zero-byte (torn create)
        b'{"holder": "op-a", "renewT',         # truncated mid-write
        b"[1, 2, 3]",                          # wrong shape: array
        b'"op-a"',                             # wrong shape: scalar
        b'{"holder": 7, "renewTime": 1.0}',    # non-string holder
        b'{"renewTime": 1.0}',                 # missing holder
        b"not json at all",
    ]

    @pytest.mark.parametrize("body", CORRUPT_BODIES)
    def test_corrupt_lease_reads_unheld(self, tmp_path, body):
        path = tmp_path / "lease.json"
        path.write_bytes(body)
        store = FileLeaseStore(str(path))
        assert store.get() is None
        assert store.corrupt_reads == 1

    def test_election_proceeds_over_the_wreckage(self, tmp_path):
        path = tmp_path / "lease.json"
        path.write_bytes(b'{"holder": "op-a", "ren')
        store = FileLeaseStore(str(path))
        elector = LeaderElector(store, "op-b", 15.0, FakeClock())
        assert elector.try_acquire_or_renew() is True
        assert store.get().holder == "op-b"
        # the wreckage carried no readable fence: takeover starts at 1
        assert elector.fence == 1


class TestOrphanedLeaseSweep:
    def test_sweep_counts_and_deletes(self):
        c = ClusterState()
        c.add_node(Node(name="n-0",
                        provider_id="fake:///us-east-1a/i-0", ready=True))
        c.add_lease(NodeLease(name="n-0", owner_node="n-0"))
        c.add_lease(NodeLease(name="dead-node", owner_node="gone"))
        c.add_lease(NodeLease(name="ownerless", owner_node=None))
        deleted = []
        assert c.sweep_orphaned_leases(deleted.append) == 2
        assert sorted(deleted) == ["dead-node", "ownerless"]
        assert c.stats()["leases_swept"] == 2

    def test_promotion_sweeps_through_the_writer(self):
        # the on_promote wiring: a newly promoted leader GCs leases whose
        # holders died during the blackout, through its own write verb
        clock = FakeClock()
        c = ClusterState()
        c.add_lease(NodeLease(name="blackout-victim", owner_node="gone"))
        writer = DirectWriter(c, clock)
        store = MemoryLeaseStore()
        elector = LeaderElector(
            store, "standby", 15.0, clock,
            on_promote=lambda: c.sweep_orphaned_leases(writer.delete_lease))
        assert elector.try_acquire_or_renew()
        assert "blackout-victim" not in c.leases
        assert c.stats()["leases_swept"] == 1
        assert writer.stats()["delete_lease"] == 1


class TestOperatorKillWeather:
    def _scenario(self, mode="kill"):
        from karpenter_provider_aws_tpu.weather.scenario import (
            OperatorKill, WeatherScenario,
        )
        return WeatherScenario(
            name="t", tick_seconds=1.0, duration_seconds=10.0,
            operator_kills=(OperatorKill(at=2.0, duration=3.0, target=0,
                                         mode=mode, restart_after=True),))

    def test_scenario_round_trip(self):
        from karpenter_provider_aws_tpu.weather.scenario import (
            WeatherScenario,
        )
        sc = self._scenario()
        rt = WeatherScenario.from_dict(sc.to_dict())
        assert rt == sc
        assert rt.operator_kills[0].mode == "kill"

    def test_pre_pr17_json_still_loads(self):
        from karpenter_provider_aws_tpu.weather.scenario import (
            WeatherScenario,
        )
        d = self._scenario().to_dict()
        del d["operator_kills"]
        assert WeatherScenario.from_dict(d).operator_kills == ()

    def test_named_handoff_scenario(self):
        from karpenter_provider_aws_tpu.weather.scenario import (
            NAMED_SCENARIOS, named,
        )
        assert "handoff" in NAMED_SCENARIOS
        sc = named("handoff")
        (kill,) = sc.operator_kills
        assert kill.mode == "kill" and kill.at == 45.0

    def test_simulator_kill_and_restore_events(self, lattice):
        from karpenter_provider_aws_tpu.weather.simulator import (
            WeatherSimulator,
        )

        class Handle:
            def __init__(self):
                self.calls = []

            def kill(self):
                self.calls.append("kill")

            def restart(self):
                self.calls.append("restart")

            def set_hang(self, hung):
                self.calls.append(f"hang={hung}")

        handle = Handle()
        sim = WeatherSimulator(self._scenario(), lattice, seed=7,
                               operators=[handle])
        for _ in range(8):
            sim.step()
        kinds = [e["kind"] for e in sim.timeline
                 if e["kind"].startswith("operator-")]
        assert kinds == ["operator-kill", "operator-restore"]
        assert handle.calls == ["kill", "restart"]
        assert sim.counters["operator_kills"] == 1
        assert sim.counters["operator_restores"] == 1

    def test_hang_mode_pauses_and_resumes(self, lattice):
        from karpenter_provider_aws_tpu.weather.simulator import (
            WeatherSimulator,
        )

        class Handle:
            def __init__(self):
                self.calls = []

            def kill(self):
                self.calls.append("kill")

            def restart(self):
                self.calls.append("restart")

            def set_hang(self, hung):
                self.calls.append(f"hang={hung}")

        handle = Handle()
        sim = WeatherSimulator(self._scenario(mode="hang"), lattice,
                               seed=7, operators=[handle])
        for _ in range(8):
            sim.step()
        assert handle.calls == ["hang=True", "hang=False"]

    def test_replay_identical_with_kills(self, lattice):
        from karpenter_provider_aws_tpu.weather.simulator import (
            WeatherSimulator,
        )
        sc = self._scenario()
        sim = WeatherSimulator(sc, lattice, seed=11)
        for _ in range(10):
            sim.step()
        assert WeatherSimulator.replay(sc, lattice, sim.ticks,
                                       seed=11) == list(sim.timeline)
