"""Requirements algebra tests — oracle-level checks mirroring the core
`Requirements.Compatible` semantics (SURVEY.md §2.2)."""

import pytest

from karpenter_provider_aws_tpu.apis import Operator, Requirement, Requirements
from karpenter_provider_aws_tpu.apis import wellknown as wk


def R(key, op, *values, min_values=None):
    return Requirement(key, op, tuple(values), min_values=min_values)


class TestSatisfiedBy:
    def test_in(self):
        reqs = Requirements([R("arch", Operator.IN, "amd64", "arm64")])
        assert reqs.satisfied_by({"arch": "amd64"})
        assert not reqs.satisfied_by({"arch": "s390x"})
        assert not reqs.satisfied_by({})  # In requires presence

    def test_not_in(self):
        reqs = Requirements([R("zone", Operator.NOT_IN, "us-west-2a")])
        assert reqs.satisfied_by({"zone": "us-west-2b"})
        assert not reqs.satisfied_by({"zone": "us-west-2a"})
        assert reqs.satisfied_by({})  # NotIn passes on absence

    def test_exists_doesnotexist(self):
        assert Requirements([R("gpu", Operator.EXISTS)]).satisfied_by({"gpu": "t4"})
        assert not Requirements([R("gpu", Operator.EXISTS)]).satisfied_by({})
        assert Requirements([R("gpu", Operator.DOES_NOT_EXIST)]).satisfied_by({})
        assert not Requirements([R("gpu", Operator.DOES_NOT_EXIST)]).satisfied_by({"gpu": "t4"})

    def test_gt_lt(self):
        reqs = Requirements([R("cpu", Operator.GT, "4"), R("cpu", Operator.LT, "64")])
        assert reqs.satisfied_by({"cpu": "8"})
        assert not reqs.satisfied_by({"cpu": "4"})   # strict
        assert not reqs.satisfied_by({"cpu": "64"})  # strict
        assert not reqs.satisfied_by({})

    def test_same_key_intersection(self):
        reqs = Requirements([
            R("size", Operator.IN, "large", "xlarge", "2xlarge"),
            R("size", Operator.NOT_IN, "xlarge"),
        ])
        assert reqs.satisfied_by({"size": "large"})
        assert not reqs.satisfied_by({"size": "xlarge"})


class TestIntersects:
    def test_disjoint_in_sets(self):
        a = Requirements([R("arch", Operator.IN, "amd64")])
        b = Requirements([R("arch", Operator.IN, "arm64")])
        assert not a.intersects(b)

    def test_overlapping_in_sets(self):
        a = Requirements([R("arch", Operator.IN, "amd64", "arm64")])
        b = Requirements([R("arch", Operator.IN, "arm64")])
        assert a.intersects(b)

    def test_unconstrained_well_known_key_is_wildcard(self):
        a = Requirements([R(wk.LABEL_ARCH, Operator.IN, "amd64")])
        b = Requirements([R(wk.LABEL_ZONE, Operator.IN, "us-west-2a")])
        assert a.intersects(b)

    def test_in_vs_notin(self):
        a = Requirements([R("type", Operator.IN, "m5.large")])
        b = Requirements([R("type", Operator.NOT_IN, "m5.large")])
        assert not a.intersects(b)
        c = Requirements([R("type", Operator.NOT_IN, "c5.large")])
        assert a.intersects(c)

    def test_exists_vs_doesnotexist(self):
        a = Requirements([R("gpu", Operator.EXISTS)])
        b = Requirements([R("gpu", Operator.DOES_NOT_EXIST)])
        assert not a.intersects(b)

    def test_doesnotexist_vs_notin(self):
        # absence satisfies both
        a = Requirements([R("gpu", Operator.DOES_NOT_EXIST)])
        b = Requirements([R("gpu", Operator.NOT_IN, "t4")])
        assert a.intersects(b)

    def test_gt_lt_interval_overlap(self):
        a = Requirements([R("cpu", Operator.GT, "4")])
        b = Requirements([R("cpu", Operator.LT, "8")])
        assert a.intersects(b)
        # integers strictly between 4 and 5: none
        c = Requirements([R("cpu", Operator.GT, "4"), R("cpu", Operator.LT, "5")])
        d = Requirements([R("cpu", Operator.EXISTS)])
        assert not c.intersects(d)

    def test_in_vs_interval(self):
        a = Requirements([R("cpu", Operator.IN, "2", "4")])
        b = Requirements([R("cpu", Operator.GT, "3")])
        assert a.intersects(b)
        c = Requirements([R("cpu", Operator.GT, "8")])
        assert not a.intersects(c)


class TestMinValues:
    def test_min_values(self):
        reqs = Requirements([
            R("family", Operator.IN, "c5", "m5", "r5", min_values=2),
        ])
        assert reqs.min_values_satisfied({"family": ["c5", "m5", "c6i"]})
        assert not reqs.min_values_satisfied({"family": ["c5"]})
        assert not reqs.min_values_satisfied({})


class TestValidation:
    def test_gt_requires_single_numeric(self):
        with pytest.raises(ValueError):
            Requirement("cpu", Operator.GT, ("a",))
        with pytest.raises(ValueError):
            Requirement("cpu", Operator.GT, ("1", "2"))

    def test_exists_no_values(self):
        with pytest.raises(ValueError):
            Requirement("k", Operator.EXISTS, ("v",))

    def test_empty_in(self):
        with pytest.raises(ValueError):
            Requirement("k", Operator.IN, ())


def test_nodepool_requirements_include_pool_label():
    from karpenter_provider_aws_tpu.apis import NodePool
    np_ = NodePool(name="default", requirements=[R(wk.LABEL_ARCH, Operator.IN, "amd64")])
    reqs = np_.scheduling_requirements()
    assert reqs.satisfied_by({wk.LABEL_NODEPOOL: "default", wk.LABEL_ARCH: "amd64"})
    assert not reqs.satisfied_by({wk.LABEL_NODEPOOL: "other", wk.LABEL_ARCH: "amd64"})


def test_tolerations():
    from karpenter_provider_aws_tpu.apis.objects import Taint, TaintEffect, Toleration, tolerates_all
    taints = [Taint("dedicated", "gpu", TaintEffect.NO_SCHEDULE)]
    assert not tolerates_all([], taints)
    assert tolerates_all([Toleration("dedicated", "Equal", "gpu")], taints)
    assert tolerates_all([Toleration("dedicated", "Exists")], taints)
    assert tolerates_all([Toleration(operator="Exists")], taints)  # tolerate-everything
    assert not tolerates_all([Toleration("dedicated", "Equal", "ml")], taints)
    # PreferNoSchedule is soft — never blocks
    soft = [Taint("x", "y", TaintEffect.PREFER_NO_SCHEDULE)]
    assert tolerates_all([], soft)


class TestUndefinedKeySemantics:
    """Reference cloudprovider.go:248: Compatible(..., AllowUndefinedWellKnownLabels)."""

    def test_custom_key_undefined_on_other_side_incompatible(self):
        pod = Requirements([R("example.com/team", Operator.IN, "ml")])
        claim = Requirements([R(wk.LABEL_ARCH, Operator.IN, "amd64")])
        assert not pod.intersects(claim)
        assert not claim.intersects(pod)

    def test_well_known_key_undefined_on_other_side_ok(self):
        pod = Requirements([R(wk.LABEL_INSTANCE_CPU, Operator.GT, "4")])
        claim = Requirements([R(wk.LABEL_ARCH, Operator.IN, "amd64")])
        assert pod.intersects(claim)

    def test_absence_tolerant_custom_key_ok(self):
        pod = Requirements([R("example.com/team", Operator.NOT_IN, "infra")])
        claim = Requirements([R(wk.LABEL_ARCH, Operator.IN, "amd64")])
        assert pod.intersects(claim)


def test_resources_to_vec_checked_unknown():
    from karpenter_provider_aws_tpu.apis import resources_to_vec_checked
    vec, unknown = resources_to_vec_checked({"cpu": "1", "hugepages-2Mi": "1Gi"}, implicit_pod=True)
    assert unknown == ("hugepages-2Mi",)
    assert vec[0] == 1000.0


class TestDirectionalCompatible:
    def test_pool_custom_label_is_not_a_demand_on_pods(self):
        from karpenter_provider_aws_tpu.apis.requirements import Requirements
        pod = Requirements.from_node_selector({})
        pool = Requirements.from_labels({"team": "infra"})
        assert pod.compatible_with(pool)
        # but a pod selecting a DIFFERENT team value is incompatible
        pod2 = Requirements.from_node_selector({"team": "web"})
        assert not pod2.compatible_with(pool)
        # and a matching selector is compatible
        pod3 = Requirements.from_node_selector({"team": "infra"})
        assert pod3.compatible_with(pool)

    def test_existence_on_unknown_custom_key_fails_directionally(self):
        from karpenter_provider_aws_tpu.apis.requirements import (
            Operator, Requirement, Requirements,
        )
        pod = Requirements([Requirement("example.com/special", Operator.EXISTS)])
        pool = Requirements.from_labels({})
        assert not pod.compatible_with(pool)
