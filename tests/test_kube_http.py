"""The apiserver over HTTP: the wire-reachable ingest boundary.

An external agent (urllib here, standing in for any non-Python client)
drives the SAME control plane the in-process controllers reconcile —
create pods over REST, watch the node stream, observe the operator
provision; protocol errors map to the real status codes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod, serde
from karpenter_provider_aws_tpu.kube import FakeAPIServer, install_admission
from karpenter_provider_aws_tpu.kube.httpserver import serve


@pytest.fixture()
def api():
    s = FakeAPIServer()
    install_admission(s)
    httpd = serve(s, 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield s, base
    httpd.shutdown()


def req(method, url, doc=None):
    r = urllib.request.Request(
        url, method=method,
        data=None if doc is None else json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read())


def status_of(err_ctx):
    return err_ctx.value.code


class TestRestVerbs:
    def test_create_get_list_roundtrip(self, api):
        _, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        code, obj = req("POST", f"{base}/apis/pods", spec)
        assert code == 201 and obj["metadata"]["name"] == "p0"
        code, got = req("GET", f"{base}/apis/pods/p0")
        assert got["spec"]["requests"]["cpu"] == "1"
        code, listed = req("GET", f"{base}/apis/pods")
        assert len(listed["items"]) == 1
        assert listed["resourceVersion"] >= 1

    def test_update_conflict_409(self, api):
        _, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        req("POST", f"{base}/apis/pods", spec)
        _, obj = req("GET", f"{base}/apis/pods/p0")
        req("PATCH", f"{base}/apis/pods/p0", {"spec": {"priority": 1}})
        obj["spec"]["priority"] = 2
        with pytest.raises(urllib.error.HTTPError) as e:
            req("PUT", f"{base}/apis/pods/p0", obj)
        assert status_of(e) == 409

    def test_admission_422_with_causes(self, api):
        _, base = api
        bad = serde.nodepool_to_dict(NodePool(name="bad"))
        bad["disruption"]["budgets"] = [{"nodes": "150%"}]
        with pytest.raises(urllib.error.HTTPError) as e:
            req("POST", f"{base}/apis/nodepools", bad)
        assert status_of(e) == 422
        causes = json.loads(e.value.read())["causes"]
        assert any("nodes" in c for c in causes)

    def test_missing_404_unknown_kind_400(self, api):
        _, base = api
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/pods/ghost")
        assert status_of(e) == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/gadgets")
        assert status_of(e) == 400

    def test_binding_and_eviction_subresources(self, api):
        server, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        req("POST", f"{base}/apis/pods", spec)
        req("POST", f"{base}/apis/pods/p0/binding", {"nodeName": "n0"})
        assert server.get("pods", "p0")["spec"]["nodeName"] == "n0"
        req("POST", f"{base}/apis/pods/p0/eviction", {})
        assert server.get("pods", "p0")["spec"].get("nodeName") is None

    def test_eviction_blocked_429(self, api):
        server, base = api
        from karpenter_provider_aws_tpu.apis import PodDisruptionBudget
        req("POST", f"{base}/apis/pods", serde.pod_to_dict(
            Pod(name="p0", requests={"cpu": "1", "memory": "1Gi"},
                node_name="n0", labels={"app": "db"})))
        req("POST", f"{base}/apis/pdbs", serde.pdb_to_dict(
            PodDisruptionBudget(name="pdb", label_selector={"app": "db"},
                                min_available=1)))
        with pytest.raises(urllib.error.HTTPError) as e:
            req("POST", f"{base}/apis/pods/p0/eviction", {})
        assert status_of(e) == 429

    def test_finalizer_delete_flow(self, api):
        server, base = api
        from karpenter_provider_aws_tpu.apis.objects import NodeClaim
        from karpenter_provider_aws_tpu.kube import KubeClient
        KubeClient(server).create_nodeclaim(
            NodeClaim(name="c0", node_pool="default"))
        req("DELETE", f"{base}/apis/nodeclaims/c0")
        _, obj = req("GET", f"{base}/apis/nodeclaims/c0")
        assert obj["metadata"]["deletionTimestamp"] is not None
        req("PATCH", f"{base}/apis/nodeclaims/c0", {"finalizers": []})
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/nodeclaims/c0")
        assert status_of(e) == 404


class TestWatchStream:
    def test_watch_delivers_events_as_json_lines(self, api):
        server, base = api
        got = []

        def reader():
            r = urllib.request.urlopen(
                f"{base}/apis/pods?watch=1&resourceVersion=0", timeout=10)
            for line in r:
                ev = json.loads(line)
                if ev["type"] == "HEARTBEAT":
                    continue
                got.append(ev)
                if len(got) >= 2:
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.1)
        for i in range(2):
            server.create("pods", serde.pod_to_dict(
                Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})))
        t.join(10)
        assert [e["type"] for e in got] == ["ADDED", "ADDED"]
        assert got[0]["object"]["metadata"]["name"] == "p0"
        assert got[0]["resourceVersion"] < got[1]["resourceVersion"]

    def test_watch_too_old_410(self, api):
        import collections
        server, base = api
        server._history["pods"] = collections.deque(maxlen=2)
        for i in range(5):
            server.create("pods", serde.pod_to_dict(
                Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{base}/apis/pods?watch=1&resourceVersion=1", timeout=5)
        assert status_of(e) == 410


class TestExternalAgentDrivesControlPlane:
    def test_rest_created_pods_get_capacity(self):
        """The full story: an external agent creates pods over HTTP; the
        operator (informer-fed) provisions; the agent observes nodes and
        bound pods over HTTP. No shared memory with the scenario at all."""
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=build_lattice([s for s in build_catalog()
                                             if s.family in ("m5", "t3")]),
                      clock=clock, api_server=server)
        httpd = serve(server, 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            for i in range(3):
                req("POST", f"{base}/apis/pods", serde.pod_to_dict(
                    Pod(name=f"w{i}",
                        requests={"cpu": "1", "memory": "2Gi"})))
            op.settle()
            _, pods = req("GET", f"{base}/apis/pods")
            assert all(o["spec"].get("nodeName") for o in pods["items"])
            _, nodes = req("GET", f"{base}/apis/nodes")
            assert nodes["items"], "no nodes visible over REST"
        finally:
            httpd.shutdown()


class TestReviewRegressions:
    def test_wrong_verb_on_subresource_is_404_not_parent_action(self, api):
        """DELETE /apis/pods/p0/eviction must NEVER delete the pod."""
        server, base = api
        req("POST", f"{base}/apis/pods", serde.pod_to_dict(
            Pod(name="p0", requests={"cpu": "1", "memory": "1Gi"})))
        for method in ("DELETE", "PUT", "PATCH", "GET"):
            with pytest.raises(urllib.error.HTTPError) as e:
                req(method, f"{base}/apis/pods/p0/eviction",
                    {} if method != "GET" else None)
            assert status_of(e) == 404, method
        server.get("pods", "p0")   # still exists

    def test_binds_loopback_by_default(self, api):
        _, base = api
        assert "127.0.0.1" in base
