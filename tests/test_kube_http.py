"""The apiserver over HTTP: the wire-reachable ingest boundary.

An external agent (urllib here, standing in for any non-Python client)
drives the SAME control plane the in-process controllers reconcile —
create pods over REST, watch the node stream, observe the operator
provision; protocol errors map to the real status codes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod, serde
from karpenter_provider_aws_tpu.kube import FakeAPIServer, install_admission
from karpenter_provider_aws_tpu.kube.httpserver import serve


@pytest.fixture()
def api():
    s = FakeAPIServer()
    install_admission(s)
    httpd = serve(s, 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield s, base
    httpd.shutdown()


def req(method, url, doc=None):
    r = urllib.request.Request(
        url, method=method,
        data=None if doc is None else json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read())


def status_of(err_ctx):
    return err_ctx.value.code


class TestRestVerbs:
    def test_create_get_list_roundtrip(self, api):
        _, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        code, obj = req("POST", f"{base}/apis/pods", spec)
        assert code == 201 and obj["metadata"]["name"] == "p0"
        code, got = req("GET", f"{base}/apis/pods/p0")
        assert got["spec"]["requests"]["cpu"] == "1"
        code, listed = req("GET", f"{base}/apis/pods")
        assert len(listed["items"]) == 1
        assert listed["resourceVersion"] >= 1

    def test_named_get_with_watch_param_returns_object(self, api):
        """A stray watch=1 on a NAMED path must return the object, not
        silently discard the name into a kind-wide stream."""
        _, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1",
                                               "memory": "1Gi"}))
        req("POST", f"{base}/apis/pods", spec)
        code, got = req("GET", f"{base}/apis/pods/p0?watch=1")
        assert code == 200 and got["metadata"]["name"] == "p0"

    def test_update_conflict_409(self, api):
        _, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        req("POST", f"{base}/apis/pods", spec)
        _, obj = req("GET", f"{base}/apis/pods/p0")
        req("PATCH", f"{base}/apis/pods/p0", {"spec": {"priority": 1}})
        obj["spec"]["priority"] = 2
        with pytest.raises(urllib.error.HTTPError) as e:
            req("PUT", f"{base}/apis/pods/p0", obj)
        assert status_of(e) == 409

    def test_admission_422_with_causes(self, api):
        _, base = api
        bad = serde.nodepool_to_dict(NodePool(name="bad"))
        bad["disruption"]["budgets"] = [{"nodes": "150%"}]
        with pytest.raises(urllib.error.HTTPError) as e:
            req("POST", f"{base}/apis/nodepools", bad)
        assert status_of(e) == 422
        causes = json.loads(e.value.read())["causes"]
        assert any("nodes" in c for c in causes)

    def test_missing_404_unknown_kind_400(self, api):
        _, base = api
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/pods/ghost")
        assert status_of(e) == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/gadgets")
        assert status_of(e) == 400

    def test_binding_and_eviction_subresources(self, api):
        server, base = api
        spec = serde.pod_to_dict(Pod(name="p0",
                                     requests={"cpu": "1", "memory": "1Gi"}))
        req("POST", f"{base}/apis/pods", spec)
        req("POST", f"{base}/apis/pods/p0/binding", {"nodeName": "n0"})
        assert server.get("pods", "p0")["spec"]["nodeName"] == "n0"
        req("POST", f"{base}/apis/pods/p0/eviction", {})
        assert server.get("pods", "p0")["spec"].get("nodeName") is None

    def test_eviction_blocked_429(self, api):
        server, base = api
        from karpenter_provider_aws_tpu.apis import PodDisruptionBudget
        req("POST", f"{base}/apis/pods", serde.pod_to_dict(
            Pod(name="p0", requests={"cpu": "1", "memory": "1Gi"},
                node_name="n0", labels={"app": "db"})))
        req("POST", f"{base}/apis/pdbs", serde.pdb_to_dict(
            PodDisruptionBudget(name="pdb", label_selector={"app": "db"},
                                min_available=1)))
        with pytest.raises(urllib.error.HTTPError) as e:
            req("POST", f"{base}/apis/pods/p0/eviction", {})
        assert status_of(e) == 429

    def test_finalizer_delete_flow(self, api):
        server, base = api
        from karpenter_provider_aws_tpu.apis.objects import NodeClaim
        from karpenter_provider_aws_tpu.kube import KubeClient
        KubeClient(server).create_nodeclaim(
            NodeClaim(name="c0", node_pool="default"))
        req("DELETE", f"{base}/apis/nodeclaims/c0")
        _, obj = req("GET", f"{base}/apis/nodeclaims/c0")
        assert obj["metadata"]["deletionTimestamp"] is not None
        req("PATCH", f"{base}/apis/nodeclaims/c0", {"finalizers": []})
        with pytest.raises(urllib.error.HTTPError) as e:
            req("GET", f"{base}/apis/nodeclaims/c0")
        assert status_of(e) == 404


class TestWatchStream:
    def test_watch_delivers_events_as_json_lines(self, api):
        server, base = api
        got = []

        def reader():
            r = urllib.request.urlopen(
                f"{base}/apis/pods?watch=1&resourceVersion=0", timeout=10)
            for line in r:
                ev = json.loads(line)
                if ev["type"] == "HEARTBEAT":
                    continue
                got.append(ev)
                if len(got) >= 2:
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.1)
        for i in range(2):
            server.create("pods", serde.pod_to_dict(
                Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})))
        t.join(10)
        assert [e["type"] for e in got] == ["ADDED", "ADDED"]
        assert got[0]["object"]["metadata"]["name"] == "p0"
        assert got[0]["resourceVersion"] < got[1]["resourceVersion"]

    def test_watch_too_old_410(self, api):
        import collections
        server, base = api
        server._history["pods"] = collections.deque(maxlen=2)
        for i in range(5):
            server.create("pods", serde.pod_to_dict(
                Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{base}/apis/pods?watch=1&resourceVersion=1", timeout=5)
        assert status_of(e) == 410


class TestWatchReconnectOver410:
    def test_reflector_relists_after_410_over_http(self, api):
        """The client-go reflector contract, ON THE WIRE: a watcher whose
        RV fell off the ring gets 410, relists over REST, and resumes
        watching from the fresh RV with no lost objects."""
        import collections
        server, base = api
        server._history["pods"] = collections.deque(maxlen=2)
        for i in range(5):
            server.create("pods", serde.pod_to_dict(
                Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})))
        # stale watch → 410 Gone
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{base}/apis/pods?watch=1&resourceVersion=1", timeout=5)
        assert status_of(e) == 410
        # recovery: relist, then watch from the listed RV
        _, listed = req("GET", f"{base}/apis/pods")
        store = {o["metadata"]["name"] for o in listed["items"]}
        assert store == {f"p{i}" for i in range(5)}
        got = []

        def reader():
            r = urllib.request.urlopen(
                f"{base}/apis/pods?watch=1"
                f"&resourceVersion={listed['resourceVersion']}", timeout=10)
            for line in r:
                ev = json.loads(line)
                if ev["type"] == "HEARTBEAT":
                    continue
                got.append(ev)
                return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        time.sleep(0.1)
        server.create("pods", serde.pod_to_dict(
            Pod(name="p-after", requests={"cpu": "1", "memory": "1Gi"})))
        t.join(10)
        assert [e["object"]["metadata"]["name"] for e in got] == ["p-after"]


class TestAuthAndTLS:
    def test_bearer_token_required_when_enabled(self):
        s = FakeAPIServer()
        httpd = serve(s, 0, token="s3cret")
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                req("GET", f"{base}/apis/pods")
            assert status_of(e) == 401
            r = urllib.request.Request(
                f"{base}/apis/pods",
                headers={"Authorization": "Bearer wrong"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(r, timeout=5)
            assert status_of(e) == 401
            r = urllib.request.Request(
                f"{base}/apis/pods",
                headers={"Authorization": "Bearer s3cret"})
            with urllib.request.urlopen(r, timeout=5) as resp:
                assert resp.status == 200
        finally:
            httpd.shutdown()

    def test_tls_serves_https(self, tmp_path):
        import ssl
        import subprocess
        crt, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-days", "1", "-keyout", str(key), "-out", str(crt),
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True)
        s = FakeAPIServer()
        httpd = serve(s, 0, token="t0k", certfile=str(crt),
                      keyfile=str(key))
        port = httpd.server_address[1]
        try:
            ctx = ssl.create_default_context(cafile=str(crt))
            r = urllib.request.Request(
                f"https://127.0.0.1:{port}/apis/pods",
                headers={"Authorization": "Bearer t0k"})
            with urllib.request.urlopen(r, timeout=5, context=ctx) as resp:
                assert resp.status == 200
                assert json.loads(resp.read())["items"] == []
        finally:
            httpd.shutdown()

    def test_stalled_tls_client_does_not_block_other_connections(
            self, tmp_path):
        """The TLS handshake runs per-connection (TLSThreadingHTTPServer):
        a client that connects and sends NOTHING must not stall accept()
        — a concurrent well-formed request still answers."""
        import socket
        import ssl
        import subprocess
        crt, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-days", "1", "-keyout", str(key), "-out", str(crt),
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True)
        s = FakeAPIServer()
        httpd = serve(s, 0, certfile=str(crt), keyfile=str(key))
        port = httpd.server_address[1]
        stall = socket.create_connection(("127.0.0.1", port))
        try:
            ctx = ssl.create_default_context(cafile=str(crt))
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{port}/apis/pods",
                    timeout=5, context=ctx) as resp:
                assert resp.status == 200
        finally:
            stall.close()
            httpd.shutdown()

    def test_non_ascii_auth_header_is_401_not_crash(self):
        s = FakeAPIServer()
        httpd = serve(s, 0, token="tok")
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            r = urllib.request.Request(
                f"{base}/apis/pods",
                headers={"Authorization": "Bearer café"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(r, timeout=5)
            assert status_of(e) == 401
        finally:
            httpd.shutdown()

    def test_cli_refuses_public_plaintext_bind(self):
        """Serving the write-capable surface beyond loopback without
        TLS+token must exit unless --api-insecure is explicit."""
        from karpenter_provider_aws_tpu.cli import main
        with pytest.raises(SystemExit) as e:
            main(["--api-port", "1", "--api-host", "0.0.0.0",
                  "--duration", "0.1", "--metrics-port", "0"])
        assert "refusing" in str(e.value)


class TestExternalAgentDrivesControlPlane:
    def test_rest_created_pods_get_capacity(self):
        """The full story: an external agent creates pods over HTTP; the
        operator (informer-fed) provisions; the agent observes nodes and
        bound pods over HTTP. No shared memory with the scenario at all."""
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=build_lattice([s for s in build_catalog()
                                             if s.family in ("m5", "t3")]),
                      clock=clock, api_server=server)
        httpd = serve(server, 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            for i in range(3):
                req("POST", f"{base}/apis/pods", serde.pod_to_dict(
                    Pod(name=f"w{i}",
                        requests={"cpu": "1", "memory": "2Gi"})))
            op.settle()
            _, pods = req("GET", f"{base}/apis/pods")
            assert all(o["spec"].get("nodeName") for o in pods["items"])
            _, nodes = req("GET", f"{base}/apis/nodes")
            assert nodes["items"], "no nodes visible over REST"
        finally:
            httpd.shutdown()


class TestReviewRegressions:
    def test_wrong_verb_on_subresource_is_404_not_parent_action(self, api):
        """DELETE /apis/pods/p0/eviction must NEVER delete the pod."""
        server, base = api
        req("POST", f"{base}/apis/pods", serde.pod_to_dict(
            Pod(name="p0", requests={"cpu": "1", "memory": "1Gi"})))
        for method in ("DELETE", "PUT", "PATCH", "GET"):
            with pytest.raises(urllib.error.HTTPError) as e:
                req(method, f"{base}/apis/pods/p0/eviction",
                    {} if method != "GET" else None)
            assert status_of(e) == 404, method
        server.get("pods", "p0")   # still exists

    def test_binds_loopback_by_default(self, api):
        _, base = api
        assert "127.0.0.1" in base


def _kpctl_get_json_with_early_close(server, base, n_pods, prefix):
    """Seed n_pods, run `kpctl get pods -o json` as a subprocess, close
    its stdout after one byte (the `| head -c1` shape), and return
    (returncode, stderr)."""
    import pathlib
    import subprocess
    import sys as _sys
    for i in range(n_pods):
        server.create("pods", serde.pod_to_dict(Pod(
            name=f"{prefix}{i}", requests={"cpu": "1", "memory": "1Gi"})))
    kpctl = (pathlib.Path(__file__).resolve().parent.parent /
             "tools" / "kpctl.py")
    proc = subprocess.Popen(
        [_sys.executable, str(kpctl), "--server", base,
         "get", "pods", "-o", "json"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    proc.stdout.read(1)
    proc.stdout.close()              # reader goes away mid-stream
    rc = proc.wait(timeout=30)
    err = proc.stderr.read().decode()
    proc.stderr.close()
    return rc, err


class TestKpctlPipeHygiene:
    def test_epipe_exits_quietly(self, api):
        """`kpctl get -o json | head -c1` closes kpctl's stdout early;
        the CLI must exit with 128+SIGPIPE like kubectl, not dump a
        BrokenPipeError traceback. 400 pods ≈ 160 KB of JSON overruns
        the 64 KB pipe buffer, so the EPIPE reliably fires mid-write."""
        server, base = api
        rc, err = _kpctl_get_json_with_early_close(server, base, 400, "pp")
        assert rc == 141, (rc, err)
        assert "Traceback" not in err, err

    def test_epipe_at_flush_time_exits_quietly(self, api):
        """Outputs UNDER the 64 KB pipe buffer take the EPIPE at flush
        time, not mid-write; without an in-try flush that lands at
        interpreter shutdown as 'Exception ignored' noise with exit
        code 120."""
        server, base = api
        rc, err = _kpctl_get_json_with_early_close(server, base, 25, "fp")
        assert rc in (0, 141), (rc, err)   # raced: may finish clean
        assert "Exception ignored" not in err, err
        assert "Traceback" not in err, err


class TestWatchHeartbeat:
    def test_idle_watch_emits_heartbeats_then_resumes_events(
            self, api, monkeypatch):
        """An idle watch stream carries periodic HEARTBEAT lines (the
        half-open-connection detector) and still delivers real events
        afterward."""
        from karpenter_provider_aws_tpu.kube import httpserver as hs
        monkeypatch.setattr(hs, "WATCH_HEARTBEAT_SECONDS", 0.2)
        s, base = api
        resp = urllib.request.urlopen(
            f"{base}/apis/pods?watch=1&resourceVersion=0", timeout=10)
        # idle: the first line must be a heartbeat, not a real event
        line = json.loads(resp.readline())
        assert line["type"] == "HEARTBEAT"
        # liveness resumes: a create lands as an ADDED after heartbeats
        s.create("pods", serde.pod_to_dict(
            Pod(name="hb-pod", requests={"cpu": "1", "memory": "1Gi"})))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            line = json.loads(resp.readline())
            if line["type"] != "HEARTBEAT":
                break
        assert line["type"] == "ADDED"
        assert line["object"]["metadata"]["name"] == "hb-pod"
        resp.close()


class TestKpctlDescribe:
    def test_describe_without_events_shows_none(self, api, capsys,
                                                monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        s, base = api
        s.create("pods", serde.pod_to_dict(
            Pod(name="d-pod", requests={"cpu": "1", "memory": "1Gi"})))
        rc = kpctl.main(["--server", base, "describe", "pods", "d-pod"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Name:             d-pod" in out
        assert "Spec:" in out and '"cpu": "1"' in out
        assert "Events:" in out and "<none>" in out

    def test_describe_matches_kind_not_just_name(self, api, capsys,
                                                 monkeypatch):
        """A Node shares its NodeClaim's name; describe must attribute
        events by kind+name like kubectl (review r5)."""
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        from karpenter_provider_aws_tpu.events import Recorder
        from karpenter_provider_aws_tpu.kube.eventsink import ApiEventSink
        s, base = api
        s.create("pods", serde.pod_to_dict(
            Pod(name="shared", requests={"cpu": "1", "memory": "1Gi"})))
        r = Recorder()
        r.sink = ApiEventSink(s)
        r.publish("Normal", "Launched", "NodeClaim", "shared", "not yours")
        r.publish("Normal", "Scheduled", "Pod", "shared", "yours")
        rc = kpctl.main(["--server", base, "describe", "pods", "shared"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Scheduled" in out and "yours" in out
        assert "Launched" not in out and "not yours" not in out


class TestKpctlYamlOutput:
    def test_get_o_yaml_round_trips(self, api, capsys, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        import yaml
        s, base = api
        s.create("pods", serde.pod_to_dict(
            Pod(name="y-pod", requests={"cpu": "2", "memory": "4Gi"})))
        rc = kpctl.main(["--server", base, "get", "pods", "y-pod",
                         "-o", "yaml"])
        out = capsys.readouterr().out
        assert rc == 0
        doc = yaml.safe_load(out)
        assert doc["metadata"]["name"] == "y-pod"
        assert doc["spec"]["requests"]["cpu"] == "2"


class TestDiscovery:
    def test_apis_lists_served_kinds(self, api):
        from karpenter_provider_aws_tpu.kube.apiserver import KINDS
        _, base = api
        code, doc = req("GET", f"{base}/apis")
        assert code == 200
        assert doc["kinds"] == list(KINDS)

    def test_kpctl_api_resources(self, api, capsys, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        _, base = api
        rc = kpctl.main(["--server", base, "api-resources"])
        out = capsys.readouterr().out.split()
        assert rc == 0
        assert "nodepools" in out and "events" in out
