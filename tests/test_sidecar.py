"""Solver sidecar tests: serde round-trips and the gRPC transport
(SURVEY §2.3 communication backend; §7 "gRPC sidecar in-process first")."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOp, Pod, Requirement
from karpenter_provider_aws_tpu.apis.resources import R
from karpenter_provider_aws_tpu.apis import serde
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import (
    PodAffinityTerm, PreferredRequirement, Taint, Toleration,
    TopologySpreadConstraint,
)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import ExistingBin, Solver, build_problem


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5", "t3")])


def rich_pod():
    return Pod(
        name="rich", labels={"app": "x"},
        requests={"cpu": "500m", "memory": "1Gi"},
        node_selector={wk.LABEL_ARCH: "amd64"},
        required_affinity=[Requirement(wk.LABEL_INSTANCE_CATEGORY,
                                       ReqOp.IN, ("m", "c"))],
        preferred_affinity=[PreferredRequirement(
            Requirement(wk.LABEL_ZONE, ReqOp.IN, ("us-west-2a",)), weight=5)],
        tolerations=[Toleration(key="dedicated", operator="Equal",
                                value="batch")],
        topology_spread=[TopologySpreadConstraint(
            max_skew=1, topology_key=wk.LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=(("app", "x"),))],
        pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                      label_selector=(("app", "x"),),
                                      anti=True)],
        volume_claims=["data-0"], priority=3)


class TestSerde:
    def test_pod_round_trip_preserves_scheduling_signature(self):
        from karpenter_provider_aws_tpu.solver.problem import _group_key
        p = rich_pod()
        q = serde.pod_from_dict(serde.pod_to_dict(p))
        rk = frozenset({"app"})
        assert _group_key(p, rk, {}) == _group_key(q, rk, {})
        assert q.priority == 3 and q.volume_claims == ["data-0"]

    def test_nodepool_round_trip(self):
        from karpenter_provider_aws_tpu.controllers.provisioning import nodepool_hash
        pool = NodePool(
            name="batch", weight=10, labels={"team": "batch"},
            taints=[Taint(key="dedicated", value="batch")],
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("spot",), min_values=2)],
            limits={"cpu": "100"})
        q = serde.nodepool_from_dict(serde.nodepool_to_dict(pool))
        assert nodepool_hash(pool) == nodepool_hash(q)
        assert q.requirements[0].min_values == 2
        assert q.limits == {"cpu": "100"}

    def test_existing_bin_round_trip(self):
        b = ExistingBin(name="n0", node_pool="default",
                        instance_type="m5.large", zone="us-west-2a",
                        capacity_type="on-demand",
                        used=np.arange(8, dtype=np.float32))
        q = serde.existing_bin_from_dict(serde.existing_bin_to_dict(b))
        assert q.name == b.name and q.instance_type == b.instance_type
        np.testing.assert_allclose(q.used, b.used)


class TestSidecarTransport:
    def test_solve_and_health_over_unix_socket(self, lattice, tmp_path):
        from karpenter_provider_aws_tpu.parallel.sidecar import (
            SolverClient, serve,
        )
        addr = f"unix:{tmp_path}/solver.sock"
        server = serve(Solver(lattice), addr)
        try:
            client = SolverClient(addr)
            h = client.health()
            assert h["ok"] and h["types"] == lattice.T
            pods = [Pod(name=f"p{i}",
                        requests={"cpu": "500m", "memory": "1Gi"})
                    for i in range(6)]
            plan = client.solve(pods, [NodePool(name="default")])
            assert not plan.unschedulable
            placed = sum(len(n.pods) for n in plan.new_nodes)
            assert placed == 6
            assert plan.new_node_cost > 0
            # parity with an in-process solve
            local = Solver(lattice).solve(
                build_problem(pods, [NodePool(name="default")], lattice))
            assert plan.new_node_cost == pytest.approx(local.new_node_cost)
            client.close()
        finally:
            server.stop(grace=None)

    def test_sidecar_carries_existing_bins_and_constraints(self, lattice, tmp_path):
        from karpenter_provider_aws_tpu.parallel.sidecar import (
            SolverClient, serve,
        )
        addr = f"unix:{tmp_path}/solver2.sock"
        server = serve(Solver(lattice), addr)
        try:
            client = SolverClient(addr)
            existing = [ExistingBin(
                name="n0", node_pool="default", instance_type="m5.4xlarge",
                zone="us-west-2a", capacity_type="on-demand",
                used=np.zeros(R, np.float32))]
            pods = [rich_pod()]
            plan = client.solve(pods, [NodePool(name="default")],
                                existing=existing)
            assert not plan.unschedulable
            # the rich pod fits the idle existing node (affinity allows it)
            assert plan.existing_assignments.get("n0") == ["rich"] or \
                plan.new_nodes
            client.close()
        finally:
            server.stop(grace=None)


class TestNodePoolWireCompleteness:
    def test_kubelet_and_budget_windows_survive_the_wire(self):
        from karpenter_provider_aws_tpu.apis import NodePool, serde
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, KubeletSpec, NodePoolDisruption)
        p = NodePool(name="x", kubelet=KubeletSpec(max_pods=110),
                     annotations={"a": "b"},
                     disruption=NodePoolDisruption(budgets=[
                         DisruptionBudget(nodes="0", schedule="0 0 * * *",
                                          duration=3600.0)]))
        rt = serde.nodepool_from_dict(serde.nodepool_to_dict(p))
        assert rt.kubelet is not None and rt.kubelet.max_pods == 110
        assert rt.annotations == {"a": "b"}
        b = rt.disruption.budgets[0]
        assert (b.nodes, b.schedule, b.duration) == ("0", "0 0 * * *", 3600.0)
        plain = serde.nodepool_from_dict(serde.nodepool_to_dict(NodePool(name="y")))
        assert plain.kubelet is None


class TestHeadroomOverTheWire:
    def test_remote_solve_respects_pool_headroom(self, tmp_path):
        import numpy as np
        from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOp, Pod, Requirement
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.apis.resources import R, axis
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.parallel.sidecar import SolverClient, serve
        from karpenter_provider_aws_tpu.solver import Solver

        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "c5", "t3")])
        addr = f"unix:{tmp_path}/solver.sock"
        server = serve(Solver(lattice), addr)
        client = SolverClient(addr)
        try:
            pool = NodePool(name="default", requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
            pods = [Pod(name=f"p{i}", requests={"cpu": "2", "memory": "2Gi"})
                    for i in range(4)]
            rem = np.full((R,), np.inf, np.float32)
            rem[axis("cpu")] = 8000.0  # one 8-cpu node's worth remains
            plan = client.solve(pods, [pool],
                                pool_headroom={"default": rem})
            placed = sum(len(n.pods) for n in plan.new_nodes)
            for n in plan.new_nodes:
                ti = lattice.name_to_idx[n.instance_type]
                assert lattice.capacity[ti][axis("cpu")] <= 8000.0
            assert placed + len(plan.unschedulable) == 4
        finally:
            client.close()
            server.stop(grace=None)
