"""Sharded (multi-chip) solve path: pod-axis DP over the 8-device CPU mesh.

Covers what VERDICT round 1 flagged: the sharded path must be executed by
tests (sharded_pack itself), integrated (Solver.solve(mesh=...) produces a
full NodePlan), and cost-bounded (≤2% of the single-device solve on
realistic workloads, the SURVEY §7 envelope).
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator, Pod, Requirement
from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.parallel import sharded_pack, solver_mesh, split_counts
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.solver.problem import ExistingBin
from karpenter_provider_aws_tpu.solver.solve import decode_sharded_pack


@pytest.fixture(scope="module")
def lattice():
    specs = [s for s in build_catalog()
             if s.family in ("m5", "c5", "r5", "m6g", "c6g", "g5")]
    return build_lattice(specs)


@pytest.fixture(scope="module")
def mesh():
    return solver_mesh(8)


def _mixed_pods(n_each: int):
    pods = [Pod(name=f"s{i}", requests={"cpu": "500m", "memory": "1Gi"})
            for i in range(n_each)]
    pods += [Pod(name=f"m{i}", requests={"cpu": "2", "memory": "4Gi"})
             for i in range(n_each)]
    pods += [Pod(name=f"l{i}", requests={"cpu": "4", "memory": "8Gi"},
                 node_selector={wk.LABEL_INSTANCE_CATEGORY: "c"})
             for i in range(n_each // 2)]
    return pods


class TestShardedPack:
    """Direct kernel-level checks of parallel/sharded.py on the 8-way mesh."""

    def test_conservation_and_collectives(self, lattice, mesh):
        pods = _mixed_pods(400)
        pools = [NodePool(name="default")]
        problem = build_problem(pods, pools, lattice)
        solver = Solver(lattice)
        G, B = 16, 512
        gbuf = solver._fused_inputs(problem, G)
        count_pad = np.zeros((G,), np.int32)
        count_pad[: problem.G] = problem.count
        count_split = split_counts(count_pad, 8)
        sp = sharded_pack(mesh, solver._alloc, solver._avail, solver._price,
                          gbuf, None, 0, count_split,
                          B, G, lattice.T, lattice.Z, lattice.C, 1, 1)
        decs = decode_sharded_pack(sp, G, lattice.T, lattice.Z, lattice.C, 1)
        assign = np.stack([d.assign for d in decs])    # [D,G,B]
        assert assign.shape == (8, G, B)
        total = int(count_pad.sum())
        placed = int(assign.sum())
        # conservation: every pod is placed or left over, per shard
        assert placed + int(sp.total_leftover) == total
        assert int(sp.total_leftover) == 0
        # the psum'd collectives agree with a host-side reduction
        live = np.stack([d.open & ~d.fixed & (d.npods > 0) for d in decs])
        prices = np.stack([d.chosen_price for d in decs])
        host_cost = float(np.where(live, prices, 0.0).sum())
        assert float(sp.total_cost) == pytest.approx(host_cost, rel=1e-5)
        assert int(sp.total_nodes) == int(live.sum())

    def test_shard_slices_respect_count_split(self, lattice, mesh):
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(801)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver = Solver(lattice)
        count_pad = np.zeros((16,), np.int32)
        count_pad[: problem.G] = problem.count
        count_split = split_counts(count_pad, 8)
        # 801 = 8*100 + 1: shard 0 gets 101, the rest 100
        gi = int(np.argmax(count_pad))
        assert count_split[0, gi] == 101
        assert all(count_split[d, gi] == 100 for d in range(1, 8))
        sp = sharded_pack(mesh, solver._alloc, solver._avail, solver._price,
                          solver._fused_inputs(problem, 16), None, 0,
                          count_split, 512, 16,
                          lattice.T, lattice.Z, lattice.C, 1, 1)
        decs = decode_sharded_pack(sp, 16, lattice.T, lattice.Z, lattice.C, 1)
        per_shard = np.array([int(d.assign.sum()) for d in decs])
        np.testing.assert_array_equal(per_shard, count_split.sum(axis=1))


class TestShardedSolve:
    """Solver.solve(mesh=...) — the integrated multi-chip product path."""

    def test_full_plan_and_cost_parity(self, lattice, mesh):
        pods = _mixed_pods(800)
        pools = [NodePool(name="default")]
        problem = build_problem(pods, pools, lattice)
        solver = Solver(lattice)
        single = solver.solve(problem)
        sharded = solver.solve(problem, mesh=mesh)
        n = len(pods)
        for plan in (single, sharded):
            placed = sum(len(x.pods) for x in plan.new_nodes)
            placed += sum(len(v) for v in plan.existing_assignments.values())
            assert placed + len(plan.unschedulable) == n
            assert not plan.unschedulable
        # ≤2% cost envelope vs the single-device solve
        ratio = sharded.new_node_cost / single.new_node_cost
        assert ratio <= 1.02, (sharded.new_node_cost, single.new_node_cost)

    def test_existing_bins_only_fill_once(self, lattice, mesh):
        """Existing capacity lives on shard 0 only: pods across all shards
        must not overfill a real node D times."""
        ti = lattice.name_to_idx["m5.4xlarge"]  # 16 vCPU
        alloc = lattice.alloc[ti]
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros_like(alloc))]
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "1Gi"})
                for i in range(240)]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                existing=existing)
        solver = Solver(lattice)
        plan = solver.solve(problem, mesh=mesh)
        placed = sum(len(x.pods) for x in plan.new_nodes)
        placed += sum(len(v) for v in plan.existing_assignments.values())
        assert placed == 240 and not plan.unschedulable
        on_existing = plan.existing_assignments.get("node-a", [])
        # 16 vCPU node minus overhead holds at most ~15 one-cpu pods — a
        # D-times overfill would show ~8x that
        cpu_cap = float(alloc[0]) / 1000.0
        assert 0 < len(on_existing) <= int(cpu_cap)

    def test_single_bin_groups_stay_whole(self, lattice, mesh):
        """Hostname self-affinity groups must not straddle shards."""
        aff = [Pod(name=f"aff{i}", requests={"cpu": "500m", "memory": "512Mi"},
                   pod_affinity=[PodAffinityTerm(
                       topology_key=wk.LABEL_HOSTNAME, anti=False,
                       label_selector=(("app", "aff"),))],
                   labels={"app": "aff"}) for i in range(6)]
        filler = [Pod(name=f"f{i}", requests={"cpu": "1", "memory": "2Gi"})
                  for i in range(400)]
        problem = build_problem(aff + filler, [NodePool(name="default")], lattice)
        solver = Solver(lattice)
        plan = solver.solve(problem, mesh=mesh)
        assert not plan.unschedulable
        homes = [x for x in plan.new_nodes
                 if any(p.startswith("aff") for p in x.pods)]
        assert len(homes) == 1
        assert sum(1 for p in homes[0].pods if p.startswith("aff")) == 6

    def test_anti_affinity_spread_across_shards(self, lattice, mesh):
        """Hostname anti-affinity (1 replica per node) must hold on every
        shard's bins, not just shard 0."""
        anti = [Pod(name=f"one{i}", requests={"cpu": "500m", "memory": "512Mi"},
                    pod_affinity=[PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME, anti=True,
                        label_selector=(("app", "one"),))],
                    labels={"app": "one"}) for i in range(24)]
        problem = build_problem(anti, [NodePool(name="default")], lattice)
        solver = Solver(lattice)
        plan = solver.solve(problem, mesh=mesh)
        assert not plan.unschedulable
        for node in plan.new_nodes:
            assert sum(1 for p in node.pods if p.startswith("one")) <= 1

    def test_merge_consolidates_tail_bins(self, lattice, mesh):
        """Each shard opens its own fractional tail bin; the merge solve must
        consolidate them instead of shipping D part-empty nodes."""
        # one big instance type only: blockwise packing would ship 8
        # part-empty 16-vCPU nodes (2 pods each); the refinement merge must
        # repack them into the same ~2 nodes the single-device solve opens
        specs = [s for s in build_catalog() if s.name == "m5.4xlarge"]
        big = build_lattice(specs)
        pods = [Pod(name=f"t{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(16)]
        problem = build_problem(pods, [NodePool(name="default")], big)
        solver = Solver(big)
        single = solver.solve(problem)
        sharded = solver.solve(problem, mesh=mesh)
        assert not sharded.unschedulable
        assert sharded.new_node_cost <= single.new_node_cost * 1.02
        assert sharded.num_new_nodes == single.num_new_nodes
        # full-dissolve configs are BYTE-IDENTICAL to the single-device
        # plan, not just cost-equal (the PR 12 mesh-parity acceptance;
        # tests/test_mesh.py pins the same claim on the mesh-native path)
        import json
        from karpenter_provider_aws_tpu.apis import serde

        def canon(p):
            return json.dumps(serde.plan_semantic_dict(p), sort_keys=True)

        assert canon(sharded) == canon(single)

    def test_weighted_pools_respected(self, lattice, mesh):
        pools = [NodePool(name="default"),
                 NodePool(name="arm", weight=10, requirements=[
                     Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))])]
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(300)]
        problem = build_problem(pods, pools, lattice)
        solver = Solver(lattice)
        plan = solver.solve(problem, mesh=mesh)
        assert not plan.unschedulable
        # the arm pool outweighs default: every node should come from it
        assert all(x.node_pool == "arm" for x in plan.new_nodes)


class TestShardedScale:
    """The VERDICT scale gap: the sharded path exists for ~50k-pod waves
    but was only ever exercised at ≤2,400 pods. This drives it at ≥16k
    pods on the 8-way mesh — full-plan invariants AND the ≤2% cost
    envelope at the scale the path is FOR. slow-marked: one sample is a
    multi-second multi-chip solve."""

    @pytest.mark.slow
    def test_16k_pod_parity_and_conservation(self, lattice, mesh):
        pods = _mixed_pods(6600)          # 16,500 pods, 3 signatures
        n = len(pods)
        assert n >= 16_000
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver = Solver(lattice)
        single = solver.solve(problem)
        sharded = solver.solve(problem, mesh=mesh)
        for plan in (single, sharded):
            placed = sum(len(x.pods) for x in plan.new_nodes)
            placed += sum(len(v) for v in plan.existing_assignments.values())
            assert placed + len(plan.unschedulable) == n
            assert not plan.unschedulable
        # no pod lost or doubled across the shard decode/merge
        names = [p for x in sharded.new_nodes for p in x.pods]
        for v in sharded.existing_assignments.values():
            names += list(v)
        assert len(names) == len(set(names)) == n
        # the ≤2% envelope holds at scale, not just on toy batches
        ratio = sharded.new_node_cost / single.new_node_cost
        assert ratio <= 1.02, (sharded.new_node_cost, single.new_node_cost)

    @pytest.mark.slow
    def test_16k_selector_group_isolation_across_shards(self, lattice,
                                                        mesh):
        """At scale the category-selector pods must still land only on
        category-c types on EVERY shard's bins."""
        pods = _mixed_pods(6600)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = Solver(lattice).solve(problem, mesh=mesh)
        selector_pods = {p.name for p in pods if p.node_selector}
        for node in plan.new_nodes:
            if selector_pods & set(node.pods):
                spec = lattice.specs[lattice.name_to_idx[node.instance_type]]
                assert spec.family.startswith("c"), (
                    node.instance_type, selector_pods & set(node.pods))


class TestMergeFillThreshold:
    """Sweep MERGE_FILL_THRESHOLD (solver/solve.py): the dissolve knob must
    trade merge-solve work against tail-bin waste without ever violating the
    plan invariants, and the default must actually consolidate (VERDICT r2
    flagged 0.85 as an unexercised magic constant)."""

    @pytest.fixture()
    def big_lattice(self):
        specs = [s for s in build_catalog() if s.name == "m5.4xlarge"]
        return build_lattice(specs)

    def _tail_problem(self, big_lattice):
        pods = [Pod(name=f"t{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(16)]
        return pods, build_problem(pods, [NodePool(name="default")],
                                   big_lattice)

    @pytest.mark.parametrize("threshold", [0.0, 0.5, 0.85, 1.0])
    def test_invariants_hold_at_every_threshold(self, big_lattice, mesh,
                                                threshold, monkeypatch):
        pods, problem = self._tail_problem(big_lattice)
        solver = Solver(big_lattice)
        monkeypatch.setattr(Solver, "MERGE_FILL_THRESHOLD", threshold)
        plan = solver.solve(problem, mesh=mesh)
        # every pod placed exactly once, regardless of the knob
        placed = [p for node in plan.new_nodes for p in node.pods]
        assert sorted(placed) == sorted(p.name for p in pods)
        assert not plan.unschedulable
        assert plan.new_node_cost == pytest.approx(
            sum(n.price_per_hour for n in plan.new_nodes))

    def test_dissolve_beats_keep_all(self, big_lattice, mesh, monkeypatch):
        """threshold=0 keeps every part-empty shard bin (merge handles only
        spills); the default must consolidate to the single-device packing,
        and never cost more than the keep-all floor."""
        pods, problem = self._tail_problem(big_lattice)
        solver = Solver(big_lattice)
        single = solver.solve(problem)

        monkeypatch.setattr(Solver, "MERGE_FILL_THRESHOLD", 0.0)
        keep_all = solver.solve(problem, mesh=mesh)
        monkeypatch.setattr(Solver, "MERGE_FILL_THRESHOLD", 0.85)
        default = solver.solve(problem, mesh=mesh)

        # 16 one-vCPU pods over 8 shards with only a 16-vCPU shape: keep-all
        # ships one part-empty node per shard
        assert keep_all.num_new_nodes > single.num_new_nodes
        assert default.num_new_nodes == single.num_new_nodes
        assert default.new_node_cost <= keep_all.new_node_cost
        assert default.new_node_cost <= single.new_node_cost * 1.02

    def test_full_dissolve_matches_single_device(self, big_lattice, mesh,
                                                 monkeypatch):
        """threshold=1.0 dissolves every new bin into the refinement solve —
        the merge degenerates to a single-device re-pack and must match it."""
        pods, problem = self._tail_problem(big_lattice)
        solver = Solver(big_lattice)
        single = solver.solve(problem)
        monkeypatch.setattr(Solver, "MERGE_FILL_THRESHOLD", 1.0)
        plan = solver.solve(problem, mesh=mesh)
        assert plan.num_new_nodes == single.num_new_nodes
        assert plan.new_node_cost == pytest.approx(single.new_node_cost)
