"""Watch fan-out & write-batching contract (kube/apiserver.py).

The scaled write path's pins (docs/reference/watch.md):

- envelopes freeze at write time: reads, watch delivery, and history
  replay share ONE object per RV — zero per-watcher copies, and a
  handler mutating a delivered envelope raises instead of corrupting
  siblings (the isolation the old per-watcher deepcopy bought),
- per-watcher queues are bounded: overrun drops the watcher to the
  TooOldError/relist path, and the informer recovers by relisting,
- BOOKMARK events keep idle watchers' resume RVs fresh,
- the bulk verb coalesces many writes into one lock acquisition with
  per-object events and captured per-op errors,
- field indexes are real inverted maps (lookups touch only matches),
  and the PDB allowance math rides the namespace index with verdicts
  unchanged,
- per-kind locks + fan-out outside the store lock keep multi-writer/
  multi-watcher runs linearizable per kind.
"""

from __future__ import annotations

import copy
import threading
import time

import pytest

from karpenter_provider_aws_tpu.apis import serde
from karpenter_provider_aws_tpu.apis.objects import (
    Pod, PodDisruptionBudget,
)
from karpenter_provider_aws_tpu.kube.apiserver import (
    AlreadyExistsError, ConflictError, EvictionBlockedError, FakeAPIServer,
    FrozenDict, FrozenList, InvalidObjectError, NotFoundError, TooOldError,
    freeze,
)
from karpenter_provider_aws_tpu.kube.client import KubeClient
from karpenter_provider_aws_tpu.kube.informer import Informer
from karpenter_provider_aws_tpu.kube.writer import ApiWriter
from karpenter_provider_aws_tpu.state.cluster import ClusterState
from karpenter_provider_aws_tpu.utils.clock import FakeClock


def pod(name: str, node_name=None, namespace="default", labels=None) -> Pod:
    return Pod(name=name, namespace=namespace, labels=labels or {},
               requests={"cpu": "1", "memory": "1Gi"}, node_name=node_name)


def pod_spec(name: str, **kw) -> dict:
    return serde.pod_to_dict(pod(name, **kw))


class TestFrozenEnvelopes:
    """One canonical immutable copy per RV, shared everywhere."""

    def test_delivery_shares_one_event_object(self):
        s = FakeAPIServer()
        w1 = s.watch("pods")
        w2 = s.watch("pods")
        s.create("pods", pod_spec("a"))
        ev1 = w1.pop_pending()[0]
        ev2 = w2.pop_pending()[0]
        # the SAME WatchEvent and the SAME envelope — delivery copied
        # nothing, to either subscriber or the history ring
        assert ev1 is ev2
        assert ev1.object is s._history["pods"][-1].object
        assert s.fanout_envelope_copies == 0
        # a late subscriber's replay shares it too
        w3 = s.watch("pods", resource_version=0)
        assert w3.pop_pending()[0].object is ev1.object

    def test_reads_share_the_stored_envelope(self):
        s = FakeAPIServer()
        created = s.create("pods", pod_spec("a"))
        got = s.get("pods", "a")
        listed, _ = s.list("pods")
        assert created is got is listed[0]

    def test_envelopes_are_frozen_at_every_level(self):
        s = FakeAPIServer()
        obj = s.create("pods", pod_spec("a"))
        assert isinstance(obj, FrozenDict)
        with pytest.raises(TypeError):
            obj["extra"] = 1
        with pytest.raises(TypeError):
            obj["spec"]["nodeName"] = "hijack"
        with pytest.raises(TypeError):
            obj["metadata"]["finalizers"].append("x")
        with pytest.raises(TypeError):
            del obj["status"]
        with pytest.raises(TypeError):
            obj["spec"].update({"a": 1})
        assert isinstance(obj["metadata"]["finalizers"], FrozenList)

    def test_deepcopy_thaws_to_plain_mutable(self):
        s = FakeAPIServer()
        obj = s.create("pods", pod_spec("a"))
        mine = copy.deepcopy(obj)
        assert type(mine) is dict
        assert type(mine["metadata"]["finalizers"]) is list
        mine["spec"]["nodeName"] = "n0"   # no raise
        # the store is untouched by the private copy
        assert s.get("pods", "a")["spec"].get("nodeName") is None

    def test_frozen_survives_json_roundtrip(self):
        import json
        s = FakeAPIServer()
        obj = s.create("pods", pod_spec("a"))
        doc = json.loads(json.dumps(obj))
        assert doc["spec"]["name"] == "a"
        # freeze() itself round-trips nested shapes
        f = freeze({"a": [{"b": 1}], "c": (2, 3)})
        assert isinstance(f["a"], FrozenList)
        assert isinstance(f["a"][0], FrozenDict)
        assert json.dumps(f)

    def test_get_by_index_returns_frozen_shared(self):
        s = FakeAPIServer()
        s.add_index("pods", "nodeName", lambda spec: spec.get("nodeName"))
        s.create("pods", pod_spec("a", node_name="n0"))
        hits = s.get_by_index("pods", "nodeName", "n0")
        assert hits and hits[0] is s.get("pods", "a")


class TestBookmarks:
    def test_bookmark_after_every_n_deliveries(self):
        s = FakeAPIServer(bookmark_every=3)
        w = s.watch("pods")
        for i in range(3):
            s.create("pods", pod_spec(f"p{i}"))
        evs = w.pop_pending()
        assert [e.type for e in evs] == ["ADDED", "ADDED", "ADDED",
                                        "BOOKMARK"]
        # the bookmark carries the kind's current RV — a resume point
        assert evs[-1].resource_version == evs[-2].resource_version
        assert s.stats()["bookmarks"] == 1

    def test_delivered_rvs_are_monotonic(self):
        s = FakeAPIServer(bookmark_every=2)
        w = s.watch("pods")
        for i in range(7):
            s.create("pods", pod_spec(f"p{i}"))
        rvs = [e.resource_version for e in w.pop_pending()]
        assert rvs == sorted(rvs)

    def test_informer_applies_bookmark_without_handler_call(self):
        s = FakeAPIServer(bookmark_every=2)
        calls = []
        inf = Informer(s, "pods",
                       lambda t, n, o, old: calls.append((t, n)))
        inf.sync_once()   # initial list
        s.create("pods", pod_spec("a"))
        s.create("pods", pod_spec("b"))
        inf.sync_once()
        assert [t for t, _ in calls] == ["ADDED", "ADDED"]
        assert set(inf.store) == {"a", "b"}
        # the bookmark advanced the resume point to the kind high-water
        assert inf._rv == s.last_rv

    def test_zero_disables_bookmarks(self):
        s = FakeAPIServer(bookmark_every=0)
        w = s.watch("pods")
        for i in range(10):
            s.create("pods", pod_spec(f"p{i}"))
        assert all(e.type == "ADDED" for e in w.pop_pending())
        assert s.stats()["bookmarks"] == 0


class TestBoundedQueues:
    def test_overflow_drops_watcher_to_410(self):
        s = FakeAPIServer(watch_queue_bound=4)
        w = s.watch("pods")
        for i in range(6):
            s.create("pods", pod_spec(f"p{i}"))
        with pytest.raises(TooOldError):
            w.pop_pending()
        # and keeps raising: the watcher is dead until it relists
        with pytest.raises(TooOldError):
            w.get(timeout=0)
        assert s.stats()["watch_drops"] >= 5

    def test_overflow_never_convoys_the_writer(self):
        """The write path stays up while a dead-slow watcher overflows —
        writes succeed and OTHER watchers keep receiving."""
        s = FakeAPIServer(watch_queue_bound=4)
        slow = s.watch("pods")
        for i in range(20):
            s.create("pods", pod_spec(f"p{i}"))
        live = s.watch("pods", resource_version=0)   # replays history
        assert len(s._store["pods"]) == 20
        assert len(live.pop_pending()) == 20
        with pytest.raises(TooOldError):
            slow.pop_pending()

    def test_informer_relists_after_overflow(self):
        s = FakeAPIServer(watch_queue_bound=4)
        calls = []
        inf = Informer(s, "pods",
                       lambda t, n, o, old: calls.append((t, n)))
        inf.sync_once()
        for i in range(10):
            s.create("pods", pod_spec(f"p{i}"))
        s.delete("pods", "p0")
        # the watcher overran its bound; the pump recovers by RELISTING
        inf.sync_once()
        assert set(inf.store) == set(s._store["pods"])
        # the relist synthesized ADDs for the survivors (p0 came and
        # went entirely inside the blackout — it never surfaces)
        assert ("ADDED", "p1") in calls
        assert all(n != "p0" for _, n in calls)
        # and the informer is live again afterwards
        s.create("pods", pod_spec("late"))
        inf.sync_once()
        assert "late" in inf.store

    def test_threaded_informer_recovers_from_overflow(self):
        s = FakeAPIServer(watch_queue_bound=8)
        inf = Informer(s, "pods").start()
        try:
            deadline = time.monotonic() + 5.0
            while not inf.has_synced and time.monotonic() < deadline:
                time.sleep(0.01)
            for i in range(200):
                s.create("pods", pod_spec(f"p{i}"))
            while time.monotonic() < deadline:
                if set(inf.store) == set(s._store["pods"]):
                    break
                time.sleep(0.02)
            assert set(inf.store) == set(s._store["pods"])
        finally:
            inf.stop()


class TestBulkVerb:
    def test_bulk_coalesces_creates_with_per_object_events(self):
        s = FakeAPIServer()
        w = s.watch("pods")
        res = s.bulk([("create", "pods", pod_spec(f"p{i}"))
                      for i in range(5)])
        assert all(isinstance(r, dict) for r in res)
        rvs = [r["metadata"]["resourceVersion"] for r in res]
        assert rvs == sorted(rvs)            # one ordered RV range
        evs = w.pop_pending()
        assert [e.type for e in evs] == ["ADDED"] * 5
        assert s.bulk_calls == 1 and s.bulk_ops == 5

    def test_bulk_captures_per_op_errors(self):
        s = FakeAPIServer()
        s.create("pods", pod_spec("dup"))
        res = s.bulk([
            ("create", "pods", pod_spec("dup")),       # AlreadyExists
            ("create", "pods", pod_spec("ok")),
            ("bind", "missing", "n0"),                 # NotFound
            ("bind", "ok", "n0"),
        ])
        assert isinstance(res[0], AlreadyExistsError)
        assert isinstance(res[1], dict)
        assert isinstance(res[2], NotFoundError)
        assert res[3]["spec"]["nodeName"] == "n0"

    def test_bulk_runs_admission(self):
        s = FakeAPIServer()
        s.register_admission(
            "pods", validate=lambda spec: (["rejected by test"]
                                           if spec.get("labels", {}).get("bad")
                                           else []))
        res = s.bulk([("create", "pods", pod_spec("fine")),
                      ("create", "pods", pod_spec("bad", labels={"bad": "1"}))])
        assert isinstance(res[0], dict)
        assert isinstance(res[1], InvalidObjectError)
        assert "bad" not in s._store["pods"]

    def test_bulk_mixed_kinds_and_delete(self):
        s = FakeAPIServer()
        res = s.bulk([
            ("create", "pods", pod_spec("p0")),
            ("create", "nodes", {"name": "n0"}),
            ("bind", "p0", "n0"),
            ("delete", "pods", "p0"),
        ])
        assert not any(isinstance(r, Exception) for r in res)
        assert "p0" not in s._store["pods"]
        assert "n0" in s._store["nodes"]

    def test_client_bind_pods_verdicts(self):
        s = FakeAPIServer()
        c = KubeClient(s)
        c.create_pod(pod("a"))
        c.create_pod(pod("b", node_name="taken"))   # already bound
        oks = c.bind_pods([("a", "n0"), ("b", "n0"), ("ghost", "n0")])
        assert oks == [True, False, False]
        assert s.get("pods", "a")["spec"]["nodeName"] == "n0"

    def test_client_create_pods_bulk(self):
        s = FakeAPIServer()
        c = KubeClient(s)
        errs = c.create_pods([pod(f"p{i}") for i in range(4)])
        assert errs == [None] * 4
        errs = c.create_pods([pod("p0")])
        assert isinstance(errs[0], AlreadyExistsError)


class TestWriterBatching:
    def _writer(self):
        clock = FakeClock()
        s = FakeAPIServer(clock=clock)
        cluster = ClusterState(clock)
        return s, KubeClient(s), ApiWriter(KubeClient(s), cluster, clock)

    def test_apiwriter_bind_pods_is_one_bulk_call(self):
        s, c, w = self._writer()
        for i in range(6):
            c.create_pod(pod(f"p{i}"))
        before = s.bulk_calls
        oks = w.bind_pods([(f"p{i}", "n0") for i in range(6)])
        assert oks == [True] * 6
        assert s.bulk_calls == before + 1
        assert w.stats()["bind_pod"] == 6
        assert w.stats()["bulk_binds"] == 1

    def test_apiwriter_drain_verdicts_ride_bulk(self):
        clock = FakeClock()
        s = FakeAPIServer(clock=clock)
        cluster = ClusterState(clock)
        c = KubeClient(s)
        w = ApiWriter(c, cluster, clock)
        # two app pods behind a minAvailable=1 PDB, both on n0 — exactly
        # one eviction is allowed; the pre-index sequential verdicts
        for i in range(2):
            p = pod(f"app-{i}", node_name="n0", labels={"app": "web"})
            c.create_pod(p)
            cluster.add_pod(p)
        free = pod("free", node_name="n0")
        c.create_pod(free)
        cluster.add_pod(free)
        c.create_pdb(PodDisruptionBudget(
            name="web-pdb", label_selector={"app": "web"}, min_available=1))
        before = s.bulk_calls
        evicted, blocked = w.drain_node("n0")
        assert s.bulk_calls == before + 1
        names = {p.name for p in evicted}
        assert "free" in names                      # un-budgeted pod evicts
        assert len([p for p in evicted if p.name.startswith("app-")]) == 1
        assert len(blocked) == 1                    # the PDB held one back


class TestIndexes:
    def test_lookup_never_scans_the_store(self):
        s = FakeAPIServer()
        calls = []

        def key_fn(spec):
            calls.append(spec["name"])
            return spec.get("nodeName")

        s.add_index("pods", "nodeName", key_fn)
        for i in range(50):
            s.create("pods", pod_spec(f"p{i}",
                                      node_name="n0" if i < 3 else "n1"))
        calls.clear()
        hits = s.get_by_index("pods", "nodeName", "n0")
        # the inverted map answered — the key_fn saw NO object on read
        assert calls == []
        assert sorted(o["spec"]["name"] for o in hits) == ["p0", "p1", "p2"]

    def test_index_follows_updates_and_deletes(self):
        s = FakeAPIServer()
        s.add_index("pods", "nodeName", lambda spec: spec.get("nodeName"))
        s.create("pods", pod_spec("a", node_name="n0"))
        s.patch("pods", "a", {"nodeName": "n1"})
        assert s.get_by_index("pods", "nodeName", "n0") == []
        assert len(s.get_by_index("pods", "nodeName", "n1")) == 1
        s.delete("pods", "a")
        assert s.get_by_index("pods", "nodeName", "n1") == []

    def test_index_registered_late_backfills(self):
        s = FakeAPIServer()
        s.create("pods", pod_spec("a", node_name="n0"))
        s.add_index("pods", "nodeName", lambda spec: spec.get("nodeName"))
        assert len(s.get_by_index("pods", "nodeName", "n0")) == 1

    def test_namespace_index_feeds_pdb_allowance(self):
        s = FakeAPIServer()
        # same labels, different namespaces: the allowance for ns-a's
        # PDB must count ONLY ns-a pods (and via the ns index bucket)
        for ns in ("ns-a", "ns-b"):
            for i in range(3):
                s.create("pods", pod_spec(f"{ns}-{i}", node_name="n0",
                                          namespace=ns,
                                          labels={"app": "web"}))
        allowance = s._pdb_allowance({
            "labelSelector": {"app": "web"}, "namespace": "ns-a",
            "minAvailable": 1})
        assert allowance == 2   # 3 healthy in ns-a, minAvailable 1
        bucket = s._index_maps[("pods", "namespace")]["ns-a"]
        assert len(bucket) == 3


class TestLinearizability:
    """Multi-writer / multi-watcher race: per-kind order and convergence
    survive the lock decomposition + out-of-lock fan-out."""

    def test_multi_writer_multi_watcher_race(self):
        s = FakeAPIServer()
        n_writers, per_writer = 4, 60
        watchers = [s.watch("pods") for _ in range(3)]
        nodes_w = s.watch("nodes")
        errors = []

        def writer(wid: int):
            try:
                for i in range(per_writer):
                    s.create("pods", pod_spec(f"w{wid}-p{i}"))
                    if i % 3 == 0:
                        s.create("nodes", {"name": f"w{wid}-n{i}"})
                    if i % 5 == 0:
                        s.patch("pods", f"w{wid}-p{i}", {"priority": i})
            except Exception as e:   # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(n_writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(s._store["pods"]) == n_writers * per_writer
        # every watcher saw every pod event exactly once, in RV order
        expect_events = (n_writers * per_writer               # ADDED
                         + n_writers * ((per_writer + 4) // 5))  # MODIFIED
        for w in watchers:
            evs = [e for e in w.pop_pending() if e.type != "BOOKMARK"]
            rvs = [e.resource_version for e in evs]
            assert rvs == sorted(rvs)
            assert len(rvs) == len(set(rvs))
            assert len(evs) == expect_events
        node_evs = [e for e in nodes_w.pop_pending()
                    if e.type != "BOOKMARK"]
        assert len(node_evs) == len(s._store["nodes"])

    def test_watch_stream_replays_to_exact_store_state(self):
        """Lost-event regression (the SOAK_r08 agreement catch): under
        concurrent writers + interleaved flushers, applying a watcher's
        full event stream must reconstruct the server's exact final
        store — one lost DELETE leaves a phantom the mirror never heals
        from. (The original bug: the flusher drained the publish queue
        with list()+clear() under the publish mutex while writers append
        under the STORE lock — an append racing the gap was cleared
        undelivered.)"""
        s = FakeAPIServer(bookmark_every=0)
        w = s.watch("pods")
        n_threads, rounds = 8, 120
        errors = []

        def churn(tid: int):
            try:
                for i in range(rounds):
                    name = f"t{tid}-{i}"
                    s.create("pods", pod_spec(name))
                    if i % 2 == 0:
                        s.patch("pods", name, {"priority": i})
                    if i % 3 == 0:
                        s.delete("pods", name)
            except Exception as e:   # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=churn, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        replayed = {}
        for ev in w.pop_pending():
            if ev.type == "DELETED":
                replayed.pop(ev.object["metadata"]["name"], None)
            else:
                replayed[ev.object["metadata"]["name"]] = ev.object
        assert set(replayed) == set(s._store["pods"])
        # and the surviving objects are at their final revisions
        for name, obj in replayed.items():
            assert (obj["metadata"]["resourceVersion"]
                    == s._store["pods"][name]["metadata"]["resourceVersion"])

    def test_rv_monotonic_per_kind_across_concurrent_kinds(self):
        s = FakeAPIServer()
        done = []

        def churn(kind: str, count: int):
            for i in range(count):
                s.create(kind, {"name": f"{kind}-{i}"})
            done.append(kind)

        ts = [threading.Thread(target=churn, args=(k, 100))
              for k in ("nodes", "leases")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(done) == ["leases", "nodes"]
        for kind in ("nodes", "leases"):
            rvs = [o["metadata"]["resourceVersion"]
                   for o in s._store[kind].values()]
            assert len(set(rvs)) == len(rvs)
        # the global high-water covers both kinds' allocations
        assert s.last_rv >= 200


class TestStats:
    def test_stats_reports_depth_via_locked_accessor(self):
        s = FakeAPIServer()
        w = s.watch("pods")
        w2 = s.watch("pods")
        for i in range(4):
            s.create("pods", pod_spec(f"p{i}"))
        st = s.stats()
        assert st["watchers"] == 2
        assert st["watch_queue_depth"] == 8
        assert st["watch_max_depth"] == 4
        assert w.depth() == 4 and w2.depth() == 4
        assert st["fanout_envelope_copies"] == 0
        assert st["events_emitted"] == 8
        w.pop_pending()
        assert s.stats()["watch_queue_depth"] == 4

    def test_bulk_counters_surface(self):
        s = FakeAPIServer()
        s.bulk([("create", "pods", pod_spec("a")),
                ("create", "nodes", {"name": "n0"})])
        st = s.stats()
        assert st["bulk_calls"] == 1
        assert st["bulk_ops"] == 2

    def test_gc_re_enabled_after_every_verb(self):
        """The collector-deferral guard (a gc pause inside a store lock
        would convoy that kind's writers) must always restore automatic
        collection — including across a multi-chunk bulk."""
        import gc
        assert gc.isenabled()
        s = FakeAPIServer()
        s.bulk([("create", "pods", pod_spec(f"p{i}")) for i in range(300)])
        assert gc.isenabled()
        s.patch("pods", "p0", {"priority": 1})
        s.bind("p1", "n0")
        s.delete("pods", "p2")
        assert gc.isenabled()

    def test_bulk_chunks_preserve_order_and_flush_once(self):
        """A bulk bigger than the per-acquisition chunk still delivers
        every event, in RV order, through ONE flush epoch."""
        from karpenter_provider_aws_tpu.kube.apiserver import BULK_CHUNK
        s = FakeAPIServer(bookmark_every=0)
        w = s.watch("pods")
        n = BULK_CHUNK * 2 + 17
        res = s.bulk([("create", "pods", pod_spec(f"p{i}"))
                      for i in range(n)])
        assert all(isinstance(r, dict) for r in res)
        evs = w.pop_pending()
        assert len(evs) == n
        rvs = [e.resource_version for e in evs]
        assert rvs == sorted(rvs)
