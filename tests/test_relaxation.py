"""Preferred-rule relaxation + ScheduleAnyway semantics.

Behavioral spec: reference website concepts/scheduling.md:203-206
(preferredDuringScheduling treated as required, relaxed when the pod cannot
otherwise schedule) and :322-334 (whenUnsatisfiable: ScheduleAnyway is
advisory — skew must never leave a pod pending).
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, Pod, PreferredRequirement, Requirement,
    TopologySpreadConstraint, relax_pod, relaxation_depth,
)
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator as Op, Options
from karpenter_provider_aws_tpu.solver import Solver
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "m6g", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def pref(key, *values, weight=1):
    return PreferredRequirement(Requirement(key, ReqOp.IN, tuple(values)),
                                weight=weight)


class TestRelaxationPrimitives:
    def test_depth_counts_prefs_and_anyway_spreads(self):
        pod = Pod(name="p", preferred_affinity=[pref(wk.LABEL_ZONE, "us-west-2a")],
                  topology_spread=[
                      TopologySpreadConstraint(1, wk.LABEL_ZONE,
                                               when_unsatisfiable="ScheduleAnyway"),
                      TopologySpreadConstraint(1, wk.LABEL_HOSTNAME)])
        # 1 preference + 1 ScheduleAnyway; the DoNotSchedule spread is hard
        assert relaxation_depth(pod) == 2

    def test_relax_drops_lowest_weight_first(self):
        pod = Pod(name="p", preferred_affinity=[
            pref(wk.LABEL_INSTANCE_CATEGORY, "c", weight=10),
            pref(wk.LABEL_ZONE, "us-west-2a", weight=1)])
        r1 = relax_pod(pod, 1)
        assert [p.weight for p in r1.preferred_affinity] == [10]
        r2 = relax_pod(pod, 2)
        assert r2.preferred_affinity == []
        assert relax_pod(pod, 0) is pod

    def test_relax_keeps_hard_spreads(self):
        pod = Pod(name="p", topology_spread=[
            TopologySpreadConstraint(1, wk.LABEL_ZONE),
            TopologySpreadConstraint(1, wk.LABEL_ZONE,
                                     when_unsatisfiable="ScheduleAnyway")])
        r = relax_pod(pod, 1)
        assert len(r.topology_spread) == 1
        assert r.topology_spread[0].when_unsatisfiable == "DoNotSchedule"


class TestPreferredAffinity:
    def test_preference_honored_when_feasible(self, solver, lattice):
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    preferred_affinity=[pref(wk.LABEL_ZONE, "us-west-2b")])
                for i in range(8)]
        plan = solver.solve_relaxed(pods, [NodePool(name="default")])
        assert not plan.unschedulable
        assert all(n.zone == "us-west-2b" for n in plan.new_nodes)

    def test_schedules_only_after_relaxation(self, solver, lattice):
        """The pool forbids the preferred zone: strict round fails, the
        relaxed round schedules — the preference must never leave the pod
        pending (scheduling.md:203-206)."""
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.NOT_IN, ("us-west-2b",))])
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    preferred_affinity=[pref(wk.LABEL_ZONE, "us-west-2b")])
                for i in range(4)]
        plan = solver.solve_relaxed(pods, [pool])
        assert not plan.unschedulable
        assert all(n.zone != "us-west-2b" for n in plan.new_nodes)

    def test_lowest_weight_dropped_first(self, solver, lattice):
        """Two preferences, one impossible: the high-weight satisfiable one
        survives relaxation of the low-weight impossible one."""
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.NOT_IN, ("us-west-2b",))])
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    preferred_affinity=[
                        pref(wk.LABEL_INSTANCE_CATEGORY, "c", weight=50),
                        pref(wk.LABEL_ZONE, "us-west-2b", weight=1)])
                for i in range(4)]
        plan = solver.solve_relaxed(pods, [pool])
        assert not plan.unschedulable
        assert all(n.instance_type.startswith("c") for n in plan.new_nodes)
        assert all(n.zone != "us-west-2b" for n in plan.new_nodes)

    def test_required_rules_never_relaxed(self, solver, lattice):
        pods = [Pod(name="p0", requests={"cpu": "1", "memory": "2Gi"},
                    required_affinity=[
                        Requirement(wk.LABEL_INSTANCE_CATEGORY, ReqOp.IN, ("x",))],
                    preferred_affinity=[pref(wk.LABEL_ZONE, "us-west-2b")])]
        plan = solver.solve_relaxed(pods, [NodePool(name="default")])
        assert "p0" in plan.unschedulable


class TestScheduleAnyway:
    def test_anyway_skew_never_unschedulable(self, solver, lattice):
        """Pool limited to one zone; a 4-zone ScheduleAnyway spread must
        collapse into that zone instead of leaving pods pending."""
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, ("us-west-2a",))])
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    labels={"app": "web"},
                    topology_spread=[TopologySpreadConstraint(
                        1, wk.LABEL_ZONE, when_unsatisfiable="ScheduleAnyway",
                        label_selector=(("app", "web"),))])
                for i in range(8)]
        plan = solver.solve_relaxed(pods, [pool])
        assert not plan.unschedulable
        assert all(n.zone == "us-west-2a" for n in plan.new_nodes)

    def test_do_not_schedule_still_hard(self, solver, lattice):
        """Same shape with DoNotSchedule: pods assigned to out-of-pool zones
        stay pending — the hard spread is not silently weakened."""
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, ("us-west-2a",))])
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    labels={"app": "web"},
                    topology_spread=[TopologySpreadConstraint(
                        1, wk.LABEL_ZONE, label_selector=(("app", "web"),))])
                for i in range(8)]
        plan = solver.solve_relaxed(pods, [pool])
        assert plan.unschedulable, "DoNotSchedule skew must stay hard"

    def test_anyway_spread_honored_when_feasible(self, solver, lattice):
        """With all zones open, the advisory spread still spreads."""
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    labels={"app": "web"},
                    topology_spread=[TopologySpreadConstraint(
                        1, wk.LABEL_ZONE, when_unsatisfiable="ScheduleAnyway",
                        label_selector=(("app", "web"),))])
                for i in range(8)]
        plan = solver.solve_relaxed(pods, [NodePool(name="default")])
        assert not plan.unschedulable
        zones = {n.zone for n in plan.new_nodes}
        assert len(zones) >= 2, "advisory spread ignored despite feasibility"


class TestEndToEnd:
    def test_provisioner_relaxes_preferences(self, lattice):
        clock = FakeClock()
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.NOT_IN, ("us-west-2b",))])
        env = Op(options=Options(registration_delay=1.0), lattice=lattice,
                 cloud=FakeCloud(clock), clock=clock, node_pools=[pool])
        env.cluster.add_pod(Pod(
            name="soft", requests={"cpu": "1", "memory": "2Gi"},
            preferred_affinity=[pref(wk.LABEL_ZONE, "us-west-2b")]))
        env.settle()
        assert env.cluster.pods["soft"].node_name
        (claim,) = env.cluster.claims.values()
        assert claim.zone != "us-west-2b"
