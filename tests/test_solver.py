"""Device solver tests: kernel vs FFD-oracle parity, constraint handling.

Mirrors the reference's unit strategy (SURVEY.md §4: real scheduler
in-process over fakes) — the full build_problem → pack → decode path runs on
the 8-device virtual CPU backend with a reduced lattice for speed.
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator, Pod, Requirement, Taint, Toleration,
)
from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.resources import R
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import (
    ExistingBin, Solver, build_problem, ffd_oracle,
)

_FAMILIES = ("m5", "c5", "r5", "m6g", "c6g", "g5", "t3")


@pytest.fixture(scope="module")
def lattice():
    specs = [s for s in build_catalog() if s.family in _FAMILIES]
    return build_lattice(specs)


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def generic_pods(n, cpu="500m", mem="1Gi", prefix="pod", **kw):
    return [Pod(name=f"{prefix}-{i}", requests={"cpu": cpu, "memory": mem}, **kw) for i in range(n)]


def default_pool(**kw):
    return NodePool(name=kw.pop("name", "default"), **kw)


def assert_plan_valid(plan, problem):
    """Every new node's pods must fit its chosen type's allocatable."""
    lat = problem.lattice
    pod_req = {}
    for g in problem.groups:
        for name in g.pod_names:
            pod_req[name] = g.req
    for node in plan.new_nodes:
        ti = lat.name_to_idx[node.instance_type]
        total = np.zeros(R, np.float32)
        for p in node.pods:
            total += pod_req[p]
        assert (total <= lat.alloc[ti] + 1e-2).all(), (
            f"{node.instance_type} overpacked: {total} > {lat.alloc[ti]}")
        assert np.isfinite(node.price_per_hour)


class TestBasicPacking:
    def test_config1_100_generic_pods(self, solver, lattice):
        """BASELINE config 1: 100 generic pods, single NodePool."""
        pods = generic_pods(100)
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        oracle = ffd_oracle(problem)
        assert not plan.unschedulable
        placed = sum(len(n.pods) for n in plan.new_nodes)
        assert placed == 100
        assert_plan_valid(plan, problem)
        # cost parity: within 2% of the FFD oracle (BASELINE.md envelope)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6


    def test_single_pod(self, solver, lattice):
        problem = build_problem(generic_pods(1), [default_pool()], lattice)
        plan = solver.solve(problem)
        assert len(plan.new_nodes) == 1
        assert plan.new_nodes[0].pods == ["pod-0"]
        assert_plan_valid(plan, problem)

    def test_empty(self, solver, lattice):
        problem = build_problem([], [default_pool()], lattice)
        plan = solver.solve(problem)
        assert plan.new_nodes == [] and not plan.unschedulable

    def test_large_pod_gets_large_node(self, solver, lattice):
        pods = [Pod(name="big", requests={"cpu": "60", "memory": "200Gi"})]
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert len(plan.new_nodes) == 1
        assert_plan_valid(plan, problem)

    def test_cheapest_offering_chosen(self, solver, lattice):
        """A spot-allowed pod should land on the cheapest compatible offering."""
        problem = build_problem(generic_pods(1), [default_pool()], lattice)
        plan = solver.solve(problem)
        oracle = ffd_oracle(problem)
        assert plan.new_node_cost == pytest.approx(oracle.new_node_cost, rel=1e-5)


class TestBinBudget:
    def test_b_hint_decays_after_large_wave(self, lattice):
        """Regression (round-2 ADVICE): one huge wave must not pin every
        later small solve in the same G-bucket to the big bin-table size;
        the hint's influence is capped near the fresh estimate and tracks
        the size that actually worked."""
        s = Solver(lattice)
        # one-pod-per-bin via max_per_bin-driving anti-affinity would be
        # heavyweight; a big flat wave is enough to push B to a high bucket
        big = build_problem(generic_pods(3000, cpu="2", mem="4Gi"),
                            [default_pool()], lattice)
        s.solve(big)
        hint_after_big = s._b_hint[16]
        small = build_problem(generic_pods(4), [default_pool()], lattice)
        s.solve(small)
        fresh, needed = s._b_hint[16]
        assert needed <= 128, (hint_after_big, s._b_hint[16])

    def test_estimate_respects_type_mask(self, lattice):
        """Regression (round-2 ADVICE): a group restricted to small types
        must not have its bin estimate computed against the biggest type in
        the whole lattice (that underestimates B and forces a retry)."""
        s = Solver(lattice)
        pods = generic_pods(64, cpu="1", mem="2Gi",
                            node_selector={wk.LABEL_INSTANCE_TYPE: "t3.medium"})
        problem = build_problem(pods, [default_pool()], lattice)
        est = s._estimate_bins(problem)
        # t3.small holds ~1 one-cpu pod after overhead: the estimate must be
        # in the dozens, not the handful a 96-vCPU-based estimate gives
        assert est >= 32, est


class TestConstraints:
    def test_node_selector_family(self, solver, lattice):
        pods = generic_pods(10, node_selector={wk.LABEL_INSTANCE_FAMILY: "c5"})
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        for n in plan.new_nodes:
            assert n.instance_type.startswith("c5.")
        assert_plan_valid(plan, problem)

    def test_gpu_pods(self, solver, lattice):
        pods = [Pod(name=f"gpu-{i}", requests={"cpu": "2", "nvidia.com/gpu": 1}) for i in range(4)]
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        for n in plan.new_nodes:
            assert n.instance_type.startswith("g5."), n.instance_type
        assert_plan_valid(plan, problem)

    def test_capacity_type_on_demand_only(self, solver, lattice):
        pool = default_pool(requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN, ("on-demand",))])
        problem = build_problem(generic_pods(5), [pool], lattice)
        plan = solver.solve(problem)
        for n in plan.new_nodes:
            assert n.capacity_type == "on-demand"

    def test_zone_selector(self, solver, lattice):
        pods = generic_pods(5, node_selector={wk.LABEL_ZONE: "us-west-2b"})
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        for n in plan.new_nodes:
            assert n.zone == "us-west-2b"

    def test_taints_block_intolerant_pods(self, solver, lattice):
        pool = default_pool(taints=[Taint("dedicated", "gpu")])
        problem = build_problem(generic_pods(3), [pool], lattice)
        plan = solver.solve(problem)
        assert len(plan.unschedulable) == 3
        tol = [Toleration("dedicated", "Equal", "gpu")]
        problem = build_problem(generic_pods(3, tolerations=tol), [pool], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable

    def test_impossible_selector_unschedulable(self, solver, lattice):
        pods = generic_pods(2, node_selector={wk.LABEL_INSTANCE_FAMILY: "does-not-exist"})
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert len(plan.unschedulable) == 2

    def test_unknown_resource_isolated(self, solver, lattice):
        pods = generic_pods(3) + [Pod(name="weird", requests={"hugepages-2Mi": "1Gi"})]
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert set(plan.unschedulable) == {"weird"}
        assert sum(len(n.pods) for n in plan.new_nodes) == 3


class TestAntiAffinity:
    def test_hostname_anti_affinity_one_pod_per_node(self, solver, lattice):
        """The 500-node scale-suite pattern: every pod its own node."""
        pods = [
            Pod(name=f"aa-{i}", labels={"app": "dense"},
                requests={"cpu": "250m", "memory": "512Mi"},
                pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                              label_selector=(("app", "dense"),), anti=True)])
            for i in range(20)
        ]
        problem = build_problem(pods, [default_pool()], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert len(plan.new_nodes) == 20
        assert all(len(n.pods) == 1 for n in plan.new_nodes)


class TestExistingCapacity:
    def test_fills_existing_first(self, solver, lattice):
        lat = lattice
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        problem = build_problem(generic_pods(4), [default_pool()], lat, existing=existing)
        plan = solver.solve(problem)
        assert plan.new_nodes == []
        assert plan.existing_assignments == {"node-a": ["pod-0", "pod-1", "pod-2", "pod-3"]}

    def test_overflow_to_new_node(self, solver, lattice):
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.large",
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        # m5.large alloc ~1930m cpu -> 3 pods of 500m fit (with memory to spare)
        problem = build_problem(generic_pods(10), [default_pool()], lattice, existing=existing)
        plan = solver.solve(problem)
        on_existing = sum(len(v) for v in plan.existing_assignments.values())
        on_new = sum(len(n.pods) for n in plan.new_nodes)
        assert on_existing >= 1
        assert on_existing + on_new == 10
        assert_plan_valid(plan, problem)


class TestNodePools:
    def test_weight_order_preferred(self, solver, lattice):
        heavy = default_pool(name="preferred", weight=100, requirements=[
            Requirement(wk.LABEL_INSTANCE_FAMILY, Operator.IN, ("r5",))])
        light = default_pool(name="fallback", weight=1)
        problem = build_problem(generic_pods(3), [light, heavy], lattice)
        plan = solver.solve(problem)
        assert all(n.node_pool == "preferred" for n in plan.new_nodes)
        assert all(n.instance_type.startswith("r5.") for n in plan.new_nodes)

    def test_pool_requirements_respected(self, solver, lattice):
        pool = default_pool(requirements=[
            Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))])
        problem = build_problem(generic_pods(5), [pool], lattice)
        plan = solver.solve(problem)
        for n in plan.new_nodes:
            assert n.instance_type.split(".")[0] in ("m6g", "c6g")

    def test_custom_template_label_matching(self, solver, lattice):
        pool_ml = default_pool(name="ml", labels={"example.com/team": "ml"})
        pods = generic_pods(2, node_selector={"example.com/team": "ml"})
        problem = build_problem(pods, [default_pool(), pool_ml], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert all(n.node_pool == "ml" for n in plan.new_nodes)


class TestDaemonSets:
    def test_daemonset_overhead_reserved(self, solver, lattice):
        ds = [Pod(name="ds", requests={"cpu": "1500m", "memory": "1Gi"}, is_daemonset=True)]
        pods = generic_pods(1, cpu="1", mem="1Gi")
        problem = build_problem(pods, [default_pool()], lattice, daemonset_pods=ds)
        plan = solver.solve(problem)
        assert len(plan.new_nodes) == 1
        ti = lattice.name_to_idx[plan.new_nodes[0].instance_type]
        # node must hold pod + daemonset: 2500m cpu > m5.large's 1930m
        assert lattice.alloc[ti][0] >= 2500


class TestOracleParity:
    """Randomized cost-parity: the device pack must stay within the 2%
    envelope of sequential FFD (BASELINE.md), both directions checked."""

    @pytest.mark.parametrize("seed", range(5))
    def test_random_workloads(self, solver, lattice, seed):
        rng = np.random.default_rng(seed)
        pods = []
        n_shapes = rng.integers(2, 8)
        for s in range(n_shapes):
            cpu = int(rng.choice([100, 250, 500, 1000, 2000, 4000]))
            mem = int(rng.choice([128, 512, 1024, 2048, 8192]))
            count = int(rng.integers(1, 60))
            sel = {}
            if rng.random() < 0.3:
                sel[wk.LABEL_INSTANCE_CATEGORY] = str(rng.choice(["m", "c", "r"]))
            if rng.random() < 0.2:
                sel[wk.LABEL_CAPACITY_TYPE] = "on-demand"
            pods += [Pod(name=f"s{s}-{i}", requests={"cpu": f"{cpu}m", "memory": f"{mem}Mi"},
                         node_selector=sel) for i in range(count)]
        pools = [default_pool(),
                 default_pool(name="arm", weight=5, requirements=[
                     Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))])]
        problem = build_problem(pods, pools, lattice)
        plan = solver.solve(problem)
        oracle = ffd_oracle(problem)
        assert set(plan.unschedulable) == set(oracle.unschedulable)
        placed = sum(len(n.pods) for n in plan.new_nodes) + \
            sum(len(v) for v in plan.existing_assignments.values())
        assert placed + len(plan.unschedulable) == len(pods)
        assert_plan_valid(plan, problem)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6, (
            f"kernel ${plan.new_node_cost:.4f} vs oracle ${oracle.new_node_cost:.4f}")


class TestReviewRegressions:
    def test_alloc_override_respected(self, solver, lattice):
        """A real node reporting less allocatable than the lattice must not be overpacked."""
        small = lattice.alloc[lattice.name_to_idx["m5.4xlarge"]] * np.float32(0.25)
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros(R, np.float32), alloc_override=small)]
        problem = build_problem(generic_pods(30, cpu="1"), [default_pool()], lattice,
                                existing=existing)
        plan = solver.solve(problem)
        on_existing = sum(len(v) for v in plan.existing_assignments.values())
        # 25% of 15.4 cpu => ~3 one-cpu pods max, never the full 30
        assert 0 < on_existing <= 4
        assert_plan_valid(plan, problem)

    def test_fixed_bin_ignores_market_availability(self, solver, lattice):
        """A running node accepts pods even if its offering is no longer for sale."""
        import copy
        lat = copy.deepcopy(lattice)
        from karpenter_provider_aws_tpu.solver.solve import Solver as S
        ti = lat.name_to_idx["m5.4xlarge"]
        lat.available[ti] = False          # market dried up
        lat.price[ti] = np.inf
        s = S(lat)
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        problem = build_problem(generic_pods(3), [default_pool()], lat, existing=existing)
        plan = s.solve(problem)
        assert sum(len(v) for v in plan.existing_assignments.values()) == 3
        assert plan.new_nodes == []

    def test_undiscoverable_topology_key_surfaces_warning(self, solver, lattice):
        """Custom-key spreads are supported when a NodePool offers the key
        (tests/test_custom_labels.py); with no domain source anywhere the
        constraint surfaces a warning instead of silently dropping."""
        from karpenter_provider_aws_tpu.apis import TopologySpreadConstraint
        pods = [Pod(name="p", requests={"cpu": "1"}, topology_spread=[
            TopologySpreadConstraint(max_skew=1, topology_key="example.com/rack")])]
        plan = solver.solve(build_problem(pods, [default_pool()], lattice))
        assert any("no discoverable domains" in w for w in plan.warnings)


class TestLeanDecodeBuffer:
    def test_lean_layout_matches_full(self, solver, lattice):
        """The lean single-device result buffer (ops/binpack.py
        _encode_decode_set lean=True) decodes to exactly the fields the
        full layout carries, at ~2/3 the transfer size."""
        from karpenter_provider_aws_tpu.ops import binpack
        from karpenter_provider_aws_tpu.solver import solve as sm

        pods = generic_pods(40) + [
            Pod(name=f"c-{i}", requests={"cpu": "2", "memory": "4Gi"},
                node_selector={wk.LABEL_INSTANCE_CATEGORY: "c"})
            for i in range(10)]
        problem = build_problem(pods, [default_pool()], lattice)
        G = sm._bucket(problem.G, sm._G_BUCKETS)
        groups = solver._padded_groups(problem, G)
        pools = solver._pool_params(problem)
        init = solver._init_state(problem, 128)
        avail, price = solver._device_avail_price(problem)
        args = (solver._alloc, avail, price, groups, pools, init)
        full = np.asarray(binpack.pack_packed(*args))
        lean = np.asarray(binpack.pack_packed(*args, lean=True))
        df = sm._unpack_decode_set(full, G, lattice.T, lattice.Z, lattice.C, 1)
        dl = sm._unpack_decode_set(lean, G, lattice.T, lattice.Z, lattice.C, 1,
                                   lean=True)
        for f in ("assign", "leftover", "np_id", "chosen_t", "chosen_z",
                  "chosen_c", "chosen_price", "tmask_p", "zmask_p",
                  "cmask_p", "open", "fixed"):
            np.testing.assert_array_equal(getattr(df, f), getattr(dl, f), f)
        assert dl.next_open == df.next_open
        assert dl.cum is None and dl.alloc_cap is None and dl.pm is None
        assert lean.nbytes < 0.75 * full.nbytes


class TestNativeReferee:
    """Parity between the native C++ FFD referee and the Python oracle."""

    def test_native_matches_python_oracle(self, solver, lattice):
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            import pytest as _pytest
            _pytest.skip("no C++ toolchain")
        pods = generic_pods(120)
        pods += [Pod(name=f"c{i}", requests={"cpu": "2", "memory": "2Gi"},
                     node_selector={wk.LABEL_INSTANCE_CATEGORY: "c"}) for i in range(30)]
        pods += [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                 for i in range(5)]
        problem = build_problem(
            pods, [default_pool(),
                   NodePool(name="od", weight=3, requirements=[
                       Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN, ("on-demand",))])],
            lattice)
        py = ffd_oracle(problem)
        nat = native_ffd_pack(problem)
        assert nat is not None
        assert nat.num_new_nodes == py.num_new_nodes
        assert abs(nat.new_node_cost - py.new_node_cost) < 1e-2
        assert nat.leftover == len(py.unschedulable) - len(problem.unschedulable)

    def test_native_respects_per_bin_cap(self, solver, lattice):
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            import pytest as _pytest
            _pytest.skip("no C++ toolchain")
        from karpenter_provider_aws_tpu.apis.objects import TopologySpreadConstraint
        pods = [Pod(name=f"p{i}", labels={"app": "a"},
                    requests={"cpu": "250m", "memory": "256Mi"},
                    topology_spread=[TopologySpreadConstraint(
                        max_skew=2, topology_key=wk.LABEL_HOSTNAME,
                        label_selector=(("app", "a"),))]) for i in range(10)]
        problem = build_problem(pods, [default_pool()], lattice)
        nat = native_ffd_pack(problem)
        assert nat is not None and nat.num_new_nodes >= 5  # <=2 pods per node

    def test_native_repack_matches_python_oracle(self, solver, lattice):
        """Existing bins + per-pool allocatable ceilings are in native
        scope: the native referee must place pods on fixed bins exactly
        like the Python oracle (the cfg4 repack referee path)."""
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            import pytest as _pytest
            _pytest.skip("no C++ toolchain")
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        from karpenter_provider_aws_tpu.solver import ExistingBin, ffd_oracle
        existing = [ExistingBin(name=f"n{i}", node_pool="default",
                                instance_type="m5.2xlarge", zone="us-west-2a",
                                capacity_type="on-demand",
                                used=np.zeros(R, np.float32))
                    for i in range(4)]
        pool = default_pool()
        pool.kubelet = KubeletSpec(max_pods=4)
        pods = generic_pods(30)
        problem = build_problem(pods, [pool], lattice, existing=existing)
        native = native_ffd_pack(problem)
        assert native is not None
        oracle = ffd_oracle(problem)
        assert native.leftover == 0
        assert native.num_new_nodes == oracle.num_new_nodes
        assert native.new_node_cost == pytest.approx(oracle.new_node_cost,
                                                     rel=1e-5)
        # per-existing-bin placements agree with the Python referee
        want = np.zeros(4, np.int64)
        for b in oracle.bins:
            if b.is_existing:
                want[b.existing_idx] = len(b.pods)
        assert list(native.e_npods) == list(want)

    def test_native_declines_out_of_scope_problems(self, solver, lattice):
        """Bound-pod affinity seeding on existing bins stays Python-only."""
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            import pytest as _pytest
            _pytest.skip("no C++ toolchain")
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        from karpenter_provider_aws_tpu.solver import ExistingBin
        from karpenter_provider_aws_tpu.solver.topology import BoundPod
        existing = [ExistingBin(name="n", node_pool="default",
                                instance_type="m5.large", zone="us-west-2a",
                                capacity_type="on-demand",
                                used=np.zeros(R, np.float32))]
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME, anti=True,
                                label_selector=(("app", "z"),))]
        bound = [BoundPod(pod=Pod(name="resident", labels={"app": "z"},
                                  pod_affinity=list(anti)),
                          node_name="n", zone="us-west-2a")]
        pods = [Pod(name="p0", labels={"app": "z"},
                    requests={"cpu": "250m", "memory": "256Mi"},
                    pod_affinity=list(anti))]
        problem = build_problem(pods, [default_pool()], lattice,
                                existing=existing, bound_pods=bound)
        native = native_ffd_pack(problem)
        assert native is not None
        # the resident owner repels p0 off the existing node, exactly like
        # the Python referee
        from karpenter_provider_aws_tpu.solver import ffd_oracle
        oracle = ffd_oracle(problem)
        assert int(native.e_npods[0]) == 0
        assert native.num_new_nodes == oracle.num_new_nodes == 1
        assert native.new_node_cost == pytest.approx(oracle.new_node_cost,
                                                     rel=1e-5)

    def test_native_shared_spread_class_parity(self, solver, lattice):
        """Two groups sharing one spread selector: the skew budget is
        shared cross-group via the pm class counts — native must agree
        with the Python referee."""
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            import pytest as _pytest
            _pytest.skip("no C++ toolchain")
        from karpenter_provider_aws_tpu.apis.objects import TopologySpreadConstraint
        spread = [TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_HOSTNAME,
                                           label_selector=(("app", "a"),))]
        pods = [Pod(name=f"x{i}", labels={"app": "a"},
                    requests={"cpu": "250m", "memory": "256Mi"},
                    topology_spread=list(spread)) for i in range(4)]
        pods += [Pod(name=f"y{i}", labels={"app": "a"},
                     requests={"cpu": "500m", "memory": "512Mi"},
                     topology_spread=list(spread)) for i in range(4)]
        problem = build_problem(pods, [default_pool()], lattice)
        from karpenter_provider_aws_tpu.solver import ffd_oracle
        native = native_ffd_pack(problem)
        oracle = ffd_oracle(problem)
        assert native is not None
        assert native.leftover == 0 and not oracle.unschedulable
        assert native.num_new_nodes == sum(
            1 for b in oracle.bins if not b.is_existing and b.pods) == 8
        assert native.new_node_cost == pytest.approx(oracle.new_node_cost,
                                                     rel=1e-5)


class TestProbeBatch:
    """Batched what-if probes (ops/binpack.pack_probe_fused via Solver.probe_batch):
    one device call must agree with the exact per-problem solves on
    feasibility, new-node count, and cost (SURVEY §2.2 consolidation
    what-ifs; reference designs/consolidation.md criterion)."""

    def test_probe_agrees_with_exact_solve(self, solver, lattice):
        pool = default_pool()
        problems = [
            build_problem(generic_pods(4), [pool], lattice),
            build_problem(generic_pods(12, cpu="2", mem="4Gi", prefix="big"),
                          [pool], lattice),
            # infeasible: no type satisfies a 10k-cpu pod
            build_problem([Pod(name="huge", requests={"cpu": "10000"})],
                          [pool], lattice),
        ]
        probes = solver.probe_batch(problems)
        for pr, problem in zip(probes, problems):
            plan = solver.solve(problem)
            exact_feasible = not plan.unschedulable
            assert pr.feasible == exact_feasible
            if exact_feasible:
                assert pr.n_new == len(plan.new_nodes)
                assert pr.new_cost == pytest.approx(plan.new_node_cost, rel=1e-5)

    def test_probe_with_existing_bins(self, solver, lattice):
        """A probe problem whose pods fit entirely on existing capacity
        opens zero new bins."""
        existing = [ExistingBin(name="n0", node_pool="default",
                                instance_type="m5.4xlarge", zone="us-west-2a",
                                capacity_type="on-demand",
                                used=np.zeros(R, np.float32))]
        problem = build_problem(generic_pods(4), [default_pool()], lattice,
                                existing=existing)
        (pr,) = solver.probe_batch([problem])
        assert pr.feasible and pr.n_new == 0 and pr.new_cost == 0.0

    def test_probe_reports_single_bin_capacity_type_and_flex(self, solver, lattice):
        """n_new == 1 probes expose the new bin's capacity type and type
        flexibility — the spot→spot ≥15-type guard inputs (disruption.md:129)."""
        pool = default_pool(requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN, ("spot",))])
        problem = build_problem(generic_pods(2), [pool], lattice)
        (pr,) = solver.probe_batch([problem])
        assert pr.feasible and pr.n_new == 1
        assert pr.new_cap_type == "spot"
        assert pr.flex > 0


class TestKubeletCapParity:
    def test_oracle_respects_pool_max_pods(self, lattice):
        """The FFD oracle applies Problem.np_alloc_cap exactly like the
        kernel, so cost parity is meaningful for maxPods pools; the
        native referee declines such problems."""
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        from karpenter_provider_aws_tpu.native import native_ffd_pack
        pool = NodePool(name="default", kubelet=KubeletSpec(max_pods=2),
                        requirements=[Requirement(wk.LABEL_CAPACITY_TYPE,
                                                  Operator.IN, ("on-demand",))])
        pods = generic_pods(6, cpu="100m", mem="128Mi")
        problem = build_problem(pods, [pool], lattice)
        solver = Solver(lattice)
        plan = solver.solve(problem)
        oracle = ffd_oracle(problem)
        assert not plan.unschedulable and not oracle.unschedulable
        # both respect the 2-pod cap: >= 3 nodes each
        assert len(plan.new_nodes) >= 3
        assert sum(1 for b in oracle.bins if not b.is_existing and b.pods) >= 3
        assert all(len(n.pods) <= 2 for n in plan.new_nodes)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6
        # per-pool allocatable ceilings are in native scope: same packing
        native = native_ffd_pack(problem)
        assert native is not None
        assert native.num_new_nodes == sum(
            1 for b in oracle.bins if not b.is_existing and b.pods)
        assert native.new_node_cost == pytest.approx(oracle.new_node_cost,
                                                     rel=1e-5)


class TestStartupTaints:
    def test_pods_need_not_tolerate_startup_taints(self, solver, lattice):
        """nodepools.md:484 (the Cilium pattern): startupTaints are
        temporary; pods schedule onto the pool WITHOUT tolerating them,
        while ordinary pool taints still require toleration."""
        pool = default_pool(
            startup_taints=[Taint("node.cilium.io/agent-not-ready", "true")])
        plan = solver.solve(build_problem(generic_pods(3), [pool], lattice))
        assert not plan.unschedulable
        # a REGULAR taint still blocks intolerant pods
        pool2 = default_pool(
            taints=[Taint("dedicated", "x")],
            startup_taints=[Taint("node.cilium.io/agent-not-ready", "true")])
        plan2 = solver.solve(build_problem(generic_pods(3), [pool2], lattice))
        assert len(plan2.unschedulable) == 3

    def test_daemonset_overhead_counts_despite_startup_taints(self, solver, lattice):
        """problem.py daemonset filter: a daemonset that does NOT tolerate
        the pool's startupTaints still runs once they clear, so its
        overhead must still size the pool's nodes."""
        pool = default_pool(
            startup_taints=[Taint("node.cilium.io/agent-not-ready", "true")])
        ds = Pod(name="logging-agent", is_daemonset=True,
                 requests={"cpu": "1", "memory": "1Gi"})
        problem = build_problem(generic_pods(1), [pool], lattice,
                                daemonset_pods=[ds])
        (pi,) = range(problem.NP)
        assert problem.ds_overhead[pi][0] >= 1000.0  # the agent's 1 cpu


class TestWarmup:
    def test_warmup_compiles_and_solve_reuses(self, lattice):
        """warmup() precompiles the warm bucket set; a subsequent real solve
        of a matching shape must hit the jit cache (no new trace)."""
        from karpenter_provider_aws_tpu.ops import binpack
        solver = Solver(lattice)
        solver.warmup(node_pools_count=1, g_buckets=(16,), b_buckets=(32,))
        sizes_after_warm = binpack.pack_packed_efused._cache_size()
        assert sizes_after_warm >= 2  # with + without existing-bin buffer
        pods = [Pod(name=f"w{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(10)]
        plan = solver.solve(build_problem(pods, [NodePool(name="default")],
                                          lattice))
        assert not plan.unschedulable
        assert binpack.pack_packed_efused._cache_size() == sizes_after_warm

    def test_background_warmup_joins(self, lattice):
        solver = Solver(lattice)
        t = solver.warmup(node_pools_count=1, g_buckets=(16,),
                          b_buckets=(32,), background=True)
        t.join(timeout=120)
        assert not t.is_alive()


class TestNativeOracleFuzzParity:
    """Randomized metamorphic parity: the C++ referee must match the
    Python oracle pod-for-pod on random problems drawn from the full
    in-scope feature surface (affinity classes, spread caps, single-bin,
    existing bins with bound-pod seeds, pool ceilings, taints)."""

    @pytest.mark.parametrize("seed", range(8))
    def test_random_problem_parity(self, lattice, seed):
        from karpenter_provider_aws_tpu.native import native_available, native_ffd_pack
        if not native_available():
            pytest.skip("no C++ toolchain")
        from karpenter_provider_aws_tpu.apis.objects import (
            KubeletSpec, PodAffinityTerm, TopologySpreadConstraint)
        from karpenter_provider_aws_tpu.solver import ExistingBin, ffd_oracle
        from karpenter_provider_aws_tpu.solver.topology import BoundPod

        rng = np.random.default_rng(seed)
        pools = [default_pool()]
        if rng.random() < 0.5:
            pools[0].kubelet = KubeletSpec(max_pods=int(rng.integers(3, 8)))
        pods = []
        napps = int(rng.integers(1, 4))
        for i in range(int(rng.integers(5, 40))):
            app = f"a{int(rng.integers(napps))}"
            kw = {}
            r = rng.random()
            if r < 0.2:
                kw["pod_affinity"] = [PodAffinityTerm(
                    topology_key=wk.LABEL_HOSTNAME, anti=True,
                    label_selector=(("app", app),))]
            elif r < 0.4:
                kw["topology_spread"] = [TopologySpreadConstraint(
                    max_skew=int(rng.integers(1, 3)),
                    topology_key=wk.LABEL_HOSTNAME,
                    label_selector=(("app", app),))]
            elif r < 0.5:
                # positive self-affinity -> single-bin co-location homing
                kw["pod_affinity"] = [PodAffinityTerm(
                    topology_key=wk.LABEL_HOSTNAME,
                    label_selector=(("app", app),))]
            elif r < 0.6:
                # affinity to another class -> presence need / self-seed
                other = f"a{int(rng.integers(napps))}"
                kw["pod_affinity"] = [PodAffinityTerm(
                    topology_key=wk.LABEL_HOSTNAME,
                    label_selector=(("app", other),))]
            pods.append(Pod(
                name=f"p{i}", labels={"app": app},
                requests={"cpu": f"{int(rng.choice([250, 500, 1000]))}m",
                          "memory": f"{int(rng.choice([512, 1024]))}Mi"},
                **kw))
        existing, bound = [], []
        for e in range(int(rng.integers(0, 4))):
            existing.append(ExistingBin(
                name=f"n{e}", node_pool="default",
                instance_type="m5.2xlarge", zone="us-west-2a",
                capacity_type="on-demand", used=np.zeros(R, np.float32)))
            if rng.random() < 0.5:
                app = f"a{int(rng.integers(napps))}"
                bound.append(BoundPod(
                    pod=Pod(name=f"r{e}", labels={"app": app},
                            pod_affinity=[PodAffinityTerm(
                                topology_key=wk.LABEL_HOSTNAME, anti=True,
                                label_selector=(("app", app),))]),
                    node_name=f"n{e}", zone="us-west-2a"))
        problem = build_problem(pods, pools, lattice, existing=existing,
                                bound_pods=bound)
        native = native_ffd_pack(problem)
        assert native is not None, "all generated features are native scope"
        oracle = ffd_oracle(problem)
        o_new = sum(1 for b in oracle.bins if not b.is_existing and b.pods)
        o_left = len(oracle.unschedulable) - len(problem.unschedulable)
        assert native.num_new_nodes == o_new
        assert native.leftover == o_left
        assert native.new_node_cost == pytest.approx(oracle.new_node_cost,
                                                     rel=1e-5, abs=1e-7)
        if problem.E:
            want = np.zeros(problem.E, np.int64)
            for b in oracle.bins:
                if b.is_existing:
                    want[b.existing_idx] = len(b.pods)
            assert list(native.e_npods) == list(want)


class TestSolverFuzzEnvelope:
    """Randomized metamorphic check of the DEVICE kernel itself: on random
    problems from the full feature surface, the grouped-FFD pack must
    place every placeable pod, produce a valid plan (capacity, masks), and
    stay inside the ≤2% cost envelope vs the sequential FFD oracle
    (SURVEY §7 hard part a: blockwise greedy must not lose pack quality)."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_problem_envelope(self, solver, lattice, seed):
        from karpenter_provider_aws_tpu.apis.objects import (
            KubeletSpec, PodAffinityTerm, TopologySpreadConstraint, Toleration,
            Taint)
        from karpenter_provider_aws_tpu.solver import ExistingBin, ffd_oracle

        rng = np.random.default_rng(1000 + seed)
        pools = [default_pool()]
        if rng.random() < 0.4:
            pools.append(NodePool(
                name="tainted", weight=int(rng.integers(0, 20)),
                taints=[Taint(key="team", value="x")]))
        pods = []
        for i in range(int(rng.integers(10, 60))):
            app = f"a{int(rng.integers(3))}"
            kw = {}
            r = rng.random()
            if r < 0.15:
                kw["pod_affinity"] = [PodAffinityTerm(
                    topology_key=wk.LABEL_HOSTNAME, anti=True,
                    label_selector=(("app", app),))]
            elif r < 0.3:
                kw["topology_spread"] = [TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.LABEL_ZONE,
                    label_selector=(("app", app),))]
            elif r < 0.4:
                kw["node_selector"] = {
                    wk.LABEL_INSTANCE_CATEGORY: str(rng.choice(["m", "c"]))}
            elif r < 0.45 and len(pools) > 1:
                kw["node_selector"] = {}
                kw["tolerations"] = [Toleration(key="team", value="x")]
            pods.append(Pod(
                name=f"p{i}", labels={"app": app},
                requests={"cpu": f"{int(rng.choice([250, 500, 1000, 2000]))}m",
                          "memory": f"{int(rng.choice([512, 1024, 4096]))}Mi"},
                **kw))
        existing = [ExistingBin(
            name=f"n{e}", node_pool="default", instance_type="m5.2xlarge",
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros(R, np.float32))
            for e in range(int(rng.integers(0, 3)))]
        problem = build_problem(pods, pools, lattice, existing=existing)
        plan = solver.solve(problem)
        # validity: every pod placed exactly once, nodes not overpacked
        placed = sorted(p for n in plan.new_nodes for p in n.pods)
        placed += sorted(p for v in plan.existing_assignments.values() for p in v)
        assert sorted(placed + list(plan.unschedulable)) == \
            sorted(p.name for p in pods)
        assert_plan_valid(plan, problem)
        # envelope: within 2% of the sequential oracle on total new cost,
        # and never strands a pod the oracle can place
        oracle = ffd_oracle(problem)
        assert len(plan.unschedulable) <= len(oracle.unschedulable)
        if oracle.new_node_cost > 0:
            assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6


class TestOSScheduling:
    """kubernetes.io/os is the POOL's property (its AMI family's OS), not
    the instance type's — any EC2 type runs either OS. A windows-selecting
    pod schedules only on a pool whose requirements say windows; pools
    without an os requirement default to linux (reference labels.go
    registers the os well-known label; the AMI family determines it)."""

    def test_os_routes_to_matching_pool(self, solver, lattice):
        win = NodePool(name="win", requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows",))])
        lin = default_pool()
        wpod = Pod(name="w0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "windows"})
        lpod = Pod(name="l0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "linux"})
        plan = solver.solve(build_problem([wpod, lpod], [win, lin], lattice))
        assert not plan.unschedulable
        by_pool = {n.node_pool: n.pods for n in plan.new_nodes}
        assert by_pool["win"] == ["w0"]
        assert by_pool["default"] == ["l0"]

    def test_windows_pod_never_lands_on_default_pool(self, solver, lattice):
        wpod = Pod(name="w0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "windows"})
        plan = solver.solve(build_problem([wpod], [default_pool()], lattice))
        assert "w0" in plan.unschedulable

    def test_unselective_pod_lands_anywhere(self, solver, lattice):
        pod = Pod(name="p0", requests={"cpu": "1", "memory": "2Gi"})
        win = NodePool(name="win", weight=10, requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows",))])
        plan = solver.solve(build_problem([pod], [win, default_pool()],
                                          lattice))
        assert not plan.unschedulable  # os-agnostic pods run on either

    def test_multi_valued_os_pool_pins_one_os(self, solver, lattice):
        """A (rejected-by-admission but defensively handled) multi-valued
        os requirement resolves to ONE concrete OS, consistently between
        scheduling and the launched node's label."""
        from karpenter_provider_aws_tpu.apis.objects import pool_os
        pool = NodePool(name="both", requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows", "linux"))])
        assert pool_os(pool) == "linux"  # deterministic: first sorted
        wpod = Pod(name="w0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "windows"})
        plan = solver.solve(build_problem([wpod], [pool], lattice))
        # the pool's nodes ARE linux; the windows pod must not land there
        assert "w0" in plan.unschedulable

    def test_windows_build_label_selectable(self, solver, lattice):
        """Pods may select the well-known windows-build label: every node
        of a windows pool carries it (implied template label)."""
        from karpenter_provider_aws_tpu.apis.objects import WINDOWS_BUILD
        win = NodePool(name="win", requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows",))])
        pod = Pod(name="b0", requests={"cpu": "1", "memory": "2Gi"},
                  node_selector={wk.LABEL_OS: "windows",
                                 wk.LABEL_WINDOWS_BUILD: WINDOWS_BUILD})
        plan = solver.solve(build_problem([pod], [win, default_pool()],
                                          lattice))
        assert not plan.unschedulable
        assert plan.new_nodes[0].node_pool == "win"

    def test_windows_group_avoids_unknown_pool_bins(self, solver, lattice):
        """Existing bins whose pool is unknown are treated as linux: a
        windows-selecting group must not join them."""
        from karpenter_provider_aws_tpu.solver import ExistingBin
        existing = [ExistingBin(
            name="orphan", node_pool="deleted-pool",
            instance_type="m5.4xlarge", zone="us-west-2a",
            capacity_type="on-demand", used=np.zeros(R, np.float32))]
        win = NodePool(name="win", requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows",))])
        wpod = Pod(name="w0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "windows"})
        plan = solver.solve(build_problem([wpod], [win], lattice,
                                          existing=existing))
        assert not plan.unschedulable
        assert not plan.existing_assignments  # NOT on the orphaned bin
        assert plan.new_nodes and plan.new_nodes[0].node_pool == "win"

    def test_pool_os_from_template_label(self, solver, lattice):
        """A pool declaring windows via its template LABEL (not a
        requirement) resolves identically — scheduling_requirements folds
        labels in, so label and requirement forms agree."""
        from karpenter_provider_aws_tpu.apis.objects import pool_os
        pool = NodePool(name="win-lab", labels={wk.LABEL_OS: "windows"})
        assert pool_os(pool) == "windows"
        wpod = Pod(name="w0", requests={"cpu": "1", "memory": "2Gi"},
                   node_selector={wk.LABEL_OS: "windows"})
        plan = solver.solve(build_problem([wpod], [pool], lattice))
        assert not plan.unschedulable
        assert plan.new_nodes[0].node_pool == "win-lab"

    def test_windows_build_spread_matches_windows_pool(self, solver, lattice):
        """A DoNotSchedule topology spread over windows-build must resolve
        a windows pool as a domain host through its EFFECTIVE (build-
        stamped) labels, exactly like plain selection on the same label
        (advisor r3 #3)."""
        from karpenter_provider_aws_tpu.apis.objects import (
            TopologySpreadConstraint, WINDOWS_BUILD)
        win = NodePool(name="win", requirements=[
            Requirement(wk.LABEL_OS, Operator.IN, ("windows",))])
        pods = [Pod(name=f"w{i}", labels={"app": "iis"},
                    requests={"cpu": "1", "memory": "2Gi"},
                    node_selector={wk.LABEL_OS: "windows"},
                    topology_spread=[TopologySpreadConstraint(
                        max_skew=1, topology_key=wk.LABEL_WINDOWS_BUILD,
                        label_selector=(("app", "iis"),))])
                for i in range(2)]
        plan = solver.solve(build_problem(pods, [win, default_pool()],
                                          lattice))
        assert not plan.unschedulable, plan.unschedulable
        assert all(n.node_pool == "win" for n in plan.new_nodes)
        # without effective-label domain resolution the spread silently
        # degrades to advisory ("no discoverable domains") — the windows
        # pool's stamped build label IS a discoverable domain
        assert not any("no discoverable domains" in w for w in plan.warnings), \
            plan.warnings


class TestAccelBinSplitting:
    """Accelerator bin-splitting (_accel_bin_cap): the solve beats the
    sequential FFD baseline on mixed accelerator+generic waves by landing
    accelerator pods on the cheapest PER-UNIT types instead of letting
    the scan stack a whole wave (plus co-located generics) onto one big
    upsized accelerator node."""

    def _mixed_problem(self):
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        specs = [s for s in build_catalog()
                 if s.family in ("m5", "c5", "g5")]
        lattice = build_lattice(specs)
        pods = [Pod(name=f"p{i}", requests={"cpu": "500m", "memory": "1Gi"})
                for i in range(24)]
        pods += [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                 for i in range(4)]
        return lattice, pods

    def test_beats_uncapped_ffd_on_mixed_wave(self):
        """The capped pack must cost LESS than the same pods packed
        without the cap (the reference's FFD behavior). The uncapped
        referee uses the first-class ``narrow=False`` path (exactly what
        bench cfg6 referees against) — monkeypatching the narrowing
        internals would be defeated by the content-keyed narrowing
        cache, which legitimately serves the memoized mask."""
        lattice, pods = self._mixed_problem()
        s = Solver(lattice)
        capped = s.solve(build_problem(pods, [default_pool()], lattice))
        uncapped = s.solve(build_problem(pods, [default_pool()], lattice,
                                         narrow=False))
        assert not capped.unschedulable and not uncapped.unschedulable
        assert capped.new_node_cost < uncapped.new_node_cost * 0.9, \
            (capped.new_node_cost, uncapped.new_node_cost)
        # every accelerator bin is a 1-GPU type (the per-unit optimum)
        gpu_bins = [n for n in capped.new_nodes
                    if any(p.startswith("g") for p in n.pods)]
        assert all(n.instance_type.startswith("g5.xlarge")
                   for n in gpu_bins), [n.instance_type for n in gpu_bins]

    def test_narrowing_cache_invalidates_on_price_version(self):
        """The content-keyed narrowing cache must serve identical masks
        for identical inputs, and recompute when in-place price edits
        bump ``price_version`` (pricing.py:133-134 mutates price[...]
        and bumps the version under the provider lock)."""
        import numpy as np
        lattice, pods = self._mixed_problem()
        pool = [default_pool()]
        p1 = build_problem(pods, pool, lattice)
        p1b = build_problem(pods, pool, lattice)

        def gpu_group(problem):
            for g in problem.groups:
                if any(n.startswith("g") for n in g.pod_names):
                    return g
            raise AssertionError("no gpu group")

        g1, g1b = gpu_group(p1), gpu_group(p1b)
        assert np.array_equal(g1.type_mask, g1b.type_mask)
        xl = lattice.name_to_idx["g5.xlarge"]
        assert g1.type_mask[xl]          # per-unit optimum pre-edit
        # 50x the per-unit winner's price; the cache must NOT serve the
        # stale mask once the version moves
        lattice.price[xl, :, :] *= 50.0
        lattice.price_version += 1
        try:
            g2 = gpu_group(build_problem(pods, pool, lattice))
            assert not g2.type_mask[xl], \
                "stale narrowing mask served after price_version bump"
        finally:
            lattice.price[xl, :, :] /= 50.0
            lattice.price_version += 1

    def test_no_cap_when_big_type_is_per_unit_cheapest(self):
        """When the multi-GPU type IS the per-unit optimum (e.g. 4-GPU
        pods that only p4-class types serve), the cap must keep bins at
        the big type's full count — never force a harmful split."""
        from karpenter_provider_aws_tpu.apis.resources import resources_to_vec
        from karpenter_provider_aws_tpu.solver.problem import _accel_bin_cap
        lattice, _ = self._mixed_problem()
        vec = resources_to_vec({"cpu": "4", "memory": "16Gi",
                                "nvidia.com/gpu": 4}, implicit_pod=True)
        import numpy as np
        ones_t = np.ones(lattice.T, bool)
        keep = _accel_bin_cap(
            vec, ones_t, np.ones(lattice.Z, bool),
            np.ones(lattice.C, bool), ones_t,
            np.zeros(lattice.T, bool), lattice)
        if keep is not None:
            # whatever types won per-unit, a 4-GPU pod fits whole
            assert keep.any()
            gpu_counts = lattice.capacity[keep][:, 4]
            assert (gpu_counts >= 4).all()

    def test_pool_restricted_gpu_pods_stay_schedulable(self):
        """Fence (review r4 #1): a pool pinned to one accelerator family
        must not be narrowed unschedulable by globally-cheaper types the
        pool can never launch."""
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        specs = [s for s in build_catalog()
                 if s.family in ("m5", "g5", "p4d")]
        lattice = build_lattice(specs)
        pool = NodePool(name="p4-only", requirements=[
            Requirement(wk.LABEL_INSTANCE_FAMILY, Operator.IN, ("p4d",))])
        pods = [Pod(name=f"g{i}", requests={"cpu": "2", "memory": "8Gi",
                                            "nvidia.com/gpu": 1})
                for i in range(4)]
        plan = Solver(lattice).solve(build_problem(pods, [pool], lattice))
        assert not plan.unschedulable, plan.unschedulable
        assert all(n.instance_type.startswith("p4d")
                   for n in plan.new_nodes)

    def test_existing_gpu_capacity_still_joinable(self):
        """Fence (review r4 #2): free GPUs on a running multi-GPU node
        beat launching new small nodes — the narrowed mask must keep the
        existing node's type joinable."""
        lattice, _ = self._mixed_problem()
        big = "g5.12xlarge"
        ti = lattice.name_to_idx[big]
        existing = [ExistingBin(
            name="running-gpu", node_pool="default", instance_type=big,
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros((R,), np.float32))]
        pods = [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                for i in range(3)]
        plan = Solver(lattice).solve(build_problem(
            pods, [default_pool()], lattice, existing=existing))
        assert not plan.unschedulable
        assert sorted(sum(plan.existing_assignments.values(), [])) ==             ["g0", "g1", "g2"], (plan.existing_assignments,
                                 [n.instance_type for n in plan.new_nodes])
        assert plan.new_nodes == []

    def test_wave_narrowing_beats_uncapped_ffd_on_tiny_pods(self):
        """Pods-axis-bound wave (_wave_bin_cap): sequential FFD grows
        tiny-pod bins to max density and end-prices at the huge types
        that carry it; the wave narrowing seals bins at the best
        per-POD-cost types instead. The capped solve must beat the
        UNCAPPED pack (the reference's behavior) outright."""
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "t3", "c5")])
        s = Solver(lattice)
        pods = [Pod(name=f"w{i}", requests={"cpu": "50m", "memory": "96Mi"})
                for i in range(500)]
        capped = s.solve(build_problem(pods, [default_pool()], lattice))
        uncapped = s.solve(build_problem(pods, [default_pool()], lattice,
                                         narrow=False))
        assert not capped.unschedulable and not uncapped.unschedulable
        assert capped.new_node_cost < uncapped.new_node_cost * 0.9, \
            (capped.new_node_cost, uncapped.new_node_cost)
        # and the uncapped solve stays at parity with the FFD referee
        # over the same (unnarrowed) problem
        o = ffd_oracle(build_problem(pods, [default_pool()], lattice,
                                     narrow=False))
        assert uncapped.new_node_cost <= o.new_node_cost * 1.02

    def test_wave_narrowing_gain_gate_stays_off_flat_shapes(self):
        """Small counts and non-pods-bound shapes must not narrow: the
        plan with narrowing enabled equals the plan without it."""
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "c5", "r5")])
        s = Solver(lattice)
        # under _WAVE_MIN_PODS: no narrowing by count
        pods = [Pod(name=f"p{i}", requests={"cpu": "50m", "memory": "96Mi"})
                for i in range(16)]
        a = s.solve(build_problem(pods, [default_pool()], lattice))
        b = s.solve(build_problem(pods, [default_pool()], lattice,
                                  narrow=False))
        assert a.new_node_cost == b.new_node_cost
        # cpu-bound wave on a flat-price palette (m/c/r scale ~linearly):
        # gain gate holds, identical plans
        pods = [Pod(name=f"q{i}", requests={"cpu": "3", "memory": "6Gi"})
                for i in range(200)]
        a = s.solve(build_problem(pods, [default_pool()], lattice))
        b = s.solve(build_problem(pods, [default_pool()], lattice,
                                  narrow=False))
        assert a.new_node_cost == b.new_node_cost

    def test_wave_narrowing_density_floor_bounds_plan_size(self):
        """A big tiny-pod wave must not fragment into thousands of
        minimum-size bins: candidates under count/_WAVE_MAX_BINS pods
        per bin are excluded, so the plan stays bounded while still
        beating the uncapped pack."""
        from karpenter_provider_aws_tpu.solver.problem import _WAVE_MAX_BINS
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "t3", "c5")])
        s = Solver(lattice)
        pods = [Pod(name=f"w{i}", requests={"cpu": "50m", "memory": "64Mi"})
                for i in range(3000)]
        capped = s.solve(build_problem(pods, [default_pool()], lattice))
        uncapped = s.solve(build_problem(pods, [default_pool()], lattice,
                                         narrow=False))
        assert not capped.unschedulable
        assert capped.num_new_nodes <= _WAVE_MAX_BINS + 2
        assert capped.new_node_cost < uncapped.new_node_cost

    def test_wave_narrowing_never_costs_schedulability(self):
        """A pool pinned away from the per-pod-cheapest types must still
        schedule the wave (unnarrowed fallback / pool fence)."""
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "t3")])
        pool = NodePool(name="m5-only", requirements=[
            Requirement(wk.LABEL_INSTANCE_FAMILY, Operator.IN, ("m5",))])
        pods = [Pod(name=f"w{i}", requests={"cpu": "50m", "memory": "96Mi"})
                for i in range(200)]
        plan = Solver(lattice).solve(build_problem(pods, [pool], lattice))
        assert not plan.unschedulable, plan.unschedulable
        assert all(n.instance_type.startswith("m5.")
                   for n in plan.new_nodes)

    def test_wave_narrowing_keeps_existing_nodes_joinable(self):
        """Free capacity on a running big node beats launching: the
        narrowed mask keeps the existing type joinable."""
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "t3")])
        big = "m5.4xlarge"
        existing = [ExistingBin(
            name="running-big", node_pool="default", instance_type=big,
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros((R,), np.float32))]
        pods = [Pod(name=f"w{i}", requests={"cpu": "50m", "memory": "96Mi"})
                for i in range(100)]
        plan = Solver(lattice).solve(build_problem(
            pods, [default_pool()], lattice, existing=existing))
        assert not plan.unschedulable
        assert "running-big" in plan.existing_assignments
        assert len(plan.existing_assignments["running-big"]) > 0

    def test_per_unit_ranking_respects_capacity_type(self):
        """Fence (review r4 #3): an on-demand-only group ranks per-unit
        prices over ON-DEMAND offerings; the cap still applies and the
        pods schedule on on-demand accelerator capacity."""
        lattice, _ = self._mixed_problem()
        pool = NodePool(name="od", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN,
                        ("on-demand",))])
        pods = [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                for i in range(4)]
        plan = Solver(lattice).solve(build_problem(pods, [pool], lattice))
        assert not plan.unschedulable, plan.unschedulable
        assert all(n.capacity_type == "on-demand" for n in plan.new_nodes)

    def test_cap_respects_hostname_self_affinity(self):
        """single_bin (hostname self-affinity) outranks the accel cap:
        all replicas still co-locate."""
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        lattice, _ = self._mixed_problem()
        pods = [Pod(name=f"co{i}", labels={"app": "trainer"},
                    requests={"cpu": "1", "nvidia.com/gpu": 1},
                    pod_affinity=[PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME,
                        label_selector=(("app", "trainer"),))])
                for i in range(3)]
        plan = Solver(lattice).solve(
            build_problem(pods, [default_pool()], lattice))
        assert not plan.unschedulable
        assert len(plan.new_nodes) == 1
        assert len(plan.new_nodes[0].pods) == 3

    def test_narrowing_never_costs_schedulability(self):
        """Fence (review r4 second pass): when narrowing interacts badly
        with downstream constraints (here: the narrowed type is ICE'd in
        the only pool-launchable zone), the group falls back to the full
        mask instead of going unschedulable."""
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.lattice.tensors import masked_view
        import numpy as np
        specs = [s for s in build_catalog() if s.family in ("m5", "g5")]
        lattice = build_lattice(specs)
        # pool pinned to one zone
        pool = NodePool(name="pinned", requirements=[
            Requirement(wk.LABEL_ZONE, Operator.IN, ("us-west-2a",))])
        # ICE out every 1-GPU type's offerings in that zone so the
        # narrowed set (cheap small types) has nothing the pool can launch
        mask = np.ones_like(lattice.available)
        zi = lattice.zones.index("us-west-2a")
        for i, name in enumerate(lattice.names):
            if lattice.capacity[i, 4] in (1.0,):   # nvidia axis
                mask[i, zi, :] = False
        view = masked_view(lattice, mask)
        pods = [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                for i in range(2)]
        plan = Solver(view).solve(build_problem(pods, [pool], view))
        assert not plan.unschedulable, plan.unschedulable
        # landed on a multi-GPU type in the pinned zone (the fallback)
        for n in plan.new_nodes:
            assert n.zone == "us-west-2a"
            ti = view.name_to_idx[n.instance_type]
            assert view.capacity[ti, 4] > 1


class TestMetamorphicInvariances:
    """Transformations with provable effects on the optimum: the solve
    must track them exactly. These pin the decode/caching layers as hard
    as the fuzz envelopes pin the kernel (SURVEY §4: solver tested by
    property/metamorphic checks vs the FFD oracle)."""

    def _mixed_pods(self):
        pods = generic_pods(60)
        pods += generic_pods(10, cpu="2", mem="8Gi", prefix="big")
        pods += [Pod(name=f"g{i}", requests={"cpu": "2", "nvidia.com/gpu": 1})
                 for i in range(3)]
        return pods

    def test_plan_idempotence(self, solver, lattice):
        """Identical inputs → identical plans, field for field (the
        memo layers must be exact, not approximate)."""
        pods = self._mixed_pods()
        pools = [default_pool()]
        p1 = solver.solve(build_problem(pods, pools, lattice))
        p2 = solver.solve(build_problem(pods, pools, lattice))
        assert p1.new_node_cost == p2.new_node_cost
        assert len(p1.new_nodes) == len(p2.new_nodes)
        for a, b in zip(p1.new_nodes, p2.new_nodes):
            assert (a.instance_type, a.zone, a.capacity_type,
                    sorted(a.pods)) == \
                   (b.instance_type, b.zone, b.capacity_type,
                    sorted(b.pods))
        assert p1.unschedulable == p2.unschedulable

    def test_price_scaling_covariance(self, lattice):
        """Scaling every price by k changes no argmin: the same nodes
        come back and the cost scales by exactly k."""
        from dataclasses import replace
        pods = self._mixed_pods()
        pools = [default_pool()]
        base = Solver(lattice).solve(build_problem(pods, pools, lattice))
        k = 3.0
        scaled_lat = replace(lattice, price=lattice.price * k)
        scaled = Solver(scaled_lat).solve(
            build_problem(pods, pools, scaled_lat))
        assert sorted((n.instance_type, n.zone, n.capacity_type)
                      for n in scaled.new_nodes) == \
               sorted((n.instance_type, n.zone, n.capacity_type)
                      for n in base.new_nodes)
        assert scaled.new_node_cost == pytest.approx(
            base.new_node_cost * k, rel=1e-5)

    def test_irrelevant_pool_invariance(self, solver, lattice):
        """A pool that can launch nothing must not change the plan at
        all. The impossible demand must be a WELL-KNOWN key: a pool
        requirement on a custom key OFFERS that label to pods (workload
        segregation, tests/test_custom_labels.py) — it would admit
        every pod rather than none."""
        pods = self._mixed_pods()
        base = solver.solve(build_problem(pods, [default_pool()], lattice))
        noise = NodePool(name="zzz-unmatchable", requirements=[
            Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN,
                        ("no-such-type",))])
        with_noise = solver.solve(
            build_problem(pods, [default_pool(), noise], lattice))
        assert with_noise.new_node_cost == base.new_node_cost
        assert sorted((n.instance_type, n.zone, n.capacity_type,
                       len(n.pods)) for n in with_noise.new_nodes) == \
               sorted((n.instance_type, n.zone, n.capacity_type,
                       len(n.pods)) for n in base.new_nodes)
        assert with_noise.unschedulable == base.unschedulable
