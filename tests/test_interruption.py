"""Interruption handling + metrics surface tests.

Behavioral spec: reference pkg/controllers/interruption (4 message schemas,
parser registry, CordonAndDrain for spot/scheduled/state-change, NoAction
for rebalance, spot ICE marking) and website reference/metrics.md series.
"""

import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOp, Pod, Requirement
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
from karpenter_provider_aws_tpu.interruption import (
    FakeQueue, MessageKind, parse_message, rebalance_recommendation,
    scheduled_change, spot_interruption, state_change,
)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.metrics import Registry
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture()
def env(lattice):
    clock = FakeClock()
    queue = FakeQueue("interruptions")
    pool = NodePool(name="default", requirements=[
        Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot", "on-demand"))])
    return Operator(options=Options(registration_delay=1.0), lattice=lattice,
                    cloud=FakeCloud(clock), clock=clock, node_pools=[pool],
                    interruption_queue=queue)


def add_pods(env, n=3):
    for i in range(n):
        env.cluster.add_pod(Pod(name=f"p{i}", requests={"cpu": "500m", "memory": "1Gi"}))


class TestParsers:
    def test_spot(self):
        m = parse_message(spot_interruption("i-abc"))
        assert m.kind == MessageKind.SPOT_INTERRUPTION and m.instance_ids == ("i-abc",)

    def test_rebalance(self):
        m = parse_message(rebalance_recommendation("i-abc"))
        assert m.kind == MessageKind.REBALANCE_RECOMMENDATION

    def test_scheduled_change_multi_entity(self):
        m = parse_message(scheduled_change("i-1", "i-2"))
        assert m.kind == MessageKind.SCHEDULED_CHANGE and m.instance_ids == ("i-1", "i-2")

    def test_scheduled_change_non_ec2_is_noop(self):
        body = scheduled_change("i-1")
        body["detail"]["service"] = "S3"
        assert parse_message(body).kind == MessageKind.NOOP

    def test_state_change_actionable_vs_not(self):
        assert parse_message(state_change("i-1", "stopping")).kind == MessageKind.STATE_CHANGE
        assert parse_message(state_change("i-1", "running")).kind == MessageKind.NOOP

    def test_unknown_detail_type_is_noop(self):
        assert parse_message({"source": "x", "detail-type": "y"}).kind == MessageKind.NOOP

    def test_non_dict_and_broken_bodies_are_malformed(self):
        # never raises: the controller loop counts + drops these
        # (tests/test_weather.py pins the full burst behavior)
        assert parse_message("junk").kind == MessageKind.MALFORMED
        assert parse_message(["junk"]).kind == MessageKind.MALFORMED
        body = spot_interruption("i-1")
        body["detail"] = {}
        assert parse_message(body).kind == MessageKind.MALFORMED


class TestInterruptionController:
    def test_spot_interruption_drains_and_marks_ice(self, env):
        add_pods(env)
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.capacity_type == "spot"
        iid = parse_instance_id(claim.provider_id)
        env.interruption_queue.send(spot_interruption(iid))
        env.interruption.reconcile()
        assert env.unavailable.is_unavailable("spot", claim.instance_type, claim.zone)
        assert env.cluster.claims[claim.name].deletion_timestamp
        assert len(env.interruption_queue) == 0
        # drive to steady state: replacement avoids the interrupted offering
        rounds = env.settle(max_rounds=60)
        assert rounds < 60
        replacement = next(iter(env.cluster.claims.values()))
        assert (replacement.instance_type, replacement.zone) != (claim.instance_type, claim.zone)
        assert all(p.node_name for p in env.cluster.pods.values())

    def test_rebalance_recommendation_no_action(self, env):
        add_pods(env)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.interruption_queue.send(
            rebalance_recommendation(parse_instance_id(claim.provider_id)))
        env.interruption.reconcile()
        assert not env.cluster.claims[claim.name].deletion_timestamp
        assert len(env.interruption_queue) == 0
        assert env.recorder.events(reason=MessageKind.REBALANCE_RECOMMENDATION.value)

    def test_scheduled_change_drains(self, env):
        add_pods(env)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.interruption_queue.send(
            scheduled_change(parse_instance_id(claim.provider_id)))
        env.interruption.reconcile()
        assert env.cluster.claims[claim.name].deletion_timestamp

    def test_unmanaged_instance_ignored(self, env):
        env.interruption_queue.send(spot_interruption("i-ffffffff"))
        handled = env.interruption.reconcile()
        assert handled == 1 and len(env.interruption_queue) == 0

    def test_spot_event_on_od_claim_does_not_mark_ice(self, lattice):
        """Regression (round-1 ADVICE): a spot-interruption event for an
        on-demand claim must not poison the spot ICE cache for that
        type/zone (reference controller.go:194-200 guards on capacity
        type). The drain itself still proceeds — the event says the
        instance is going away."""
        clock = FakeClock()
        queue = FakeQueue("interruptions")
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock, node_pools=[pool],
                       interruption_queue=queue)
        add_pods(env)
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.capacity_type == "on-demand"
        queue.send(spot_interruption(parse_instance_id(claim.provider_id)))
        env.interruption.reconcile()
        assert not env.unavailable.is_unavailable("spot", claim.instance_type,
                                                  claim.zone)
        assert env.cluster.claims[claim.name].deletion_timestamp

    def test_message_metrics(self, env):
        add_pods(env)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.interruption_queue.send(spot_interruption(parse_instance_id(claim.provider_id)))
        env.interruption.reconcile()
        received = env.metrics.get("karpenter_interruption_received_messages_total")
        assert received.value(message_type=MessageKind.SPOT_INTERRUPTION.value) == 1
        deleted = env.metrics.get("karpenter_interruption_deleted_messages_total")
        assert deleted.value() == 1


class TestMetricsSurface:
    def test_core_series_populated(self, env):
        add_pods(env, 5)
        env.settle()
        text = env.metrics.render()
        assert "karpenter_pods_scheduled_total 5.0" in text
        assert 'karpenter_nodeclaims_launched_total{nodepool="default"} 1.0' in text
        assert 'karpenter_nodeclaims_registered_total{nodepool="default"} 1.0' in text
        assert 'karpenter_nodeclaims_initialized_total{nodepool="default"} 1.0' in text
        assert "karpenter_cluster_state_node_count 1.0" in text
        assert "karpenter_cluster_state_pod_count 5.0" in text
        sched = env.metrics.get("karpenter_provisioner_scheduling_duration_seconds")
        assert sched.count() >= 1

    def test_cloudprovider_decoration(self, env):
        add_pods(env, 1)
        env.settle()
        dur = env.metrics.get("karpenter_cloudprovider_duration_seconds")
        assert dur.count(controller="operator", method="create") >= 1
        # error path increments the error counter
        from karpenter_provider_aws_tpu.errors import NotFoundError
        with pytest.raises(NotFoundError):
            env.cloud_provider.get("fake:///zone/i-doesnotexist")
        errs = env.metrics.get("karpenter_cloudprovider_errors_total")
        assert errs.value(controller="operator", method="get", error="NotFoundError") == 1

    def test_terminated_and_disrupted_counters(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import NodePoolDisruption
        clock = FakeClock()
        pool = NodePool(name="default",
                        requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))],
                        disruption=NodePoolDisruption(consolidate_after=5.0))
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock, node_pools=[pool])
        add_pods(env, 2)
        env.settle()
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        clock.step(6)
        for _ in range(5):
            env.run_once()
            clock.step(2)
        disrupted = env.metrics.get("karpenter_nodeclaims_disrupted_total")
        assert disrupted.value(nodepool="default", reason="Empty") == 1
        terminated = env.metrics.get("karpenter_nodeclaims_terminated_total")
        assert terminated.value(nodepool="default") == 1

    def test_render_is_prometheus_text(self, env):
        text = env.metrics.render()
        assert "# TYPE karpenter_provisioner_batch_size histogram" in text
        assert "# TYPE karpenter_pods_scheduled_total counter" in text
        assert "# TYPE karpenter_cluster_state_node_count gauge" in text


class TestThroughputHarness:
    """The reference benches its interruption path at 100/1k/5k/15k queue
    depths (interruption_benchmark_test.go:61-75); tools/bench_interruption.py
    is that harness. This exercises it at depth 2000 and guards against the
    queue or controller going quadratic on deep drains."""

    def test_drain_2000_messages(self):
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        from tools.bench_interruption import build_env, drain, seed_messages
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice

        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "c5")])
        env = build_env(lattice)
        seed_messages(env, 2000)
        import time
        t0 = time.perf_counter()
        handled = drain(env)
        wall = time.perf_counter() - t0
        assert handled == 2000
        assert len(env.interruption_queue) == 0
        # every spot interruption for a spot claim marked the pool ICE
        assert sum(1 for _ in env.unavailable.entries()) > 0
        # all received+deleted accounted in the metric surface
        assert env.metrics.get(
            "karpenter_interruption_deleted_messages_total").value() == 2000
        # quadratic drains land in the tens of seconds; a healthy one is <2s
        assert wall < 10.0, f"drain took {wall:.1f}s"


class TestReferenceMetricSurface:
    """karpenter_nodepool_usage/limit + pods_startup_time_seconds
    (reference metrics.md:16-22,62)."""

    def test_pool_usage_limit_and_startup_series(self, lattice):
        clock = FakeClock()
        pool = NodePool(name="default", limits={"cpu": "100"},
                        requirements=[Requirement(wk.LABEL_CAPACITY_TYPE,
                                                  ReqOp.IN, ("on-demand",))])
        env = Operator(options=Options(registration_delay=2.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[pool])
        env.cluster.add_pod(Pod(name="w", requests={"cpu": "500m", "memory": "1Gi"}))
        env.settle()
        usage = env.metrics.get("karpenter_nodepool_usage")
        assert usage.value(nodepool="default", resource_type="cpu") > 0
        limit = env.metrics.get("karpenter_nodepool_limit")
        assert limit.value(nodepool="default", resource_type="cpu") == 100_000
        startup = env.metrics.get("karpenter_pods_startup_time_seconds")
        assert startup.count() == 1
        # startup = batch wait + launch + registration_delay >= 2s
        assert startup.sum() >= 2.0
        text = env.metrics.render()
        assert "karpenter_pods_startup_time_seconds" in text
        assert "karpenter_nodepool_usage" in text
