"""Adversarial weather suite tests (weather/; docs/reference/weather.md).

Behavioral spec: ISSUE 9 / ROADMAP item 5 — a replayable spot-market +
interruption-storm chaos system driving the degradation ladder. Pins:

- scenarios serialize round-trip and the named library parses,
- the weather timeline is a pure function of (scenario, seed, ticks):
  same-seed replays are byte-identical, different seeds diverge,
- the simulator's side effects land through the REAL seams: spot prices
  via PricingProvider (price_version bumps), ICE via FakeCloud capacity
  + UnavailableOfferings, storms via the interruption queue (all four
  EventBridge schemas + junk), device weather via FaultInjector — and
  stop() restores fair weather,
- --fault-schedule and --weather compose on one injector, and a `clear`
  mark fully restores the un-faulted solver (regression),
- storm bursts round-trip through the interruption controller: dedup,
  cordon→teardown ordering, no lost messages at queue bounds, malformed
  bodies counted and dropped.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.interruption.messages import MessageKind
from karpenter_provider_aws_tpu.interruption.queue import FakeQueue
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock
from karpenter_provider_aws_tpu.weather import (
    IceSpell, Regime, Storm, WeatherScenario, WeatherSimulator,
    inject_device_errors, load_scenario, named, NAMED_SCENARIOS,
)

_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


def make_env(lattice, **opt):
    clock = FakeClock()
    queue = FakeQueue("weather-test")
    op = Operator(options=Options(registration_delay=0.5, **opt),
                  lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                  interruption_queue=queue)
    return op, clock, queue


def attach(op, clock, queue, scenario, lattice, seed=None):
    return WeatherSimulator(
        scenario, lattice, seed=seed, clock=clock,
        pricing=op.pricing_provider, cloud=op.cloud,
        unavailable=op.unavailable, queue=queue, solver=op.solver,
        metrics=op.metrics).start()


class TestScenario:
    def test_named_library_round_trips(self):
        for name in NAMED_SCENARIOS:
            sc = named(name)
            assert sc.name == name
            assert WeatherScenario.from_json(sc.to_json()) == sc

    def test_load_scenario_name_file_and_error(self, tmp_path):
        assert load_scenario("squall") == named("squall")
        p = tmp_path / "custom.json"
        sc = WeatherScenario(name="mine", seed=7, storms=(
            Storm(at=1.0, duration=2.0, zones=("us-west-2a",)),))
        p.write_text(sc.to_json())
        assert load_scenario(str(p)) == sc
        with pytest.raises(ValueError):
            load_scenario("hurricane-noexist")

    def test_unknown_fields_rejected(self):
        with pytest.raises(ValueError):
            WeatherScenario.from_dict({"name": "x", "tornado": True})


class TestDeterminism:
    def test_same_seed_identical_different_seed_diverges(self, lattice):
        sc = named("storm-front")
        a = WeatherSimulator.replay(sc, lattice, 120)
        b = WeatherSimulator.replay(sc, lattice, 120)
        c = WeatherSimulator.replay(sc, lattice, 120, seed=123)
        assert a == b
        assert a != c
        assert len(a) > 50

    def test_live_run_matches_noop_replay(self, lattice):
        """The timeline a sim records WITH a control plane attached (live
        instance counts, queue sends, price pushes) is identical to the
        detached derivation — runtime state never leaks into it."""
        op, clock, queue = make_env(lattice)
        for i in range(4):
            op.cluster.add_pod(Pod(name=f"p{i}",
                                   requests={"cpu": "500m",
                                             "memory": "1Gi"}))
        op.settle()
        sc = named("squall")
        sim = attach(op, clock, queue, sc, lattice)
        for _ in range(40):
            op.run_once()
            clock.step(sc.tick_seconds)
            sim.advance()
        assert sim.ticks == 40
        assert WeatherSimulator.replay(sc, lattice, 40) == sim.timeline
        sim.stop()   # restore the shared fixture's market

    def test_subtick_storm_pairs_begin_burst_end(self, lattice):
        """A storm shorter than tick_seconds still runs begin → one
        burst → end on the tick it slips past — never an unpaired
        storm-end in the timeline."""
        sc = WeatherScenario(
            name="t", tick_seconds=2.0,
            storms=(Storm(at=1.0, duration=0.5, intensity=0.5),))
        tl = WeatherSimulator.replay(sc, lattice, 3)
        kinds = [e["kind"] for e in tl if e["kind"].startswith("storm")]
        assert kinds == ["storm-begin", "storm-burst", "storm-end"]

    def test_regime_matching_nothing_never_activates(self, lattice):
        """A regime whose families/zones name nothing the lattice
        carries must not count as a shift (the soak's regime
        non-vacuity gate would otherwise pass on a price drill that
        never happened)."""
        sc = WeatherScenario(
            name="t", regimes=(Regime(at=0.0, mu=1.0,
                                      families=("zz99",)),))
        sim = WeatherSimulator(sc, lattice)
        sim.step(10)
        assert sim.counters["regime_shifts"] == 0
        assert not any(e["kind"] == "regime" for e in sim.timeline)

    def test_advance_catches_up_missed_ticks(self, lattice):
        sc = named("calm")
        clock = FakeClock()
        sim = WeatherSimulator(sc, lattice, clock=clock).start()
        clock.step(sc.tick_seconds * 7)
        assert sim.advance() == 7
        assert sim.ticks == 7
        assert sim.advance() == 0


class TestMarketField:
    def test_mean_reversion_keeps_multipliers_bounded(self, lattice):
        sc = WeatherScenario(name="t", market_sigma=0.04)
        sim = WeatherSimulator(sc, lattice)
        sim.step(500)
        mean, mx = sim.market.multiplier_stats()
        # OU stationary sd = sigma/sqrt(2*theta) ≈ 0.073 in log space:
        # a runaway walk (no reversion) would drift far past this
        assert 0.6 < mean < 1.6
        assert mx < 3.0

    def test_regime_shift_moves_the_mean(self, lattice):
        sc = WeatherScenario(
            name="t", market_sigma=0.01,
            regimes=(Regime(at=0.0, mu=0.7),))   # e^0.7 ≈ 2x
        sim = WeatherSimulator(sc, lattice)
        sim.step(100)
        mean, _ = sim.market.multiplier_stats()
        assert mean > 1.6
        assert any(e["kind"] == "regime" for e in sim.timeline)

    def test_reprice_pushes_through_pricing_provider(self):
        import numpy as np
        # a PRIVATE lattice: this test compares against the pristine
        # static tensor, which the shared module fixture cannot
        # guarantee (other tests weather it through the same in-place
        # pricing seam production uses)
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in _FAMILIES])
        op, clock, queue = make_env(lattice)
        before = lattice.price.copy()
        v0 = lattice.price_version
        sc = WeatherScenario(name="t", market_sigma=0.2, seed=3)
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(5)
        assert lattice.price_version > v0
        ci = lattice.capacity_types.index("spot")
        assert not np.allclose(before[:, :, ci], lattice.price[:, :, ci],
                               equal_nan=True)
        # on-demand prices are not weather's to move
        oci = lattice.capacity_types.index("on-demand")
        assert np.allclose(before[:, :, oci], lattice.price[:, :, oci],
                           equal_nan=True)
        # stop() restores the base market (one more version bump)
        v1 = lattice.price_version
        sim.stop()
        assert lattice.price_version > v1
        assert np.allclose(before[:, :, ci], lattice.price[:, :, ci],
                           equal_nan=True)


class TestIceField:
    def test_spell_holds_and_thaws_pools(self, lattice):
        op, clock, queue = make_env(lattice)
        sc = WeatherScenario(
            name="t", tick_seconds=1.0,
            ice=(IceSpell(at=0.0, duration=5.0, rate=2.0,
                          hold_seconds=4.0),))
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(4)
        assert sim.stats()["ice_pools"] > 0
        held = [o for o, _ in sim._held.items()]
        for ct, it, z in held:
            assert op.cloud.capacity_pools[(ct, it, z)] == 0
            assert op.unavailable.is_unavailable(ct, it, z)
        # march past every hold: spells end at 5 s, max hold 6 ticks
        sim.step(15)
        assert sim.stats()["ice_pools"] == 0
        assert any(e["kind"] == "ice-thaw" for e in sim.timeline)
        for ct, it, z in held:
            assert (ct, it, z) not in op.cloud.capacity_pools
            assert not op.unavailable.is_unavailable(ct, it, z)

    def test_stop_thaws_everything(self, lattice):
        op, clock, queue = make_env(lattice)
        sc = WeatherScenario(
            name="t", tick_seconds=1.0,
            ice=(IceSpell(at=0.0, duration=50.0, rate=3.0,
                          hold_seconds=100.0),),
            storms=(Storm(at=0.0, duration=50.0, intensity=0.1),))
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(5)
        assert len(sim._held) > 0
        assert sim.stats()["storms_active"] == 1
        sim.stop()
        assert len(sim._held) == 0
        assert not op.cloud.capacity_pools
        assert sum(1 for _ in op.unavailable.entries()) == 0
        # every live surface agrees after stop(): the stats provider and
        # the gauges both read fair weather, counters stay as evidence
        st = sim.stats()
        assert st["storms_active"] == 0
        assert st["spot_mult_mean"] == 1.0 and st["spot_mult_max"] == 1.0
        assert st["ice_marks"] > 0
        assert op.metrics.get(
            "karpenter_weather_spot_price_multiplier_max").value() == 1.0

    def test_weather_hold_survives_capacity_handback(self, lattice):
        """terminate_instances hands +1 capacity back to a limited pool —
        the next tick must re-assert the hold at 0 (cloud/fake.py)."""
        op, clock, queue = make_env(lattice)
        sc = WeatherScenario(
            name="t", tick_seconds=1.0,
            ice=(IceSpell(at=0.0, duration=60.0, rate=2.0,
                          hold_seconds=100.0),))
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(3)
        (ct, it, z) = next(iter(sim._held))
        op.cloud.capacity_pools[(ct, it, z)] = 1   # the hand-back race
        sim.step(1)
        assert op.cloud.capacity_pools[(ct, it, z)] == 0


class TestDeviceWeather:
    def test_faults_merge_with_operator_injector(self, lattice):
        """--fault-schedule and --weather share one FaultInjector: weather
        device errors must MERGE into an operator-applied injector, never
        clobber its g/b ceilings."""
        from karpenter_provider_aws_tpu.solver import FaultInjector
        op, clock, queue = make_env(lattice)
        inj = FaultInjector(g_limit=64)
        op.solver.inject_faults(inj)
        inject_device_errors(op.solver, 3)
        assert op.solver.faults is inj
        assert op.solver.faults.g_limit == 64
        assert op.solver.faults.device_errors == 3
        inject_device_errors(op.solver, 2)
        assert op.solver.faults.device_errors == 5

    def test_storm_injects_and_ladder_engages(self, lattice):
        op, clock, queue = make_env(lattice)
        sc = WeatherScenario(
            name="t", tick_seconds=1.0,
            storms=(Storm(at=0.0, duration=30.0, intensity=0.0,
                          device_error_rate=1.0, device_errors=3),))
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(2)
        assert op.solver.faults is not None
        assert sim.counters["device_errors"] >= 6
        for i in range(3):
            op.cluster.add_pod(Pod(name=f"d{i}",
                                   requests={"cpu": "500m",
                                             "memory": "1Gi"}))
        op.settle()
        # 3 pending errors >= retry budget: the host-FFD rung engaged and
        # every pod still scheduled (degrade latency, never availability)
        assert sum(op.solver.degraded_counts.values()) > 0
        assert not op.cluster.pending_pods()


class TestSoakCompose:
    def test_clear_fully_restores_unfaulted_solver(self, lattice):
        """Regression for the soak's `clear` semantics: after g-limit +
        weather device errors, a `clear` mark drops the injector entirely
        and the next solve runs the primary path (no wave-split, no new
        degradation)."""
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        from soak import apply_fault, parse_fault_schedule
        sched = parse_fault_schedule("1:g-limit=8,2:device-error=2,9:clear")
        assert [(s[1], s[2]) for s in sched] == [
            ("g-limit", 8), ("device-error", 2), ("clear", None)]
        op, clock, queue = make_env(lattice)
        apply_fault(op.solver, "g-limit", 8)
        inject_device_errors(op.solver, 2)       # weather composing on top
        assert op.solver.faults.g_limit == 8
        assert op.solver.faults.device_errors == 2
        apply_fault(op.solver, "clear", None)
        assert op.solver.faults is None
        degraded_before = dict(op.solver.degraded_counts)
        for i in range(3):
            op.cluster.add_pod(Pod(name=f"c{i}",
                                   requests={"cpu": "500m",
                                             "memory": "1Gi"}))
        op.settle()
        assert not op.cluster.pending_pods()
        assert op.solver.degraded_counts == degraded_before
        assert op.solver.faults is None


class TestStormBurst:
    """All four EventBridge schemas round-tripped through
    interruption/controller.py in one burst under FakeClock (ISSUE 9
    satellite): dedup, cordon→teardown ordering, no lost messages at
    queue bounds."""

    def _settled_env(self, lattice, pods=6):
        op, clock, queue = make_env(lattice)
        for i in range(pods):
            op.cluster.add_pod(Pod(name=f"b{i}",
                                   requests={"cpu": "2", "memory": "4Gi"}))
        op.settle()
        assert not op.cluster.pending_pods()
        return op, clock, queue

    def test_burst_all_schemas_dedup_ordering_no_loss(self, lattice):
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        from karpenter_provider_aws_tpu.interruption.messages import (
            rebalance_recommendation, scheduled_change, spot_interruption,
            state_change)
        op, clock, queue = self._settled_env(lattice)
        claims = {parse_instance_id(c.provider_id): c
                  for c in op.cluster.claims.values() if c.provider_id}
        iid = next(iter(claims))
        # one burst: duplicates of every schema for ONE instance, plus
        # junk, all beyond the MAX_MESSAGES=10 receive bound
        sent = 0
        for _ in range(4):
            queue.send(spot_interruption(iid)); sent += 1
            queue.send(rebalance_recommendation(iid)); sent += 1
            queue.send(scheduled_change(iid)); sent += 1
            queue.send(state_change(iid, "stopping")); sent += 1
        for j in range(20):
            queue.send(["junk", j] if j % 2 else
                       {"source": "chaos", "detail-type": "??"})
            sent += 1
        assert sent > 10   # multiple receive batches required
        deleted0 = op.metrics.get(
            "karpenter_interruption_deleted_messages_total").value()
        handled = 0
        for _ in range(20):
            handled += op.interruption.reconcile()
            if len(queue) == 0:
                break
        # no lost messages at queue bounds: every send was received,
        # handled, and deleted exactly once
        assert handled == sent
        assert len(queue) == 0
        deleted = op.metrics.get(
            "karpenter_interruption_deleted_messages_total").value()
        assert deleted - deleted0 == sent
        stats = op.interruption.stats()
        assert stats["handler_errors"] == 0
        assert stats["received_spot_interruption"] == 4
        assert stats["received_rebalance_recommendation"] == 4
        assert stats["received_scheduled_change"] == 4
        assert stats["received_state_change"] == 4
        assert stats["received_malformed"] == 10
        assert stats["received_noop"] == 10   # well-formed unknown bodies
        assert stats["queue_depth"] == 0
        # dedup: 12 actionable messages for one instance → ONE deleting
        # claim, every other claim untouched
        target = claims[iid]
        assert op.cluster.claims[target.name].deletion_timestamp
        others = [c for i2, c in claims.items() if i2 != iid]
        for c in others:
            assert not op.cluster.claims[c.name].deletion_timestamp
        # cordon → teardown ordering: drive termination to completion and
        # check the event order for the drained node
        node = op.cluster.node_for_claim(target.name)
        assert node is not None
        op.settle(max_rounds=60)
        events = [(e.reason, e.object_name) for e in op.recorder.events()]
        cordon_i = events.index(("Cordoned", node.name))
        term_i = events.index(("Terminated", target.name))
        assert cordon_i < term_i
        # the interruption counter surface saw the whole burst
        m = op.metrics.get("karpenter_interruption_messages_total")
        assert m.value(kind="spot-interruption") == 4
        assert m.value(kind="malformed") == 10
        assert m.value(kind="noop") == 10
        assert op.metrics.get(
            "karpenter_interruption_queue_depth").value() == 0

    def test_simulator_storm_targets_matching_spot_instances(self, lattice):
        op, clock, queue = self._settled_env(lattice, pods=8)
        spot = [i for i in op.cloud.peek_instances()
                if i.capacity_type == "spot"]
        assert spot, "settled env launched no spot capacity"
        zones = sorted({i.zone for i in spot})
        sc = WeatherScenario(
            name="t", tick_seconds=1.0,
            storms=(Storm(at=0.0, duration=10.0, zones=(zones[0],),
                          intensity=1.0, junk_rate=1.0),))
        sim = attach(op, clock, queue, sc, lattice)
        sim.step(3)
        assert sim.counters["messages_sent"] > 0
        assert sim.counters["junk_sent"] == 3
        # every targeted body names an instance in the storm zone
        from karpenter_provider_aws_tpu.interruption.messages import \
            parse_message
        by_id = {i.id: i for i in op.cloud.peek_instances()}
        for qm in queue.receive(max_messages=1000):
            msg = parse_message(qm.body)
            for iid in msg.instance_ids:
                assert by_id[iid].zone == zones[0]
                assert by_id[iid].capacity_type == "spot"
        # the controller drains the storm without crashing
        for _ in range(30):
            if op.interruption.reconcile() == 0 and len(queue) == 0:
                break
        assert len(queue) == 0
        assert op.interruption.stats()["handler_errors"] == 0


class TestHandlerRetrySemantics:
    """A handler blow-up must NOT cost the message (at-least-once: a
    2-minute spot notice survives a transient cloud hiccup), but a
    message that keeps failing is a poison pill — counted and dropped
    after HANDLER_RETRY_LIMIT attempts."""

    def _env_with_claim(self, lattice):
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        op, clock, queue = make_env(lattice)
        op.cluster.add_pod(Pod(name="h0",
                               requests={"cpu": "500m", "memory": "1Gi"}))
        op.settle()
        claim = next(iter(op.cluster.claims.values()))
        return op, queue, parse_instance_id(claim.provider_id), claim

    def test_transient_failure_redelivers_then_succeeds(self, lattice):
        from karpenter_provider_aws_tpu.interruption.messages import \
            spot_interruption
        op, queue, iid, claim = self._env_with_claim(lattice)
        real = op.interruption.termination.delete_claim
        calls = {"n": 0}

        def flaky(name):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("transient cloud hiccup")
            return real(name)

        op.interruption.termination = type(
            "T", (), {"delete_claim": staticmethod(flaky)})()
        queue.send(spot_interruption(iid))
        assert op.interruption.reconcile() == 0     # attempt 1: kept
        assert len(queue) == 1
        assert op.interruption.reconcile() == 0     # attempt 2: kept
        assert len(queue) == 1
        assert op.interruption.reconcile() == 1     # attempt 3: handled
        assert len(queue) == 0
        stats = op.interruption.stats()
        assert stats["handler_errors"] == 2
        assert stats["poison_dropped"] == 0
        # processed-by-kind counts on DISPOSAL: three deliveries of one
        # message count ONCE (the soak's evidence gate sums these)
        assert stats["received_spot_interruption"] == 1
        assert op.metrics.get(
            "karpenter_interruption_messages_total").value(
                kind="spot-interruption") == 1
        # the legacy received counter keeps per-delivery semantics
        assert op.metrics.get(
            "karpenter_interruption_received_messages_total").value(
                message_type="SpotInterruptionKind") == 3
        assert op.cluster.claims[claim.name].deletion_timestamp

    def test_poison_pill_dropped_after_retry_limit(self, lattice):
        from karpenter_provider_aws_tpu.interruption.controller import \
            InterruptionController
        from karpenter_provider_aws_tpu.interruption.messages import \
            spot_interruption
        op, queue, iid, claim = self._env_with_claim(lattice)

        def always_broken(name):
            raise RuntimeError("deterministic handler bug")

        op.interruption.termination = type(
            "T", (), {"delete_claim": staticmethod(always_broken)})()
        queue.send(spot_interruption(iid))
        limit = InterruptionController.HANDLER_RETRY_LIMIT
        for attempt in range(limit - 1):
            assert op.interruption.reconcile() == 0
            assert len(queue) == 1                  # still redelivering
        assert op.interruption.reconcile() == 1     # final attempt: drop
        assert len(queue) == 0
        stats = op.interruption.stats()
        assert stats["handler_errors"] == limit
        assert stats["poison_dropped"] == 1
        assert op.interruption._attempts == {}      # bounded bookkeeping


class TestIntrospectionSurface:
    def test_weather_provider_and_gauges(self, lattice):
        from karpenter_provider_aws_tpu import introspect
        op, clock, queue = make_env(lattice)
        sc = named("squall")
        sim = attach(op, clock, queue, sc, lattice)
        introspect.registry().register("weather", sim.stats)
        sim.step(25)   # into the squall
        doc = introspect.registry().collect()
        w = doc["weather"]
        assert w["scenario"] == "squall"
        assert w["ticks"] == 25
        assert w["storms_active"] == 1
        assert op.metrics.get("karpenter_weather_ticks").value() == 25
        assert op.metrics.get("karpenter_weather_storm_active").value() == 1
        assert op.metrics.get(
            "karpenter_weather_events_total").value(kind="reprice") == 25
        introspect.registry().unregister("weather")

    def test_interruption_provider_registered(self, lattice):
        from karpenter_provider_aws_tpu import introspect
        op, clock, queue = make_env(lattice)
        doc = introspect.registry().collect()
        assert "interruption" in doc
        assert doc["interruption"]["queue_depth"] == 0

    def test_metrics_render_lints_clean(self, lattice):
        from karpenter_provider_aws_tpu.metrics import lint_exposition
        op, clock, queue = make_env(lattice)
        sim = attach(op, clock, queue, named("squall"), lattice)
        sim.step(25)
        queue_drained = 0
        for _ in range(10):
            queue_drained += op.interruption.reconcile()
        assert lint_exposition(op.metrics.render()) == []

    def test_kpctl_weather_and_interrupt_rows(self, lattice):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        import kpctl
        doc = {"uptimeSeconds": 5.0, "providers": {
            "weather": {"scenario": "squall", "ticks": 30,
                        "storms_active": 1, "ice_pools": 2,
                        "spot_mult_mean": 1.12, "spot_mult_max": 1.8,
                        "messages_sent": 40, "junk_sent": 5},
            "interruption": {"queue_depth": 3,
                             "received_spot_interruption": 7,
                             "received_malformed": 2,
                             "handler_errors": 1},
        }}
        frame = "\n".join(kpctl._render_top(doc, "test"))
        assert "WEATHER   squall tick 30" in frame
        assert "spot x1.12 (max x1.80)" in frame
        assert "INTERRUPT queue 3" in frame
        assert "spot-interruption 7" in frame
        assert "handler-errors 1" in frame

    def test_rows_absent_without_providers(self, lattice):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        import kpctl
        frame = "\n".join(kpctl._render_top(
            {"uptimeSeconds": 1.0, "providers": {}}, "test"))
        assert "WEATHER" not in frame
        assert "INTERRUPT" not in frame


class TestParserRobustness:
    """parse_message must NEVER raise (the controller loop depends on
    it): non-dict bodies and parser blow-ups classify MALFORMED, unknown
    (source, detail-type) stays NOOP."""

    def test_non_dict_bodies(self):
        from karpenter_provider_aws_tpu.interruption.messages import \
            parse_message
        for body in (None, 42, "junk", ["a"], ("b",)):
            assert parse_message(body).kind == MessageKind.MALFORMED

    def test_registered_parser_blowup_is_malformed(self):
        from karpenter_provider_aws_tpu.interruption.messages import \
            parse_message
        bodies = [
            {"source": "aws.ec2", "detail-type":
             "EC2 Spot Instance Interruption Warning", "detail": {}},
            {"source": "aws.ec2", "detail-type":
             "EC2 Spot Instance Interruption Warning", "detail": None},
            {"source": "aws.health", "detail-type": "AWS Health Event",
             "detail": {"service": "EC2", "affectedEntities": 17}},
        ]
        for b in bodies:
            assert parse_message(b).kind == MessageKind.MALFORMED, b

    def test_unknown_is_noop_not_malformed(self):
        from karpenter_provider_aws_tpu.interruption.messages import \
            parse_message
        m = parse_message({"source": "x", "detail-type": "y"})
        assert m.kind == MessageKind.NOOP
