"""Concept docs tell the truth.

docs/concepts/{scheduling,disruption}.md are standalone behavioral
specs (the reference's concepts pages are its spec of record); this
pins the load-bearing numbers and names they cite to the code
constants that implement them, the same freshness discipline the
generated reference docs get from tools/gen_docs.py --check.
"""

import pathlib
import re

DOCS = pathlib.Path(__file__).resolve().parent.parent / "docs" / "concepts"


def _read(name):
    # collapse hard wraps so phrase assertions are layout-independent
    return re.sub(r"\s+", " ", (DOCS / name).read_text())


def _lines(name):
    return (DOCS / name).read_text().splitlines()


def _assert_cited_metrics_exist(doc_name):
    """Every karpenter_* metric a doc names must exist in the registry
    source."""
    src = (DOCS.parent.parent / "karpenter_provider_aws_tpu" /
           "metrics.py").read_text()
    for m in re.findall(r"karpenter_[a-z_]+", _read(doc_name)):
        assert m in src, m


class TestSchedulingDocFacts:
    def test_spec_depth(self):
        assert len(_lines("scheduling.md")) >= 250

    def test_batching_defaults_match_options(self):
        from karpenter_provider_aws_tpu.operator.options import Options
        o = Options()
        doc = _read("scheduling.md")
        assert f"default {o.batch_idle_duration:.0f} s" in doc
        assert f"default {o.batch_max_duration:.0f} s" in doc

    def test_max_flexible_types_matches(self):
        from karpenter_provider_aws_tpu.solver.solve import MAX_FLEXIBLE_TYPES
        assert f"**{MAX_FLEXIBLE_TYPES}** feasible types" in _read(
            "scheduling.md")

    def test_narrowing_constants_match(self):
        from karpenter_provider_aws_tpu.solver.problem import (
            _ACCEL_UNIT_PRICE_SLACK, _WAVE_GAIN, _WAVE_MIN_PODS,
            _WAVE_PRICE_SLACK,
        )
        doc = _read("scheduling.md")
        slack_pct = round((_ACCEL_UNIT_PRICE_SLACK - 1) * 100)
        assert f"within {slack_pct}% of the best **per-unit** price" in doc
        wave_pct = round((_WAVE_PRICE_SLACK - 1) * 100)
        assert f"within {wave_pct}% of the best" in doc
        assert f"≥{_WAVE_MIN_PODS} identical small pods" in doc
        gain_pct = round((1 - _WAVE_GAIN) * 100)
        assert f"≥{gain_pct}%" in doc
        from karpenter_provider_aws_tpu.solver.problem import _WAVE_MAX_BINS
        assert f"under {_WAVE_MAX_BINS} bins" in doc
        assert "global density floor" in doc

    def test_overhead_formula_matches(self):
        doc = _read("scheduling.md")
        # 11*maxPods + 255 Mi kube-reserved memory; 100 Mi eviction
        assert "11·maxPods + 255 Mi" in doc
        assert "100 Mi" in doc
        from karpenter_provider_aws_tpu.lattice.overhead import kube_reserved
        vec = kube_reserved(2000.0, 29)
        from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
        assert vec[RESOURCE_AXES.index("memory")] == 11 * 29 + 255

    def test_wellknown_labels_listed(self):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        doc = _read("scheduling.md")
        for label in (wk.LABEL_CAPACITY_TYPE, wk.LABEL_INSTANCE_CATEGORY,
                      wk.LABEL_INSTANCE_FAMILY, wk.LABEL_INSTANCE_CPU,
                      wk.LABEL_WINDOWS_BUILD):
            assert label in doc, label


class TestDisruptionDocFacts:
    def test_spec_depth(self):
        assert len(_lines("disruption.md")) >= 250

    def test_spot_guard_floor_matches(self):
        from karpenter_provider_aws_tpu.controllers.disruption import (
            SPOT_TO_SPOT_MIN_TYPES,
        )
        assert (f"≥{SPOT_TO_SPOT_MIN_TYPES} distinct feasible instance "
                "types" in _read("disruption.md"))

    def test_disruption_taint_matches(self):
        from karpenter_provider_aws_tpu.controllers.termination import (
            DISRUPTION_TAINT,
        )
        effect = getattr(DISRUPTION_TAINT.effect, "value",
                         DISRUPTION_TAINT.effect)
        want = f"{DISRUPTION_TAINT.key}={DISRUPTION_TAINT.value}:{effect}"
        assert want in _read("disruption.md")

    def test_do_not_disrupt_annotation_matches(self):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        assert wk.ANNOTATION_DO_NOT_DISRUPT in _read("disruption.md")

    def test_registration_ttl_matches(self):
        from karpenter_provider_aws_tpu.controllers.lifecycle import (
            REGISTRATION_TTL,
        )
        minutes = int(REGISTRATION_TTL // 60)
        assert f"{minutes}-minute registration TTL" in _read("disruption.md")

    def test_lease_timing_matches(self):
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            LEASE_DURATION, RETRY_PERIOD,
        )
        doc = _read("disruption.md")
        assert f"{LEASE_DURATION:.0f} s lease" in doc
        assert f"{RETRY_PERIOD:.0f} s" in doc

    def test_budget_rounding_is_up(self):
        """The doc's worked example (19 × 20% → 4) must match the
        implementation's ceil."""
        import numpy as np
        assert int(np.ceil(19 * 0.2)) == 4
        assert "round **up**" in _read("disruption.md")

    def test_method_order_stated(self):
        doc = _read("disruption.md")
        assert "expiration" in doc and "drift" in doc
        i = doc.find("expiration →")
        assert i >= 0 and "drift → emptiness → consolidation" in doc

    def test_cited_metric_names_exist(self):
        _assert_cited_metrics_exist("disruption.md")


class TestDegradationDocFacts:
    """docs/concepts/degradation.md pins the solve ladder — its rungs,
    wave budget, retry count, and metric names — to the code."""

    def test_ladder_rungs_and_cited_metrics(self):
        doc = _read("degradation.md")
        for rung in ("device solve", "wave-split", "host FFD"):
            assert rung in doc
        _assert_cited_metrics_exist("degradation.md")

    def test_wave_budget_and_retries_match(self):
        from karpenter_provider_aws_tpu.solver.solve import (Solver,
                                                             _G_BUCKETS)
        doc = _read("degradation.md")
        assert f"≤{Solver._WAVE_G_TARGET} groups per wave" in doc
        assert f"G ≤ {_G_BUCKETS[-1]}" in doc

    def test_reason_enum_matches_plan_contract(self):
        doc = _read("degradation.md")
        for reason in ("g-overflow", "b-exhausted", "device-error",
                       "internal-error"):
            assert reason in doc


class TestPerformanceDocFacts:
    """docs/concepts/performance.md pins the solve path's latency
    machinery — its budgets, buckets, TTLs, and memo invalidation
    story — to the constants that implement them."""

    def test_algo_budget_matches_bench(self):
        import bench
        assert f"**{bench.CFG5_ALGO_BUDGET_MS:.0f} ms** budget" in _read(
            "performance.md")

    def test_bucket_tables_match(self):
        from karpenter_provider_aws_tpu.solver.solve import (_B_BUCKETS,
                                                             _G_BUCKETS)
        doc = _read("performance.md")
        assert "G ∈ {" + ", ".join(str(g) for g in _G_BUCKETS) + "}" in doc
        assert "B ∈ {" + ", ".join(str(b) for b in _B_BUCKETS) + "}" in doc

    def test_ice_ttl_and_cleanup_cadence(self):
        from karpenter_provider_aws_tpu.cache.unavailable import (
            UNAVAILABLE_OFFERINGS_TTL,
        )
        from karpenter_provider_aws_tpu.operator.operator import (
            ICE_CLEANUP_INTERVAL,
        )
        doc = _read("performance.md")
        assert f"**{UNAVAILABLE_OFFERINGS_TTL:.0f} s**" in doc
        assert f"**{ICE_CLEANUP_INTERVAL:.0f} s** cleanup tick" in doc

    def test_density_floor_matches(self):
        from karpenter_provider_aws_tpu.solver.problem import _WAVE_MAX_BINS
        assert f"at most **{_WAVE_MAX_BINS}** bins" in _read("performance.md")

    def test_narrow_cache_bounds_match(self):
        from karpenter_provider_aws_tpu.solver.problem import (_NARROW_LATS,
                                                               _NARROW_MAX)
        assert (f"at most {_NARROW_LATS} lattices × {_NARROW_MAX} entries"
                in _read("performance.md"))

    def test_cited_symbols_exist(self):
        """Every code symbol the doc cites must exist where it says."""
        from karpenter_provider_aws_tpu.lattice.tensors import (
            masked_view_versioned,
        )
        from karpenter_provider_aws_tpu.solver.problem import _NARROW_CACHE
        from karpenter_provider_aws_tpu.solver.solve import Solver
        assert callable(masked_view_versioned)
        assert isinstance(_NARROW_CACHE, dict)
        assert hasattr(Solver, "start_profiling")

    def test_cited_metric_names_exist(self):
        _assert_cited_metrics_exist("performance.md")


class TestNodePoolsDocFacts:
    """docs/concepts/nodepools.md pins the weight order, hash contents,
    and version-migration story to the implementation."""

    def test_spec_depth(self):
        assert len(_lines("nodepools.md")) >= 100

    def test_hash_covers_startup_taints_and_skips_weight(self):
        from karpenter_provider_aws_tpu.apis.objects import NodePool, Taint
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            nodepool_hash)
        doc = _read("nodepools.md")
        assert "startupTaints" in doc
        p = NodePool(name="x")
        h = nodepool_hash(p)
        p.startup_taints = [Taint(key="k", value="v", effect="NoSchedule")]
        assert nodepool_hash(p) != h          # stamped fields hash
        p2 = NodePool(name="x", weight=99, limits={"cpu": 1})
        assert nodepool_hash(p2) == h         # solve-only fields don't

    def test_hash_version_symbol_cited(self):
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            NODEPOOL_HASH_VERSION,
        )
        assert "NODEPOOL_HASH_VERSION" in _read("nodepools.md")
        assert NODEPOOL_HASH_VERSION

    def test_weight_order_matches(self):
        # pools sort weight-descending, name-ascending (problem.py)
        import pathlib as _p
        from karpenter_provider_aws_tpu.solver import problem
        src = _p.Path(problem.__file__).read_text()
        assert "key=lambda p: (-p.weight, p.name)" in src
        assert "weight-descending, name-ascending" in _read("nodepools.md")


class TestNodeClassesDocFacts:
    """docs/concepts/nodeclasses.md pins the family set, reconcile
    cadence, and the role/instanceProfile exclusivity to the code."""

    def test_spec_depth(self):
        assert len(_lines("nodeclasses.md")) >= 90

    def test_family_enum_matches(self):
        from karpenter_provider_aws_tpu.providers.amifamily import (
            AMI_FAMILIES,
        )
        doc = _read("nodeclasses.md")
        for fam in AMI_FAMILIES:
            assert fam in doc, fam

    def test_reconcile_interval_matches(self):
        from karpenter_provider_aws_tpu.controllers.nodeclass import (
            RECONCILE_INTERVAL,
        )
        assert (f"`RECONCILE_INTERVAL = {RECONCILE_INTERVAL:.0f} s`"
                in _read("nodeclasses.md"))

    def test_role_xor_profile_rule_exists(self):
        from karpenter_provider_aws_tpu.apis.schema import (
            _rule_role_xor_profile,
        )
        assert _rule_role_xor_profile({"role": "r"})
        assert not _rule_role_xor_profile({"role": "r",
                                           "instanceProfile": "p"})
        assert "exactly one" in _read("nodeclasses.md")


class TestInterruptionDocFacts:
    """docs/concepts/interruption.md pins the queue semantics, schema
    strings, fanout width, and metric names to the implementation."""

    def test_spec_depth(self):
        assert len(_lines("interruption.md")) >= 90

    def test_schema_strings_match(self):
        import pathlib as _p
        from karpenter_provider_aws_tpu.interruption import messages
        src = _p.Path(messages.__file__).read_text()
        doc = _read("interruption.md")
        for dt in ("EC2 Spot Instance Interruption Warning",
                   "EC2 Instance Rebalance Recommendation",
                   "AWS Health Event",
                   "EC2 Instance State-change Notification"):
            assert dt in doc, dt
            assert dt in src, dt

    def test_queue_constants_match(self):
        from karpenter_provider_aws_tpu.interruption.controller import (
            InterruptionController,
        )
        from karpenter_provider_aws_tpu.interruption.queue import (
            WAIT_TIME_SECONDS,
        )
        doc = _read("interruption.md")
        assert f"`WAIT_TIME_SECONDS = {WAIT_TIME_SECONDS}`" in doc
        assert (f"`MESSAGE_WORKERS = "
                f"{InterruptionController.MESSAGE_WORKERS}` wide") in doc

    def test_cited_metric_names_exist(self):
        _assert_cited_metrics_exist("interruption.md")


class TestGettingStartedDocFacts:
    """docs/getting-started.md promises that every command it shows is
    the surface the cross-process e2e drives — so each cited flag,
    subcommand, route, and schema string must exist in the code."""

    def _doc(self):
        return _read("../getting-started.md")

    def test_cited_cli_flags_exist(self):
        import karpenter_provider_aws_tpu.cli as cli
        src = pathlib.Path(cli.__file__).read_text()
        doc = self._doc()
        for flag in ("--api-port", "--interruption-queue", "--metrics-port",
                     "--api-insecure", "--cluster-name", "--log-level",
                     "--api-tls-cert", "--api-tls-key", "--api-token-file"):
            assert flag in doc
            assert flag in src, flag

    def test_cert_paths_match_gen_certs(self):
        """The doc's TLS/token paths are what deploy/gen_certs.sh
        actually writes."""
        sh = (DOCS.parent.parent / "deploy" / "gen_certs.sh").read_text()
        doc = self._doc()
        for p in ("deploy/certs/tls.crt", "deploy/certs/tls.key",
                  "deploy/certs/token"):
            assert p in doc, p
            assert p.rsplit("/", 1)[-1] in sh, p

    def test_cited_kpctl_subcommands_exist(self):
        tools = DOCS.parent.parent / "tools" / "kpctl.py"
        src = tools.read_text()
        for sub in ("get", "apply", "delete", "watch"):
            assert f'"{sub}"' in src or f"'{sub}'" in src, sub
        assert "--token-file" in src and "--token-file" in self._doc()

    def test_queue_wire_route_exists(self):
        from karpenter_provider_aws_tpu.kube import httpserver
        src = pathlib.Path(httpserver.__file__).read_text()
        assert "/queue/messages" in self._doc()
        assert "/queue/messages" in src

    def test_interruption_schema_string_matches(self):
        from karpenter_provider_aws_tpu.interruption import messages
        src = pathlib.Path(messages.__file__).read_text()
        assert "EC2 Spot Instance Interruption Warning" in self._doc()
        assert "EC2 Spot Instance Interruption Warning" in src

    def test_batch_window_defaults_match(self):
        from karpenter_provider_aws_tpu.operator.options import Options
        o = Options()
        assert (f"default {o.batch_idle_duration:.0f} s idle / "
                f"{o.batch_max_duration:.0f} s max") in self._doc()

    def test_cited_kinds_are_real(self):
        from karpenter_provider_aws_tpu.kube.apiserver import KINDS
        doc = self._doc()
        for kind in ("nodepools", "pods", "nodeclaims"):
            # word-boundary: 'pods' must not ride along inside 'nodepools'
            assert re.search(rf"\b{kind}\b", doc), kind
            assert kind in KINDS, kind


class TestTroubleshootingDocFacts:
    """docs/troubleshooting.md (the reference's 698-line symptom guide)
    cites constants, event reasons, metrics, and flags — pin them all."""

    PKG = DOCS.parent.parent / "karpenter_provider_aws_tpu"

    def _doc(self):
        return re.sub(r"\s+", " ",
                      (DOCS.parent / "troubleshooting.md").read_text())

    def _pkg_src(self):
        if not hasattr(self, "_src_cache"):
            self._src_cache = "\n".join(
                p.read_text() for p in self.PKG.rglob("*.py"))
        return self._src_cache

    def test_spec_depth(self):
        lines = (DOCS.parent / "troubleshooting.md").read_text().splitlines()
        assert len(lines) >= 250

    def test_cited_event_reasons_are_published(self):
        """Every CamelCase reason the doc tells the user to grep for is
        actually published somewhere in the package."""
        src = self._pkg_src()
        for reason in ("FailedScheduling", "InsufficientCapacity",
                       "Launched", "Registered", "Initialized",
                       "LivenessFailure", "InstanceDisappeared",
                       "LeakedInstance", "DisruptionBlocked", "Cordoned",
                       "Drained", "Terminated", "InvalidConfig"):
            assert reason in self._doc(), reason
            assert f'"{reason}"' in src, reason

    def test_cited_metric_names_exist(self):
        src = (self.PKG / "metrics.py").read_text()
        for m in re.findall(r"karpenter_[a-z_]+", self._doc()):
            assert m in src, m

    def test_cited_constants_match(self):
        from karpenter_provider_aws_tpu.cache.unavailable import (
            UNAVAILABLE_OFFERINGS_TTL)
        from karpenter_provider_aws_tpu.controllers.disruption import (
            SPOT_TO_SPOT_MIN_TYPES)
        from karpenter_provider_aws_tpu.controllers.garbagecollection import (
            LEAK_GRACE_SECONDS)
        from karpenter_provider_aws_tpu.controllers.lifecycle import (
            REGISTRATION_TTL)
        from karpenter_provider_aws_tpu.events import MAX_EVENTS
        from karpenter_provider_aws_tpu.kube.eventsink import EVENTS_RETAINED
        doc = self._doc()
        assert f"{UNAVAILABLE_OFFERINGS_TTL:.0f} s" in doc
        assert f"≥15" not in doc or SPOT_TO_SPOT_MIN_TYPES == 15
        assert "≥15 candidate types" in doc
        assert f"older than {LEAK_GRACE_SECONDS:.0f} s" in doc
        assert f"{REGISTRATION_TTL:.0f} s" in doc
        assert f"newest {MAX_EVENTS}" in doc
        assert f"newest {EVENTS_RETAINED}" in doc

    def test_cited_cli_flags_exist(self):
        src = (self.PKG / "cli.py").read_text()
        for flag in re.findall(r"--[a-z][a-z-]+", self._doc()):
            if flag in ("--token", "--token-file", "--cacert",
                        "--insecure-skip-tls-verify"):   # kpctl's flags
                continue
            assert flag in src, flag

    def test_force_drain_message_matches(self):
        src = (self.PKG / "controllers" / "termination.py").read_text()
        assert "termination grace period expired" in self._doc()
        assert "termination grace period expired" in src

    def test_batch_window_defaults_match(self):
        from karpenter_provider_aws_tpu.operator.options import Options
        o = Options()
        doc = self._doc()
        assert f"default {o.batch_idle_duration:.0f} s" in doc
        assert f"{o.batch_max_duration:.0f} s" in doc

    def test_hash_version_symbol_exists(self):
        from karpenter_provider_aws_tpu.controllers import provisioning
        assert hasattr(provisioning, "NODEPOOL_HASH_VERSION")
        assert "NODEPOOL_HASH_VERSION" in self._doc()

    def test_status_resources_surface_exists(self):
        from karpenter_provider_aws_tpu.apis.objects import NodePool
        assert "statusResources" in self._doc()
        assert hasattr(NodePool(name="x"), "status_resources")


class TestFaqDocFacts:
    def _doc(self):
        return re.sub(r"\s+", " ", (DOCS.parent / "faq.md").read_text())

    def test_ami_family_count_matches(self):
        from karpenter_provider_aws_tpu.providers.amifamily import (
            AMI_FAMILIES)
        assert len(AMI_FAMILIES) == 6
        assert "Six AMI families" in self._doc()
        for fam in ("AL2023", "Bottlerocket", "Ubuntu", "Windows"):
            assert fam in self._doc(), fam

    def test_flexibility_threshold_matches(self):
        from karpenter_provider_aws_tpu.cloudprovider.cloudprovider import (
            FLEXIBILITY_THRESHOLD)
        assert f"≥{FLEXIBILITY_THRESHOLD}-type flexibility warning" in \
            self._doc()

    def test_cited_labels_exist(self):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        doc = self._doc()
        for label in ("karpenter.sh/nodepool", "karpenter.sh/capacity-type",
                      "kubernetes.io/arch", "kubernetes.io/os"):
            assert label in doc, label
        assert wk.LABEL_NODEPOOL == "karpenter.sh/nodepool"

    def test_catalog_has_graviton(self):
        """The FAQ promises arm64 Graviton types in the catalog."""
        import json
        import pathlib
        cat = json.loads(
            (DOCS.parent.parent / "karpenter_provider_aws_tpu" / "lattice" /
             "data" / "reference_catalog.json").read_text())
        assert any(t["name"].startswith("m6g.") for t in cat["types"])
        assert "m6g" in self._doc()

    def test_do_not_disrupt_matches(self):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        assert wk.ANNOTATION_DO_NOT_DISRUPT in self._doc()


class TestManagingAmisDocFacts:
    def _doc(self):
        return re.sub(r"\s+", " ",
                      (DOCS.parent / "tasks" / "managing-amis.md").read_text())

    def test_drift_reason_strings_exist(self):
        src = (DOCS.parent.parent / "karpenter_provider_aws_tpu" /
               "cloudprovider" / "cloudprovider.py").read_text()
        doc = self._doc()
        for reason in ("AMIDrift", "NodeClassDrift"):
            assert reason in doc, reason
            assert f'"{reason}"' in src, reason

    def test_budget_reason_literal_valid(self):
        """The YAML example's reasons entry must use a schema-valid
        enum value."""
        from karpenter_provider_aws_tpu.apis.schema import _BUDGET
        assert "Drifted" in _BUDGET["properties"]["reasons"]["items"]["enum"]
        assert "reasons: [Drifted]" in self._doc()

    def test_ami_ttl_matches(self):
        from karpenter_provider_aws_tpu.providers.amifamily import AMI_TTL
        assert f"{AMI_TTL:.0f} s" in self._doc()
        assert "AMI_TTL" in self._doc()

    def test_cited_metric_label_matches(self):
        assert 'reason="Drifted"' in self._doc()
        src = (DOCS.parent.parent / "karpenter_provider_aws_tpu" /
               "metrics.py").read_text()
        assert "karpenter_nodeclaims_disrupted_total" in src

    def test_selector_field_names_match_serde(self):
        src = (DOCS.parent.parent / "karpenter_provider_aws_tpu" / "apis" /
               "serde.py").read_text()
        for fld in ("amiSelectorTerms", "statusAMIs"):
            assert fld in self._doc(), fld
            assert fld in src, fld


class TestUpgradingDocFacts:
    def _doc(self):
        return re.sub(r"\s+", " ",
                      (DOCS.parent / "tasks" / "upgrading.md").read_text())

    def test_hash_versions_match_code(self):
        from karpenter_provider_aws_tpu.cloudprovider.cloudprovider import (
            NODECLASS_HASH_VERSION)
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            NODEPOOL_HASH_VERSION)
        doc = self._doc()
        assert f"currently `{NODEPOOL_HASH_VERSION}`" in doc
        assert f"currently `{NODECLASS_HASH_VERSION}`" in doc

    def test_lease_timings_match_code(self):
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            LEASE_DURATION, RETRY_PERIOD)
        doc = self._doc()
        assert f"{LEASE_DURATION:.0f} s lease" in doc
        assert f"{RETRY_PERIOD:.0f} s renew" in doc

    def test_kompat_usage_is_real(self):
        doc = self._doc()
        assert "tools/kompat.py check" in doc
        src = (DOCS.parent.parent / "tools" / "kompat.py").read_text()
        assert '"check"' in src or "'check'" in src

    def test_linked_pages_exist(self):
        for rel in ("../reference/compatibility.md", "managing-amis.md"):
            assert (DOCS.parent / "tasks" / rel).resolve().exists(), rel
