"""Metrics wire-format conformance (promtool-style lint).

Two layers:

- the linter itself (metrics.lint_exposition) catches each class of
  corruption the classic text format can suffer: HELP/TYPE pairing and
  ordering, unknown kinds, label escaping, duplicate series,
  non-contiguous family blocks, histogram bucket monotonicity, missing
  +Inf, +Inf/_count disagreement, missing _sum/_count;
- a LIVE scrape of a running operator's /metrics — exercised through
  real provisioning activity, with tracing exemplars attached — passes
  the lint clean, including the `# exemplar` comment lines staying
  scrape-safe.
"""

import urllib.request

import pytest

from karpenter_provider_aws_tpu import trace
from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.metrics import (Registry, lint_exposition,
                                                wire_core_metrics)
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


class TestLinter:
    def test_clean_document_passes(self):
        doc = "\n".join([
            "# HELP my_counter_total A counter.",
            "# TYPE my_counter_total counter",
            'my_counter_total{op="a"} 3.0',
            'my_counter_total{op="b"} 1.0',
            "# HELP my_hist A histogram.",
            "# TYPE my_hist histogram",
            'my_hist_bucket{le="0.1"} 1',
            'my_hist_bucket{le="1.0"} 3',
            'my_hist_bucket{le="+Inf"} 4',
            "my_hist_sum 2.5",
            "my_hist_count 4",
        ]) + "\n"
        assert lint_exposition(doc) == []

    def test_sample_without_type(self):
        assert any("no TYPE" in p
                   for p in lint_exposition("orphan_series 1.0\n"))

    def test_help_after_type_and_duplicates(self):
        doc = ("# TYPE m gauge\n"
               "# HELP m late help\n"
               "# TYPE m gauge\n"
               "m 1\n")
        probs = lint_exposition(doc)
        assert any("no preceding HELP" in p for p in probs)
        assert any("after its TYPE" in p for p in probs)
        assert any("duplicate TYPE" in p for p in probs)

    def test_unknown_kind(self):
        doc = "# HELP m x\n# TYPE m enum\nm 1\n"
        assert any("unknown kind" in p for p in lint_exposition(doc))

    def test_unescaped_label_value(self):
        doc = ("# HELP m x\n# TYPE m gauge\n"
               'm{l="a"b"} 1\n')
        assert any("malformed/unescaped" in p for p in lint_exposition(doc))

    def test_escaped_label_value_is_fine(self):
        doc = ("# HELP m x\n# TYPE m gauge\n"
               'm{l="a\\"b",m="c\\\\d"} 1\n')
        assert lint_exposition(doc) == []

    def test_duplicate_series(self):
        doc = ("# HELP m x\n# TYPE m gauge\n"
               'm{l="a"} 1\nm{l="a"} 2\n')
        assert any("duplicate series" in p for p in lint_exposition(doc))

    def test_non_contiguous_family_blocks(self):
        doc = ("# HELP a x\n# TYPE a gauge\n"
               "# HELP b x\n# TYPE b gauge\n"
               "a 1\nb 1\na 2\n")
        probs = lint_exposition(doc)
        assert any("not contiguous" in p for p in probs)

    def test_histogram_bucket_counts_decrease(self):
        doc = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="0.1"} 5\n'
               'h_bucket{le="1.0"} 3\n'
               'h_bucket{le="+Inf"} 5\n'
               "h_sum 1\nh_count 5\n")
        assert any("counts decrease" in p for p in lint_exposition(doc))

    def test_histogram_missing_inf(self):
        doc = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="0.1"} 1\n'
               "h_sum 1\nh_count 1\n")
        assert any("+Inf" in p for p in lint_exposition(doc))

    def test_histogram_inf_count_disagreement(self):
        doc = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="+Inf"} 4\n'
               "h_sum 1\nh_count 5\n")
        assert any("!= _count" in p for p in lint_exposition(doc))

    def test_histogram_missing_sum_count(self):
        doc = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="+Inf"} 4\n')
        probs = lint_exposition(doc)
        assert any("missing _sum" in p for p in probs)
        assert any("missing _count" in p for p in probs)

    def test_bare_histogram_sample(self):
        doc = ("# HELP h x\n# TYPE h histogram\n"
               "h 4\n"
               'h_bucket{le="+Inf"} 4\nh_sum 1\nh_count 4\n')
        assert any("bare sample" in p for p in lint_exposition(doc))

    def test_unparseable_value_and_line(self):
        doc = ("# HELP m x\n# TYPE m gauge\n"
               "m notanumber\n"
               "!!garbage!!\n")
        probs = lint_exposition(doc)
        assert any("unparseable value" in p for p in probs)
        assert any("unparseable sample" in p for p in probs)

    def test_comment_without_space_flagged(self):
        doc = "#HELPish something\n"
        assert any("scrape-safe" in p for p in lint_exposition(doc))

    def test_exemplar_comment_lines_are_scrape_safe(self):
        """The tracing exemplar rendering: a `# exemplar ...` line after
        +Inf is a comment, invisible to the lint's sample parser."""
        reg = Registry()
        m = wire_core_metrics(reg)
        m["solver_stage_duration"].observe(0.01, exemplar="deadbeef",
                                           stage="compute")
        text = reg.render()
        assert "# exemplar" in text
        assert lint_exposition(text) == []


class TestLiveScrape:
    def test_live_operator_scrape_is_clean(self, lattice):
        """promtool-style lint over a REAL /metrics scrape: operator with
        tracing on (exemplar comment lines included), pods provisioned,
        served over live HTTP."""
        from karpenter_provider_aws_tpu.cli import start_server
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        trace.enable()
        try:
            for i in range(5):
                op.cluster.add_pod(Pod(name=f"lint-{i}",
                                       requests={"cpu": "500m",
                                                 "memory": "1Gi"}))
            op.settle(max_rounds=20)
            server = start_server(op, 0)
            try:
                text = urllib.request.urlopen(
                    f"http://127.0.0.1:{server.server_address[1]}/metrics",
                    timeout=10).read().decode()
            finally:
                server.shutdown()
        finally:
            trace.disable()
        assert "karpenter_solver_stage_duration_seconds_bucket" in text
        assert "# exemplar" in text      # tracing attached one
        assert lint_exposition(text) == []

    def test_registry_render_always_lints_clean(self, lattice):
        """The renderer/linter pair is a standing contract: whatever the
        full wired registry renders must pass its own lint."""
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        for i in range(3):
            op.cluster.add_pod(Pod(name=f"rr-{i}",
                                   requests={"cpu": "250m",
                                             "memory": "512Mi"}))
        op.settle(max_rounds=20)
        assert lint_exposition(op.metrics.render()) == []
