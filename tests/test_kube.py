"""The Kubernetes-API-shaped ingest seam: protocol semantics.

Mirrors the contracts the reference's controllers rely on from the real
apiserver/client-go stack: resourceVersion optimistic concurrency, watch
event ordering + 410-Gone relists, finalizer-gated deletion, server-side
PDB enforcement on the eviction subresource, field indexers
(operator.go:180-186), and admission at the boundary
(pkg/webhooks/webhooks.go).
"""

import threading

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Pod, PodDisruptionBudget, Requirement,
)
from karpenter_provider_aws_tpu.apis import Operator as ReqOp
from karpenter_provider_aws_tpu.apis import serde
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import NodeClaim
from karpenter_provider_aws_tpu.kube import (
    ConflictError, EvictionBlockedError, FakeAPIServer, Informer,
    InformerSet, InvalidObjectError, KubeClient, NotFoundError,
    TERMINATION_FINALIZER, TooOldError, install_admission,
    install_default_indexes,
)
import karpenter_provider_aws_tpu.kube.apiserver as apiserver_mod


def pod(name, **kw):
    return Pod(name=name, requests={"cpu": "1", "memory": "1Gi"}, **kw)


class TestVerbs:
    def test_create_get_roundtrip(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("p0")))
        obj = s.get("pods", "p0")
        assert obj["metadata"]["name"] == "p0"
        assert obj["metadata"]["resourceVersion"] == 1
        assert serde.pod_from_dict(obj["spec"]).requests["cpu"] == "1"

    def test_create_duplicate_rejected(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("p0")))
        with pytest.raises(Exception, match="already exists"):
            s.create("pods", serde.pod_to_dict(pod("p0")))

    def test_resource_version_is_global_and_monotonic(self):
        s = FakeAPIServer()
        a = s.create("pods", serde.pod_to_dict(pod("a")))
        b = s.create("nodes", {"name": "n0"})
        c = s.patch("pods", "a", {"priority": 5})
        rvs = [a["metadata"]["resourceVersion"],
               b["metadata"]["resourceVersion"],
               c["metadata"]["resourceVersion"]]
        assert rvs == sorted(rvs) and len(set(rvs)) == 3

    def test_update_conflict_on_stale_rv(self):
        import copy
        s = FakeAPIServer()
        # read verbs hand out FROZEN shared envelopes (copy-on-read):
        # deepcopy thaws a private mutable copy for the CAS flow
        obj = copy.deepcopy(s.create("pods", serde.pod_to_dict(pod("p0"))))
        s.patch("pods", "p0", {"priority": 1})   # bumps RV behind our back
        obj["spec"]["priority"] = 2
        with pytest.raises(ConflictError):
            s.update("pods", obj)
        # refetch-and-retry succeeds (the client-go retry contract)
        fresh = copy.deepcopy(s.get("pods", "p0"))
        fresh["spec"]["priority"] = 2
        s.update("pods", fresh)
        assert s.get("pods", "p0")["spec"]["priority"] == 2

    def test_patch_merges_and_deletes_keys(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("p0", node_name="n0")))
        s.patch("pods", "p0", {"nodeName": None, "priority": 7})
        spec = s.get("pods", "p0")["spec"]
        assert "nodeName" not in spec
        assert spec["priority"] == 7

    def test_get_missing_raises(self):
        with pytest.raises(NotFoundError):
            FakeAPIServer().get("pods", "ghost")

    def test_list_returns_rv_high_water_mark(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("a")))
        s.create("nodes", {"name": "n0"})  # other-kind write bumps global RV
        items, rv = s.list("pods")
        assert len(items) == 1
        assert rv == 2


class TestFinalizers:
    def test_delete_with_finalizer_only_stamps_timestamp(self):
        s = FakeAPIServer()
        s.create("nodeclaims", {"name": "c0"}, finalizers=("fin",))
        s.delete("nodeclaims", "c0", now=42.0)
        obj = s.get("nodeclaims", "c0")
        assert obj["metadata"]["deletionTimestamp"] == 42.0
        # second delete is a no-op (timestamp not re-stamped)
        s.delete("nodeclaims", "c0", now=99.0)
        assert s.get("nodeclaims", "c0")["metadata"]["deletionTimestamp"] == 42.0

    def test_clearing_last_finalizer_removes_deleting_object(self):
        s = FakeAPIServer()
        s.create("nodeclaims", {"name": "c0"}, finalizers=("fin",))
        s.delete("nodeclaims", "c0", now=1.0)
        s.patch("nodeclaims", "c0", finalizers=())
        with pytest.raises(NotFoundError):
            s.get("nodeclaims", "c0")

    def test_clearing_finalizer_on_live_object_keeps_it(self):
        s = FakeAPIServer()
        s.create("nodeclaims", {"name": "c0"}, finalizers=("fin",))
        s.patch("nodeclaims", "c0", finalizers=())
        assert s.get("nodeclaims", "c0")["metadata"]["finalizers"] == []

    def test_force_delete_bypasses_finalizer(self):
        s = FakeAPIServer()
        s.create("nodeclaims", {"name": "c0"}, finalizers=("fin",))
        s.delete("nodeclaims", "c0", force=True)
        with pytest.raises(NotFoundError):
            s.get("nodeclaims", "c0")


class TestWatch:
    def test_events_arrive_in_rv_order(self):
        s = FakeAPIServer()
        w = s.watch("pods")
        s.create("pods", serde.pod_to_dict(pod("a")))
        s.patch("pods", "a", {"priority": 1})
        s.delete("pods", "a")
        evs = w.pop_pending()
        assert [e.type for e in evs] == ["ADDED", "MODIFIED", "DELETED"]
        rvs = [e.resource_version for e in evs]
        assert rvs == sorted(rvs)

    def test_watch_from_rv_replays_only_later_events(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("a")))
        _, rv = s.list("pods")
        s.create("pods", serde.pod_to_dict(pod("b")))
        w = s.watch("pods", resource_version=rv)
        evs = w.pop_pending()
        assert len(evs) == 1
        assert evs[0].object["metadata"]["name"] == "b"

    def test_watch_too_old_raises_gone(self):
        s = FakeAPIServer()
        old_max = apiserver_mod.EVENT_HISTORY
        s._history["pods"] = __import__("collections").deque(maxlen=4)
        for i in range(8):
            s.create("pods", serde.pod_to_dict(pod(f"p{i}")))
        with pytest.raises(TooOldError):
            s.watch("pods", resource_version=1)
        assert old_max == apiserver_mod.EVENT_HISTORY  # module constant untouched

    def test_blocking_get_wakes_on_event(self):
        s = FakeAPIServer()
        w = s.watch("pods")
        got = []

        def reader():
            got.append(w.get(timeout=5.0))

        t = threading.Thread(target=reader)
        t.start()
        s.create("pods", serde.pod_to_dict(pod("a")))
        t.join(5.0)
        assert got and got[0].type == "ADDED"


class TestSubresources:
    def test_bind_sets_node_name_once(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("p0")))
        s.bind("p0", "n0")
        assert s.get("pods", "p0")["spec"]["nodeName"] == "n0"
        with pytest.raises(ConflictError):
            s.bind("p0", "n1")

    def test_evict_unbinds(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("p0", node_name="n0")))
        s.evict("p0")
        assert s.get("pods", "p0")["spec"].get("nodeName") is None

    def test_evict_blocked_by_pdb(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(
            pod("p0", node_name="n0", labels={"app": "db"})))
        s.create("pdbs", serde.pdb_to_dict(PodDisruptionBudget(
            name="db-pdb", label_selector={"app": "db"}, min_available=1)))
        with pytest.raises(EvictionBlockedError):
            s.evict("p0")
        # a second healthy replica restores the allowance
        s.create("pods", serde.pod_to_dict(
            pod("p1", node_name="n1", labels={"app": "db"})))
        s.evict("p0")
        assert s.get("pods", "p0")["spec"].get("nodeName") is None

    def test_force_evict_bypasses_pdb(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(
            pod("p0", node_name="n0", labels={"app": "db"})))
        s.create("pdbs", serde.pdb_to_dict(PodDisruptionBudget(
            name="db-pdb", label_selector={"app": "db"}, min_available=1)))
        s.evict("p0", force=True)
        assert s.get("pods", "p0")["spec"].get("nodeName") is None

    def test_sequential_evictions_decrement_allowance(self):
        s = FakeAPIServer()
        for i in range(3):
            s.create("pods", serde.pod_to_dict(
                pod(f"p{i}", node_name=f"n{i}", labels={"app": "web"})))
        s.create("pdbs", serde.pdb_to_dict(PodDisruptionBudget(
            name="web-pdb", label_selector={"app": "web"}, min_available=2)))
        s.evict("p0")
        with pytest.raises(EvictionBlockedError):
            s.evict("p1")


class TestIndexes:
    def test_provider_id_index(self):
        s = FakeAPIServer()
        install_default_indexes(s)
        c = NodeClaim(name="c0", node_pool="default",
                      provider_id="aws:///us-west-2a/i-0abc")
        KubeClient(s).create_nodeclaim(c)
        hits = KubeClient(s).claims_by_provider_id("aws:///us-west-2a/i-0abc")
        assert [h.name for h in hits] == ["c0"]
        assert KubeClient(s).claims_by_provider_id("aws:///zz/i-none") == []


class TestAdmission:
    def test_invalid_nodepool_rejected_at_boundary(self):
        s = FakeAPIServer()
        install_admission(s)
        c = KubeClient(s)
        bad = NodePool(name="bad", requirements=[
            Requirement(wk.LABEL_OS, ReqOp.IN, ("linux", "windows"))])
        with pytest.raises(InvalidObjectError, match="os"):
            c.create_nodepool(bad)

    def test_defaults_applied_on_create(self):
        s = FakeAPIServer()
        install_admission(s)
        c = KubeClient(s)
        c.create_nodepool(NodePool(name="plain"))
        stored = c.list_nodepools()[0]
        keys = {r.key for r in stored.requirements}
        assert wk.LABEL_CAPACITY_TYPE in keys and wk.LABEL_ARCH in keys

    def test_invalid_pdb_rejected(self):
        s = FakeAPIServer()
        install_admission(s)
        with pytest.raises(InvalidObjectError):
            KubeClient(s).create_pdb(PodDisruptionBudget(
                name="both", min_available=1, max_unavailable=1))


class TestInformer:
    def test_initial_list_then_watch(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("a")))
        seen = []
        inf = Informer(s, "pods",
                       lambda t, n, o, old: seen.append((t, n)))
        inf.sync_once()
        assert inf.has_synced
        assert seen == [("ADDED", "a")]
        s.create("pods", serde.pod_to_dict(pod("b")))
        s.patch("pods", "a", {"priority": 3})
        inf.sync_once()
        assert seen == [("ADDED", "a"), ("ADDED", "b"), ("MODIFIED", "a")]
        assert set(inf.store) == {"a", "b"}

    def test_delete_reaches_store_and_handler(self):
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("a")))
        seen = []
        inf = Informer(s, "pods", lambda t, n, o, old: seen.append((t, n)))
        inf.sync_once()
        s.delete("pods", "a")
        inf.sync_once()
        assert ("DELETED", "a") in seen
        assert inf.store == {}

    def test_relist_after_gone_synthesizes_delta(self):
        """A reflector whose watch fell off the history ring must relist
        and reconcile its store, synthesizing handler events for exactly
        the delta (client-go reflector recovery)."""
        import collections
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(pod("a")))
        s.create("pods", serde.pod_to_dict(pod("stale")))
        seen = []
        inf = Informer(s, "pods", lambda t, n, o, old: seen.append((t, n)))
        inf.sync_once()
        assert set(inf.store) == {"a", "stale"}
        seen.clear()
        # the informer's connection "breaks"; many events fall off a tiny
        # ring while it is away
        s.stop_watch(inf._watch)
        s._history["pods"] = collections.deque(maxlen=2)
        s.delete("pods", "stale")
        s.create("pods", serde.pod_to_dict(pod("c")))
        s.create("pods", serde.pod_to_dict(pod("d")))
        s.patch("pods", "a", {"priority": 9})
        # resuming the watch from the informer's old RV is 410 Gone...
        with pytest.raises(TooOldError):
            s.watch("pods", resource_version=inf._rv)
        # ...so the reflector relists: store replaced, delta synthesized
        inf._relist()
        assert set(inf.store) == {"a", "c", "d"}
        assert ("DELETED", "stale") in seen
        assert ("ADDED", "c") in seen and ("ADDED", "d") in seen
        assert ("MODIFIED", "a") in seen

    def test_threaded_informer_converges(self):
        s = FakeAPIServer()
        inf = Informer(s, "pods").start()
        try:
            for i in range(5):
                s.create("pods", serde.pod_to_dict(pod(f"p{i}")))
            import time
            deadline = time.time() + 5.0
            while time.time() < deadline and len(inf.store) < 5:
                time.sleep(0.01)
            assert len(inf.store) == 5
        finally:
            inf.stop()

    def test_informer_set_pumps_in_order(self):
        s = FakeAPIServer()
        seen = []
        iset = InformerSet(s)
        iset.add("nodepools", lambda t, n, o, old: seen.append(("pool", n)))
        iset.add("pods", lambda t, n, o, old: seen.append(("pod", n)))
        s.create("pods", serde.pod_to_dict(pod("p")))
        s.create("nodepools", serde.nodepool_to_dict(NodePool(name="np")))
        iset.sync_once()
        assert seen == [("pool", "np"), ("pod", "p")]


class TestClientRoundTrips:
    def test_nodeclaim_finalizer_flow_via_client(self):
        s = FakeAPIServer()
        c = KubeClient(s)
        c.create_nodeclaim(NodeClaim(name="c0", node_pool="default"))
        obj = s.get("nodeclaims", "c0")
        assert obj["metadata"]["finalizers"] == [TERMINATION_FINALIZER]
        c.delete_nodeclaim("c0", now=10.0)
        got = c.get_nodeclaim("c0")
        assert got.deletion_timestamp == 10.0
        c.remove_nodeclaim_finalizer("c0")
        with pytest.raises(NotFoundError):
            c.get_nodeclaim("c0")

    def test_node_taint_helper_is_idempotent(self):
        from karpenter_provider_aws_tpu.apis.objects import Node
        from karpenter_provider_aws_tpu.controllers.termination import (
            DISRUPTION_TAINT,
        )
        s = FakeAPIServer()
        c = KubeClient(s)
        c.create_node(Node(name="n0", provider_id="aws:///z/i-1"))
        assert c.taint_node("n0", DISRUPTION_TAINT) is True
        assert c.taint_node("n0", DISRUPTION_TAINT) is False
        assert len(c.get_node("n0").taints) == 1


class TestReviewRegressions:
    def test_status_update_cannot_resurrect_deleting_claim(self):
        """update_nodeclaim patches ONLY caller-owned status fields: a
        stale typed claim (deletion_timestamp None) written back during a
        concurrent delete must not clear the server's deletionTimestamp
        or any other lifecycle metadata (advisor r4)."""
        s = FakeAPIServer()
        c = KubeClient(s)
        claim = NodeClaim(name="c0", node_pool="default")
        c.create_nodeclaim(claim)
        c.delete_nodeclaim("c0", now=10.0)      # finalizer holds it
        # stale typed copy: no deletion stamp, new phase
        from karpenter_provider_aws_tpu.apis.objects import NodeClaimPhase
        claim.phase = NodeClaimPhase.LAUNCHED
        claim.provider_id = "aws:///z/i-1"
        c.update_nodeclaim(claim)
        got = c.get_nodeclaim("c0")
        assert got.deletion_timestamp == 10.0   # survives the status write
        assert got.phase == NodeClaimPhase.LAUNCHED
        assert got.provider_id == "aws:///z/i-1"
        obj = s.get("nodeclaims", "c0")
        assert obj["metadata"]["finalizers"]    # finalizers untouched

    def test_status_update_persists_annotations_with_per_key_merge(self):
        """Launch stamps drift-hash annotations on the claim; the status
        write must persist them (review r5: dropping them breaks
        NodeClassDrift in API mode), and the server's RFC 7386 merge
        must keep OTHER controllers' annotation keys intact."""
        s = FakeAPIServer()
        c = KubeClient(s)
        claim = NodeClaim(name="c2", node_pool="default")
        c.create_nodeclaim(claim)
        # another controller's annotation key lands first (tagging)
        s.patch("nodeclaims", "c2",
                {"annotations": {"karpenter.k8s.aws/tagged": "true"}})
        claim.annotations["karpenter.k8s.aws/nodeclass-hash"] = "abc123"
        c.update_nodeclaim(claim)
        got = s.get("nodeclaims", "c2")["spec"]["annotations"]
        assert got["karpenter.k8s.aws/nodeclass-hash"] == "abc123"
        assert got["karpenter.k8s.aws/tagged"] == "true"   # not clobbered

    def test_status_update_does_not_regress_spec_fields(self):
        """A status write from a holder of a STALE spec leaves the
        server's spec fields (requirements/nodePool/taints) alone."""
        s = FakeAPIServer()
        c = KubeClient(s)
        claim = NodeClaim(name="c1", node_pool="default")
        c.create_nodeclaim(claim)
        s.patch("nodeclaims", "c1", {"nodePool": "gpu"})
        c.update_nodeclaim(claim)               # stale nodePool="default"
        assert s.get("nodeclaims", "c1")["spec"]["nodePool"] == "gpu"

    def test_raced_bind_reports_false_and_is_not_counted(self):
        """ApiWriter.bind_pod returns False when the pod vanished; True
        on success (advisor r4: pods_scheduled overcount)."""
        from karpenter_provider_aws_tpu.apis.objects import Node
        from karpenter_provider_aws_tpu.kube.writer import ApiWriter
        from karpenter_provider_aws_tpu.state.cluster import ClusterState
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        s = FakeAPIServer()
        c = KubeClient(s)
        cluster = ClusterState(clock=FakeClock())
        w = ApiWriter(c, cluster, FakeClock())
        c.create_node(Node(name="n0", provider_id="aws:///z/i-1"))
        s.create("pods", serde.pod_to_dict(pod("p0")))
        assert w.bind_pod("p0", "n0") is True
        assert w.bind_pod("vanished", "n0") is False

    def test_default_delete_timestamp_is_truthy(self):
        """delete() without an explicit time must never stamp a falsy
        deletionTimestamp — every consumer truth-tests it."""
        s = FakeAPIServer()
        c = KubeClient(s)
        c.create_nodeclaim(NodeClaim(name="c0", node_pool="default"))
        c.delete_nodeclaim("c0")  # no now= given
        assert c.get_nodeclaim("c0").deletion_timestamp  # truthy
        # a FakeClock at t=0 still yields a truthy stamp
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        s2 = FakeAPIServer(clock=FakeClock())
        c2 = KubeClient(s2)
        c2.create_nodeclaim(NodeClaim(name="c0", node_pool="default"))
        c2.delete_nodeclaim("c0")
        assert c2.get_nodeclaim("c0").deletion_timestamp

    def test_pdb_healthy_excludes_spec_deleting_pods(self):
        """A bound pod marked deleting at the SPEC level is not healthy:
        the eviction budget must block evicting its sibling."""
        s = FakeAPIServer()
        s.create("pods", serde.pod_to_dict(
            pod("p0", node_name="n0", labels={"app": "db"})))
        s.create("pods", serde.pod_to_dict(
            pod("p1", node_name="n1", labels={"app": "db"},
                deletion_timestamp=5.0)))
        s.create("pdbs", serde.pdb_to_dict(PodDisruptionBudget(
            name="db-pdb", label_selector={"app": "db"}, min_available=1)))
        with pytest.raises(EvictionBlockedError):
            s.evict("p0")

    def test_index_lookup_overlays_deletion_timestamp(self):
        """claims_by_provider_id must see the API-level deletion stamp
        like get/list do — a terminating claim must not look live."""
        s = FakeAPIServer()
        install_default_indexes(s)
        c = KubeClient(s)
        c.create_nodeclaim(NodeClaim(name="c0", node_pool="default",
                                     provider_id="aws:///z/i-9"))
        c.delete_nodeclaim("c0", now=7.0)
        hits = c.claims_by_provider_id("aws:///z/i-9")
        assert hits and hits[0].deletion_timestamp == 7.0

    def test_watch_subscribers_are_isolated(self):
        """A handler cannot corrupt sibling watchers or the history
        replay: delivered envelopes are FROZEN shared objects, so the
        mutation that used to rely on per-watcher deepcopy isolation
        now raises outright — structural isolation, zero copies."""
        s = FakeAPIServer()
        w1 = s.watch("pods")
        w2 = s.watch("pods")
        s.create("pods", serde.pod_to_dict(pod("a")))
        ev1 = w1.pop_pending()[0]
        with pytest.raises(TypeError):
            ev1.object["spec"]["name"] = "CORRUPTED"
        assert w2.pop_pending()[0].object["spec"]["name"] == "a"
        w3 = s.watch("pods", resource_version=0)  # replays from history
        assert w3.pop_pending()[0].object["spec"]["name"] == "a"
        # a handler that NEEDS a mutable view thaws its own copy
        import copy
        mine = copy.deepcopy(ev1.object)
        mine["spec"]["name"] = "mine"
        assert s.get("pods", "a")["spec"]["name"] == "a"


class TestEventSink:
    """Recorder → apiserver events mirror (kube/eventsink.py)."""

    def _recorder(self, server, retained=None):
        from karpenter_provider_aws_tpu.events import Recorder
        from karpenter_provider_aws_tpu.kube.eventsink import ApiEventSink
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        r = Recorder(FakeClock(100.0))
        r.sink = (ApiEventSink(server) if retained is None
                  else ApiEventSink(server, retained=retained))
        return r

    def test_publish_mirrors_into_apiserver_in_order(self):
        s = FakeAPIServer()
        r = self._recorder(s)
        r.publish("Normal", "Launched", "NodeClaim", "c0", "type=m5.large")
        r.publish("Warning", "LaunchFailed", "NodeClaim", "c1", "ICE")
        objs, _ = s.list("events")
        assert [o["spec"]["reason"] for o in objs] == [
            "Launched", "LaunchFailed"]
        assert objs[0]["spec"]["objectKind"] == "NodeClaim"
        assert objs[0]["spec"]["objectName"] == "c0"
        assert objs[0]["spec"]["time"] == 100.0
        # the in-memory ring still serves reads (direct-stratum surface)
        assert len(r.events()) == 2

    def test_retention_cap_ages_out_oldest(self):
        s = FakeAPIServer()
        r = self._recorder(s, retained=3)
        for i in range(7):
            r.publish("Normal", "R", "Pod", f"p{i}", "")
        objs, _ = s.list("events")
        assert len(objs) == 3
        assert [o["spec"]["objectName"] for o in objs] == ["p4", "p5", "p6"]

    def test_sink_failure_never_breaks_publish(self):
        from karpenter_provider_aws_tpu.events import Recorder
        r = Recorder()
        calls = []

        def bad_sink(ev):
            calls.append(ev)
            raise RuntimeError("apiserver down")

        r.sink = bad_sink
        r.publish("Normal", "Launched", "NodeClaim", "c0", "")
        assert calls and len(r.events()) == 1

    def test_restart_skips_past_existing_names(self):
        """A second sink against a pre-populated server (operator
        restart) keeps appending instead of failing on name collisions."""
        s = FakeAPIServer()
        r1 = self._recorder(s)
        r1.publish("Normal", "A", "Pod", "p0", "")
        r2 = self._recorder(s)   # fresh counter, same server
        r2.publish("Normal", "B", "Pod", "p1", "")
        objs, _ = s.list("events")
        assert [o["spec"]["reason"] for o in objs] == ["A", "B"]

    def test_events_kind_is_watchable(self):
        s = FakeAPIServer()
        w = s.watch("events", resource_version=0)
        r = self._recorder(s)
        r.publish("Warning", "DisruptionBlocked", "NodeClaim", "c0", "budget")
        evs = w.pop_pending()
        assert evs and evs[0].type == "ADDED"
        assert evs[0].object["spec"]["reason"] == "DisruptionBlocked"

    def test_retention_adopts_preexisting_events_on_restart(self):
        """A fresh sink (operator restart) counts the prior run's events
        against the cap instead of letting them live forever."""
        s = FakeAPIServer()
        r1 = self._recorder(s, retained=4)
        for i in range(3):
            r1.publish("Normal", "Old", "Pod", f"o{i}", "")
        r2 = self._recorder(s, retained=4)   # adopts the 3 above
        for i in range(3):
            r2.publish("Normal", "New", "Pod", f"n{i}", "")
        objs, _ = s.list("events")
        assert len(objs) == 4
        names = [o["spec"]["objectName"] for o in objs]
        assert names == ["o2", "n0", "n1", "n2"], names

    def test_adoption_orders_numerically_past_six_digits(self):
        """Restart adoption must order ev-1000000 AFTER ev-999999 and
        resume the counter past the numeric max (review r5)."""
        from karpenter_provider_aws_tpu.kube.eventsink import ApiEventSink
        s = FakeAPIServer()
        for n in ("ev-999999", "ev-1000000", "ev-1000001"):
            s.create("events", {"name": n, "reason": "Old",
                                "objectName": n, "type": "Normal",
                                "objectKind": "Pod", "message": "",
                                "time": 0.0})
        sink = ApiEventSink(s, retained=3)
        from karpenter_provider_aws_tpu.events import Recorder
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        r = Recorder(FakeClock(1.0))
        r.sink = sink
        r.publish("Normal", "New", "Pod", "fresh", "")
        objs, _ = s.list("events")
        names = sorted(o["metadata"]["name"] for o in objs)
        # oldest (ev-999999) aged out; the new event took 1000002
        assert "ev-999999" not in names
        assert "ev-1000002" in names, names
        assert len(objs) == 3
