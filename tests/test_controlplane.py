"""Control-plane shell tests: caches, batcher, fake cloud, cloudprovider
boundary, and the full provision → launch → register → bind loop.

Mirrors the reference's stratum 1-2 strategy (SURVEY.md §4): the real
provisioner + solver run in-process over the fake cloud with a FakeClock,
with strict state reset between tests (reference pkg/test/environment.go
Reset / pkg/fake/ec2api.go Reset).
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOperator, Pod, Requirement
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import NodeClaimPhase, NodeClass
from karpenter_provider_aws_tpu.batcher import Batcher, BatcherOptions
from karpenter_provider_aws_tpu.cache import TTLCache, UnavailableOfferings
from karpenter_provider_aws_tpu.cloud import FakeCloud, LaunchOverride
from karpenter_provider_aws_tpu.cloudprovider import nodeclass_hash
from karpenter_provider_aws_tpu.errors import NotFoundError, UnfulfillableCapacityError
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture()
def env(lattice):
    clock = FakeClock()
    op = Operator(options=Options(registration_delay=2.0), lattice=lattice,
                  cloud=FakeCloud(clock), clock=clock)
    return op


def pods(n, cpu="500m", mem="1Gi", prefix="pod", **kw):
    return [Pod(name=f"{prefix}-{i}", requests={"cpu": cpu, "memory": mem}, **kw)
            for i in range(n)]


class TestTTLCache:
    def test_expiry_and_eviction_hook(self):
        clock = FakeClock()
        evicted = []
        c = TTLCache(ttl=10.0, clock=clock, on_evict=lambda k, v: evicted.append(k))
        c.set("a", 1)
        assert c.get("a") == 1
        clock.step(11)
        assert c.get("a") is None
        assert len(c) == 0

    def test_cleanup_counts(self):
        clock = FakeClock()
        c = TTLCache(ttl=5.0, clock=clock)
        c.set("a", 1)
        c.set("b", 2, ttl=100.0)
        clock.step(6)
        assert c.cleanup() == 1
        assert c.get("b") == 2


class TestUnavailableOfferings:
    def test_mask_and_ttl(self, lattice):
        clock = FakeClock()
        u = UnavailableOfferings(clock)
        t = lattice.names[0]
        z = lattice.zones[0]
        seq0 = u.seq_num
        u.mark_unavailable("ice", "on-demand", t, z)
        assert u.is_unavailable("on-demand", t, z)
        assert u.seq_num > seq0
        m = u.mask(lattice)
        ti, zi = lattice.name_to_idx[t], 0
        ci = lattice.capacity_types.index("on-demand")
        assert not m[ti, zi, ci]
        assert m.sum() == m.size - 1
        clock.step(200)  # 3-minute TTL expired
        assert not u.is_unavailable("on-demand", t, z)
        assert u.mask(lattice).all()


class TestBatcher:
    def test_coalesces_concurrent_requests(self):
        import threading
        calls = []

        def batch_fn(reqs):
            calls.append(list(reqs))
            return [r * 2 for r in reqs]

        b = Batcher(batch_fn, BatcherOptions(idle_seconds=0.05, max_seconds=1.0))
        results = {}

        def worker(i):
            results[i] = b.add(i)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i * 2 for i in range(8)}
        assert len(calls) == 1, f"expected one fused call, got {calls}"

    def test_per_request_errors(self):
        def batch_fn(reqs):
            return [ValueError("boom") if r == 1 else r for r in reqs]

        b = Batcher(batch_fn, BatcherOptions(idle_seconds=0.01))
        assert b.add(0) == 0
        with pytest.raises(ValueError):
            b.add(1)


class TestFakeCloud:
    def test_fleet_picks_cheapest_available(self):
        cloud = FakeCloud(FakeClock())
        o1 = LaunchOverride("m5.large", "us-west-2a", "on-demand", 0.10)
        o2 = LaunchOverride("c5.large", "us-west-2a", "on-demand", 0.08)
        inst = cloud.create_fleet([o1, o2]).instance
        assert inst.instance_type == "c5.large"

    def test_ice_pool_exhaustion_and_release(self):
        cloud = FakeCloud(FakeClock())
        cloud.set_capacity("on-demand", "m5.large", "us-west-2a", 1)
        o = LaunchOverride("m5.large", "us-west-2a", "on-demand", 0.10)
        inst = cloud.create_fleet([o]).instance
        with pytest.raises(UnfulfillableCapacityError) as ei:
            cloud.create_fleet([o])
        assert ("on-demand", "m5.large", "us-west-2a") in ei.value.offerings
        cloud.terminate_instances([inst.id])  # capacity returns
        assert cloud.create_fleet([o]).instance.instance_type == "m5.large"

    def test_error_injection_fires_once(self):
        cloud = FakeCloud(FakeClock())
        cloud.inject_error(RuntimeError("api down"))
        with pytest.raises(RuntimeError):
            cloud.list_instances()
        assert cloud.list_instances() == []


class TestCloudProviderBoundary:
    def test_create_populates_claim(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        assert claim.phase == NodeClaimPhase.LAUNCHED
        assert claim.provider_id and claim.instance_type
        assert claim.capacity["cpu"] > 0 and claim.allocatable["cpu"] > 0
        assert claim.labels[wk.LABEL_INSTANCE_TYPE] == claim.instance_type
        assert claim.labels[wk.LABEL_NODEPOOL] == "default"
        assert wk.ANNOTATION_NODECLASS_HASH in claim.annotations

    def test_spot_preferred_when_allowed(self, env, lattice):
        pool = NodePool(name="spotty", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOperator.IN, ("spot", "on-demand"))])
        env.node_pools["spotty"] = pool
        del env.node_pools["default"]
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        assert claim.capacity_type == "spot"

    def test_ice_feedback_relaunches_elsewhere(self, env, lattice):
        """The launch ICE path: offering exhausted → marked unavailable →
        the SAME claim launch falls through to the next-cheapest override."""
        p = pods(1, cpu="1800m", mem="7Gi")[0]
        env.cluster.add_pod(p)
        # dry-run solve to find the would-be choice, then exhaust it
        probe = env.provisioner.provision_once()
        choice = probe.plan.new_nodes[0]
        (claim,) = env.cluster.claims.values()
        assert claim.instance_type == choice.instance_type
        # now exhaust that pool and force a second pod through the same path
        env.cloud.set_capacity(choice.capacity_type, choice.instance_type, choice.zone, 0)
        p2 = pods(1, cpu="1800m", mem="7Gi", prefix="again")[0]
        env.cluster.add_pod(p2)
        r2 = env.provisioner.provision_once()
        assert r2.launched == 1
        claims = list(env.cluster.claims.values())
        launched2 = [c for c in claims if c.name != claim.name]
        assert launched2, "second claim should have launched on an alternative offering"
        alt = launched2[0]
        assert (alt.instance_type, alt.zone) != (choice.instance_type, choice.zone)

    def test_is_drifted_on_nodeclass_change(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        assert env.cloud_provider.is_drifted(claim) is None
        env.node_classes["default"].user_data = "#!/bin/bash echo changed"
        assert env.cloud_provider.is_drifted(claim) == "NodeClassDrift"

    def test_ami_drift_when_default_ami_rolls(self, env):
        """Live drift (reference drift.go:73-96): the SSM default AMI moves
        to a new image; after the NodeClass re-resolves, nodes launched from
        the old image report AMIDrift."""
        env.cluster.add_pod(pods(1)[0])
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.image_id, "launch should record the AMI"
        assert env.cloud_provider.is_drifted(claim) is None
        # roll every SSM alias to a fresh image (new default AMI release)
        net = env.cloud.network
        for path, iid in list(net.ssm_parameters.items()):
            img = net.images[iid]
            nid = f"{iid}-v2"
            from karpenter_provider_aws_tpu.cloud.network import Image
            net.images[nid] = Image(id=nid, name=img.name + "-v2", arch=img.arch,
                                    creation_date=img.creation_date + 1)
            net.ssm_parameters[path] = nid
        env.ami_provider._cache.flush()
        env.clock.step(400)
        env.nodeclass_controller.reconcile()
        assert env.cloud_provider.is_drifted(claim) == "AMIDrift"

    def test_subnet_drift(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.settle()
        (claim,) = env.cluster.claims.values()
        nc = env.node_classes[claim.node_class_ref]
        assert env.cloud_provider.is_drifted(claim) is None
        nc.status_subnets = [{"id": "subnet-9999", "zone": "us-west-2a"}]
        assert env.cloud_provider.is_drifted(claim) == "SubnetDrift"

    def test_security_group_drift(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.settle()
        (claim,) = env.cluster.claims.values()
        nc = env.node_classes[claim.node_class_ref]
        assert env.cloud_provider.is_drifted(claim) is None
        nc.status_security_groups = [{"id": "sg-9999", "name": "other"}]
        assert env.cloud_provider.is_drifted(claim) == "SecurityGroupDrift"

    def test_drift_on_missing_instance(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        env.cloud.terminate_instances([parse_instance_id(claim.provider_id)])
        assert env.cloud_provider.is_drifted(claim) == "InstanceDrift"

    def test_exotic_types_filtered_for_generic_pods(self, lattice):
        clock = FakeClock()
        full = build_lattice([s for s in build_catalog()
                              if s.family in ("m5", "g5", "p4d")])
        op = Operator(lattice=full, cloud=FakeCloud(clock), clock=clock)
        op.cluster.add_pod(pods(1)[0])
        op.provisioner.provision_once()
        (claim,) = op.cluster.claims.values()
        spec = full.specs[full.name_to_idx[claim.instance_type]]
        assert spec.gpu_count == 0


class TestEndToEnd:
    def test_provision_register_bind(self, env):
        for p in pods(20):
            env.cluster.add_pod(p)
        rounds = env.settle()
        assert rounds < 50
        assert not env.cluster.pending_pods()
        bound = [p for p in env.cluster.pods.values() if p.node_name]
        assert len(bound) == 20
        assert all(c.phase == NodeClaimPhase.INITIALIZED
                   for c in env.cluster.claims.values())
        # every node's instance exists in the cloud
        for node in env.cluster.nodes.values():
            assert env.cloud_provider.get(node.provider_id)

    def test_batch_window_idle_then_fire(self, env):
        env.cluster.add_pod(pods(1)[0])
        assert not env.provisioner.batch_ready()  # window opens
        env.clock.step(0.5)
        env.cluster.add_pod(pods(1, prefix="late")[0])
        assert not env.provisioner.batch_ready()  # arrival resets idle
        env.clock.step(1.1)
        assert env.provisioner.batch_ready()

    def test_batch_swap_same_count_is_arrival(self, env):
        """Regression (round-1 ADVICE): one pod leaving while another
        arrives in the same window keeps the pending COUNT constant; the
        name-set comparison must still see the arrival and reset the idle
        timer."""
        env.cluster.add_pod(pods(1, prefix="a")[0])
        assert not env.provisioner.batch_ready()  # window opens at t=0
        env.clock.step(0.6)
        env.cluster.delete_pod("a-0")
        env.cluster.add_pod(pods(1, prefix="b")[0])
        assert not env.provisioner.batch_ready()  # swap = arrival
        env.clock.step(0.6)
        # t=1.2: idle since b's arrival is only 0.6 s — a count-based
        # tracker would have fired here
        assert not env.provisioner.batch_ready()
        env.clock.step(0.6)
        assert env.provisioner.batch_ready()

    def test_nodepool_limits_downsize_then_block(self, env, lattice):
        from karpenter_provider_aws_tpu.apis.resources import axis
        env.node_pools["default"].limits = {"cpu": "8"}
        # 3 x 2cpu fits an 8-cpu type: the plan downsizes into the limit
        for p in pods(3, cpu="2", mem="1Gi"):
            env.cluster.add_pod(p)
        r1 = env.provisioner.provision_once()
        assert r1.launched == 1
        (claim,) = env.cluster.claims.values()
        assert claim.capacity["cpu"] <= 8000.0
        # the budget is now exhausted: the next batch cannot launch
        for p in pods(3, cpu="2", mem="1Gi", prefix="over"):
            env.cluster.add_pod(p)
        r2 = env.provisioner.provision_once()
        assert r2.launched == 0
        assert r2.pods_unschedulable == 3
        usage = env.cluster.pool_usage()["default"]
        assert usage[axis("cpu")] <= 8000.0 + 1e-3

    def test_tagging_after_registration(self, env):
        """Post-registration tagging (reference tagging/controller.go:57-110):
        instance gets Name + nodeclaim tags once, never re-tagged."""
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        env.cluster.add_pod(pods(1)[0])
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.annotations.get(wk.ANNOTATION_INSTANCE_TAGGED) == "true"
        inst = env.cloud.instances[parse_instance_id(claim.provider_id)]
        node = env.cluster.node_for_claim(claim.name)
        assert inst.tags[wk.TAG_NAME] == node.name
        assert inst.tags[wk.TAG_NODECLAIM] == claim.name
        # idempotent: a second pass issues no further CreateTags calls
        n_calls = sum(1 for c in env.cloud.calls if c[0] == "create_tags")
        env.tagging.reconcile()
        assert sum(1 for c in env.cloud.calls if c[0] == "create_tags") == n_calls

    def test_tagging_preserves_existing_tags(self, env):
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        iid = parse_instance_id(claim.provider_id)
        env.cloud.instances[iid].tags[wk.TAG_NAME] = "user-set-name"
        env.settle()
        assert env.cloud.instances[iid].tags[wk.TAG_NAME] == "user-set-name"
        assert env.cloud.instances[iid].tags[wk.TAG_NODECLAIM] == claim.name

    def test_tagging_waits_for_registration(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        assert claim.registered_at is None
        env.tagging.reconcile()
        assert wk.ANNOTATION_INSTANCE_TAGGED not in claim.annotations

    def test_gc_terminates_leaked_instance(self, env):
        inst = env.cloud.create_fleet([LaunchOverride("m5.large", "us-west-2a",
                                                      "on-demand", 0.1)]).instance
        env.clock.step(31)
        env.gc.reconcile()
        assert env.cloud.instances[inst.id].state == "terminated"

    def test_gc_removes_claim_for_vanished_instance(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        env.settle()
        (claim,) = env.cluster.claims.values()
        node_name = env.cluster.node_for_claim(claim.name).name
        env.cluster.add_pod(Pod(name="ds-on-victim", is_daemonset=True,
                                node_name=node_name, requests={"cpu": "100m"}))
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        env.cloud.terminate_instances([parse_instance_id(claim.provider_id)])
        env.gc.reconcile()
        assert not env.cluster.claims
        assert not env.cluster.nodes
        assert env.cluster.pending_pods(), "pods should be pending again"
        # the daemonset pod died with its node — no phantom overhead
        assert "ds-on-victim" not in env.cluster.pods

    def test_termination_drains_and_deletes(self, env):
        for p in pods(3):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.termination.delete_claim(claim.name)
        env.termination.reconcile()
        assert not env.cluster.claims and not env.cluster.nodes
        assert len(env.cluster.pending_pods()) == 3
        assert all(i.state == "terminated" for i in env.cloud.instances.values())

    def test_relaunch_after_interruption_like_delete(self, env):
        for p in pods(3):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.termination.delete_claim(claim.name)
        rounds = env.settle()
        assert rounds < 50
        claims = list(env.cluster.claims.values())
        assert len(claims) == 1 and claims[0].name != claim.name
        assert not env.cluster.pending_pods()


class TestOptions:
    def test_env_layering(self, monkeypatch):
        monkeypatch.setenv("CLUSTER_NAME", "prod")
        monkeypatch.setenv("BATCH_IDLE_DURATION", "0.5")
        o = Options.from_env(batch_max_duration=5.0)
        assert o.cluster_name == "prod"
        assert o.batch_idle_duration == 0.5
        assert o.batch_max_duration == 5.0

    def test_validation(self):
        with pytest.raises(ValueError):
            Options(batch_idle_duration=5.0, batch_max_duration=1.0).validate()

    def test_nodeclass_hash_stable(self):
        a = NodeClass(name="x", user_data="a")
        b = NodeClass(name="y", user_data="a")
        c = NodeClass(name="x", user_data="b")
        assert nodeclass_hash(a) == nodeclass_hash(b)
        assert nodeclass_hash(a) != nodeclass_hash(c)


class TestReviewRegressions:
    def test_zero_limit_pauses_pool(self, env):
        """limits={'cpu': 0} is the standard pause-the-pool pattern."""
        env.node_pools["default"].limits = {"cpu": 0}
        env.cluster.add_pod(pods(1)[0])
        r = env.provisioner.provision_once()
        assert r.launched == 0 and r.pods_unschedulable == 1

    def test_batch_windows_wired_from_options(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(batch_idle_duration=0.2, batch_max_duration=5.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        op.cluster.add_pod(pods(1)[0])
        assert not op.provisioner.batch_ready()
        clock.step(0.3)   # past the custom idle window, well under default 1s
        assert op.provisioner.batch_ready()

    def test_ice_expiry_bumps_seq(self, lattice):
        clock = FakeClock()
        u = UnavailableOfferings(clock)
        u.mark_unavailable("ice", "on-demand", lattice.names[0], lattice.zones[0])
        seq = u.seq_num
        clock.step(200)
        u.cleanup()
        assert u.seq_num > seq

    def test_nodepool_hash_annotation_set_and_drift(self, env):
        env.cluster.add_pod(pods(1)[0])
        env.provisioner.provision_once()
        (claim,) = env.cluster.claims.values()
        assert wk.ANNOTATION_NODEPOOL_HASH in claim.annotations
        env.settle()
        env.node_pools["default"].labels["team"] = "new"
        for _ in range(20):
            env.run_once()
            env.clock.step(2)
        claims = list(env.cluster.claims.values())
        assert claims and all(c.name != claim.name for c in claims), \
            "NodePool template change must drift-replace the claim"


class TestLatticeGauges:
    """The per-type / per-offering gauge surface (reference
    pkg/providers/instancetype/metrics.go:32-79), emitted in bulk from the
    lattice tensors and refreshed when pricing or the ICE set changes."""

    def test_offering_gauges_emitted(self, env, lattice):
        env.run_once()
        g = env.metrics.get("karpenter_cloudprovider_instance_type_offering_price_estimate")
        name = lattice.names[0]
        zone, cap = lattice.zones[0], lattice.capacity_types[0]
        if not np.isfinite(lattice.price[0, 0, 0]):
            pytest.skip("first offering not priced in this catalog slice")
        assert g.value(instance_type=name, capacity_type=cap, zone=zone) == \
            pytest.approx(float(lattice.price[0, 0, 0]))
        cpu = env.metrics.get("karpenter_cloudprovider_instance_type_cpu_cores")
        assert cpu.value(instance_type=name) == lattice.specs[0].vcpus
        mem = env.metrics.get("karpenter_cloudprovider_instance_type_memory_bytes")
        assert mem.value(instance_type=name) == lattice.specs[0].memory_mib * 1024 * 1024
        # the full offered surface is present in the rendered exposition
        rendered = env.metrics.render()
        assert "karpenter_cloudprovider_instance_type_offering_available" in rendered

    def test_ice_flips_offering_available(self, env, lattice):
        env.run_once()
        g = env.metrics.get("karpenter_cloudprovider_instance_type_offering_available")
        ti = lattice.name_to_idx["m5.large"]
        zi = next(i for i in range(lattice.Z)
                  if np.isfinite(lattice.price[ti, i, 0]))
        zone, cap = lattice.zones[zi], lattice.capacity_types[0]
        assert g.value(instance_type="m5.large", capacity_type=cap, zone=zone) == 1.0
        env.unavailable.mark_unavailable("test-ice", cap, "m5.large", zone)
        env.run_once()   # seq_num changed -> surface re-emitted
        assert g.value(instance_type="m5.large", capacity_type=cap, zone=zone) == 0.0
        # TTL expiry brings it back
        env.clock.step(181)
        env.unavailable.cleanup()
        env.run_once()
        assert g.value(instance_type="m5.large", capacity_type=cap, zone=zone) == 1.0


class TestReservedCapacityPriority:
    """scheduling.md:450-533 (Savings Plans / Reserved Instances +
    Fallback): a high-weight NodePool pinned to the reserved type and
    capped by spec.limits fills FIRST; overflow falls back to the generic
    default pool instead of going unschedulable."""

    def test_reserved_pool_fills_then_falls_back(self, lattice):
        clock = FakeClock()
        pools = [
            NodePool(name="reserved-instance", weight=50,
                     limits={"cpu": "8"},   # one c5.2xlarge worth
                     requirements=[
                         Requirement(wk.LABEL_INSTANCE_TYPE, ReqOperator.IN,
                                     ("c5.2xlarge",)),
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                                     ("on-demand",))]),
            NodePool(name="default",
                     requirements=[
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                                     ("on-demand",))]),
        ]
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=pools)
        # ~20 cpu of demand: far beyond the 8-cpu reserved limit
        for i in range(10):
            env.cluster.add_pod(Pod(name=f"p{i}",
                                    requests={"cpu": "2", "memory": "2Gi"}))
        env.settle()
        assert all(p.node_name for p in env.cluster.pods.values())
        by_pool = {}
        for c in env.cluster.claims.values():
            by_pool.setdefault(c.node_pool, []).append(c)
        # reserved capacity engaged first and is capped by its limit
        assert "reserved-instance" in by_pool
        reserved_cpu = sum(
            lattice.capacity[lattice.name_to_idx[c.instance_type]][0]
            for c in by_pool["reserved-instance"])
        assert reserved_cpu <= 8000  # millicores
        assert all(c.instance_type == "c5.2xlarge"
                   for c in by_pool["reserved-instance"])
        # the overflow landed on the generic pool
        assert by_pool.get("default"), by_pool

    def test_fallback_rounds_share_one_limit_budget(self, lattice):
        """A retry round must see capacity accepted earlier in the SAME
        pass: pool B's limit cannot be spent once by round 1 and again by
        the fallback round (claims only materialize after the loop)."""
        clock = FakeClock()
        pools = [
            NodePool(name="paused", weight=50, limits={"cpu": "0"},
                     requirements=[
                         Requirement("tier", ReqOperator.IN, ("gold",)),
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                                     ("on-demand",))]),
            NodePool(name="default", limits={"cpu": "8"},
                     requirements=[
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                                     ("on-demand",))]),
        ]
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=pools)
        # generic demand that fills default's 8-cpu limit in round 1
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"gen{i}",
                                    requests={"cpu": "2", "memory": "2Gi"}))
        # gold-tier pods whose round-1 pool (paused) drops them into the
        # fallback retry against default
        for i in range(2):
            env.cluster.add_pod(Pod(name=f"gold{i}",
                                    requests={"cpu": "2", "memory": "2Gi"},
                                    node_selector={"tier": "gold"}))
        env.settle(max_rounds=20)
        launched_cpu = sum(
            lattice.capacity[lattice.name_to_idx[c.instance_type]][0]
            for c in env.cluster.claims.values() if c.node_pool == "default")
        assert launched_cpu <= 8000, \
            f"default pool limit double-spent: {launched_cpu}m launched"
        assert not any(c.node_pool == "paused"
                       for c in env.cluster.claims.values())

    def test_limited_pool_fills_partially_to_its_cap(self, lattice):
        """A limited pool takes what fits instead of all-or-nothing: the
        solve caps fresh-node type options by the pool's remaining
        headroom (the reference narrows in-flight node options the same
        way as spec.limits approaches)."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(
                           name="default", limits={"cpu": "8"},
                           requirements=[Requirement(
                               wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                               ("on-demand",))])])
        for i in range(6):
            env.cluster.add_pod(Pod(name=f"gen{i}",
                                    requests={"cpu": "2", "memory": "2Gi"}))
        env.settle(max_rounds=20)
        launched_cpu = sum(
            lattice.capacity[lattice.name_to_idx[c.instance_type]][0]
            for c in env.cluster.claims.values())
        bound = sum(1 for p in env.cluster.pods.values() if p.node_name)
        assert 0 < launched_cpu <= 8000
        assert bound >= 3           # partial fill, not zero
        assert env.cluster.pending_pods()  # overflow correctly pending


class TestKubeletMaxPods:
    """NodePool spec.template.spec.kubelet.maxPods (reference nodepools
    CRD; the pod-dense scale test pins maxPods: 110): the pool's nodes
    accept at most N pods regardless of ENI-derived density, enforced at
    solve time and persisted through the claim to the registered node."""

    def test_max_pods_caps_density(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(
                           name="default", kubelet=KubeletSpec(max_pods=4),
                           requirements=[Requirement(
                               wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                               ("on-demand",))])])
        # 10 tiny pods easily fit ONE node by resources; maxPods=4 forces
        # at least 3 nodes
        for p in pods(10, cpu="100m", mem="128Mi"):
            env.cluster.add_pod(p)
        env.settle()
        assert all(p.node_name for p in env.cluster.pods.values())
        per_node = {n: len(ps) for n, ps in env.cluster.pods_by_node().items()}
        assert max(per_node.values()) <= 4, per_node
        assert len(env.cluster.nodes) >= 3
        # the clamp persisted into claim + node allocatable
        for claim in env.cluster.claims.values():
            assert claim.allocatable["pods"] <= 4
            node = env.cluster.node_for_claim(claim.name)
            assert node.allocatable["pods"] <= 4

    def test_second_wave_respects_existing_node_cap(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(
                           name="default", kubelet=KubeletSpec(max_pods=3),
                           requirements=[Requirement(
                               wk.LABEL_CAPACITY_TYPE, ReqOperator.IN,
                               ("on-demand",))])])
        for p in pods(3, cpu="100m", mem="128Mi"):
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.nodes) == 1
        # a second wave cannot squeeze onto the full node
        for p in pods(2, cpu="100m", mem="128Mi", prefix="wave2"):
            env.cluster.add_pod(p)
        env.settle()
        per_node = {n: len(ps) for n, ps in env.cluster.pods_by_node().items()}
        assert max(per_node.values()) <= 3, per_node
        assert len(env.cluster.nodes) == 2

    def test_max_pods_change_drifts_nodes(self, lattice):
        """kubelet is template spec: lowering maxPods must roll existing
        nodes (the hash covers the kubelet block)."""
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        from karpenter_provider_aws_tpu.controllers.provisioning import nodepool_hash
        p1 = NodePool(name="x", kubelet=KubeletSpec(max_pods=110))
        p2 = NodePool(name="x", kubelet=KubeletSpec(max_pods=50))
        p3 = NodePool(name="x")
        assert len({nodepool_hash(p1), nodepool_hash(p2), nodepool_hash(p3)}) == 3


class TestLocalZone:
    """Local-zone provisioning (reference test/suites/localzone/
    suite_test.go:50-104): a NodePool restricted to the local zone scales
    hostname-spread pods onto local-zone nodes, drawing from the zone's
    restricted on-demand-only palette at its price premium."""

    def test_scale_up_in_local_zone(self):
        from karpenter_provider_aws_tpu.apis import (
            NodePool, Operator as ReqOp, Pod, Requirement)
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.lattice import build_lattice
        from karpenter_provider_aws_tpu.lattice.catalog import (
            LOCAL_ZONES, ZONE_TYPES, offering_available)
        from karpenter_provider_aws_tpu.solver import Solver, build_problem

        lz = next(iter(LOCAL_ZONES))
        assert ZONE_TYPES[lz] == "local-zone"
        lattice = build_lattice()
        pool = NodePool(name="edge", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, (lz,))])
        pods = [Pod(name=f"edge-{i}", requests={"cpu": "1", "memory": "2Gi"},
                    labels={"foo": "bar"},
                    pod_affinity=[PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME, anti=True,
                        label_selector=(("foo", "bar"),))])
                for i in range(3)]
        problem = build_problem(pods, [pool], lattice)
        plan = Solver(lattice).solve(problem)
        assert not plan.unschedulable
        assert len(plan.new_nodes) == 3  # hostname anti-affinity: 1 per node
        for n in plan.new_nodes:
            assert n.zone == lz
            assert n.capacity_type == "on-demand"  # no spot market in a LZ
            spec = lattice.specs[lattice.name_to_idx[n.instance_type]]
            assert offering_available(spec, lz, "on-demand")
            # local-zone premium over the regional on-demand price
            assert n.price_per_hour > spec.od_price

    def test_spot_constrained_pool_cannot_use_local_zone(self):
        from karpenter_provider_aws_tpu.apis import (
            NodePool, Operator as ReqOp, Pod, Requirement)
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.lattice import build_lattice
        from karpenter_provider_aws_tpu.lattice.catalog import LOCAL_ZONES
        from karpenter_provider_aws_tpu.solver import Solver, build_problem

        lz = next(iter(LOCAL_ZONES))
        lattice = build_lattice()
        pool = NodePool(name="edge-spot", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, (lz,)),
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",))])
        pods = [Pod(name="p0", requests={"cpu": "1", "memory": "2Gi"})]
        plan = Solver(lattice).solve(build_problem(pods, [pool], lattice))
        assert "p0" in plan.unschedulable


class TestIPv6:
    """Single-stack IPv6 provisioning (reference test/suites/ipv6/
    suite_test.go:72-97): nodes come up with an IPv6 internal address; the
    kubelet cluster-DNS comes from operator kube-dns discovery by default
    and from the NodePool kubelet block when set."""

    def _settled_env(self, lattice, pool=None):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=2.0),
                      lattice=lattice,
                      cloud=FakeCloud(clock, ip_family="ipv6"), clock=clock,
                      node_pools=[pool] if pool else None)
        for p in pods(3, prefix="v6"):
            op.cluster.add_pod(p)
        assert op.settle() < 50
        return op

    def test_nodes_register_with_ipv6_internal_address(self, lattice):
        op = self._settled_env(lattice)
        assert op.cluster.nodes
        for node in op.cluster.nodes.values():
            assert node.internal_ip and ":" in node.internal_ip  # v6, not v4

    def test_cluster_dns_discovered_into_userdata(self, lattice):
        op = self._settled_env(lattice)
        dns = op.cloud.network.kube_dns_ip
        assert ":" in dns
        lts = list(op.cloud.network.launch_templates.values())
        assert lts and all(dns in lt.user_data for lt in lts)

    def test_pool_kubelet_cluster_dns_overrides(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import KubeletSpec
        pool = NodePool(name="default",
                        kubelet=KubeletSpec(cluster_dns="fd00:1234::53"))
        op = self._settled_env(lattice, pool=pool)
        lts = list(op.cloud.network.launch_templates.values())
        assert lts and all("fd00:1234::53" in lt.user_data for lt in lts)


class TestLeaseGarbageCollection:
    """Orphaned kube-node-lease Leases are GC'd (reference
    test/suites/integration/lease_garbagecollection_test.go: a lease with
    no OwnerReference is deleted); a live node's owned lease survives."""

    def test_ownerless_and_orphaned_leases_collected(self, env):
        from karpenter_provider_aws_tpu.apis.objects import Lease
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        assert env.cluster.nodes
        node_name = next(iter(env.cluster.nodes))
        # registration created the node's owned lease
        assert env.cluster.leases[node_name].owner_node == node_name
        env.cluster.add_lease(Lease(name="bad-lease", owner_node=None))
        env.cluster.add_lease(Lease(name="stale", owner_node="gone-node"))
        env.gc.reconcile()
        assert "bad-lease" not in env.cluster.leases
        assert "stale" not in env.cluster.leases
        assert node_name in env.cluster.leases  # live owner: kept


class TestClusterStateSynced:
    def test_synced_gauge_tracks_cloud_agreement(self, env):
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        env.emit_gauges()
        assert env.metrics.gauge("karpenter_cluster_state_synced").value() == 1.0
        # a registered claim whose node vanished from the mirror = not
        # synced until the state machine converges (GC/lifecycle)
        claim = next(c for c in env.cluster.claims.values()
                     if env.cluster.node_for_claim(c.name) is not None)
        env.cluster.evict_node(env.cluster.node_for_claim(claim.name).name)
        env.emit_gauges()
        assert env.metrics.gauge("karpenter_cluster_state_synced").value() == 0.0


class TestNodePoolDeletionCascade:
    """Deleting a NodePool drains its nodes (the reference cascades via
    ownerReferences + the termination finalizer, nodepools.md "deleting
    a NodePool deletes its nodes"); claims of live pools are untouched."""

    def test_deleted_pool_drains_and_pods_move(self, env):
        for p in pods(3):
            env.cluster.add_pod(p)
        env.settle()
        first_nodes = set(env.cluster.nodes)
        assert first_nodes
        # replace the pool: fresh capacity takes over, old nodes drain
        env.node_pools["fallback"] = NodePool(name="fallback")
        del env.node_pools["default"]
        env.gc.reconcile()
        assert all(c.deletion_timestamp for c in env.cluster.claims.values()
                   if c.node_pool == "default")
        # settle() exits on no-pending — the drain may still be paging
        # evictions through the old nodes; give it full rounds
        for _ in range(6):
            env.settle()
            env.clock.step(5.0)
            if not (set(env.cluster.nodes) & first_nodes):
                break
        assert not (set(env.cluster.nodes) & first_nodes)
        assert env.cluster.nodes and not env.cluster.pending_pods()
        assert all(c.node_pool == "fallback"
                   for c in env.cluster.claims.values())

    def test_live_pool_claims_survive_gc(self, env):
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        env.gc.reconcile()
        assert env.cluster.claims
        assert all(not c.deletion_timestamp
                   for c in env.cluster.claims.values())
