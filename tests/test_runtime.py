"""Controller runtime + fan-out tests (reference controller-runtime's
MaxConcurrentReconciles registration, nodeclass/controller.go:298-305, and
workqueue.ParallelizeUntil fan-out, interruption/controller.go:104)."""

import threading
import time

import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.operator.runtime import (
    ControllerRuntime, ControllerSpec, operator_specs,
)
from karpenter_provider_aws_tpu.utils.clock import Clock
from karpenter_provider_aws_tpu.utils.fanout import parallelize


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "t3")])


class TestFanout:
    def test_results_keep_order(self):
        assert parallelize(8, list(range(50)), lambda x: x * 2) == \
            [x * 2 for x in range(50)]

    def test_concurrency_is_bounded(self):
        active, peak = [0], [0]
        lock = threading.Lock()

        def fn(_):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.01)
            with lock:
                active[0] -= 1

        parallelize(4, list(range(32)), fn)
        assert 1 < peak[0] <= 4

    def test_exception_propagates(self):
        def fn(x):
            if x == 7:
                raise RuntimeError("boom")
            return x

        with pytest.raises(RuntimeError):
            parallelize(4, list(range(16)), fn)


class TestControllerRuntime:
    def test_controllers_tick_concurrently_and_stop(self):
        counts = {"a": 0, "b": 0}
        runtime = ControllerRuntime([
            ControllerSpec("a", lambda: counts.__setitem__("a", counts["a"] + 1),
                           interval=0.01),
            ControllerSpec("b", lambda: counts.__setitem__("b", counts["b"] + 1),
                           interval=0.01),
        ]).start()
        time.sleep(0.3)
        runtime.stop()
        assert counts["a"] >= 3 and counts["b"] >= 3
        assert not runtime.running
        after = dict(counts)
        time.sleep(0.05)
        assert counts == after, "controllers ticked after stop()"

    def test_crashing_controller_does_not_kill_siblings(self):
        counts = {"ok": 0}

        def bad():
            raise RuntimeError("crash")

        errors = []
        runtime = ControllerRuntime(
            [ControllerSpec("bad", bad, interval=0.01),
             ControllerSpec("ok", lambda: counts.__setitem__("ok", counts["ok"] + 1),
                            interval=0.01)],
            on_error=lambda name, e: errors.append(name)).start()
        time.sleep(0.3)
        runtime.stop()
        assert counts["ok"] >= 3
        assert runtime.error_counts.get("bad", 0) >= 3
        assert set(errors) == {"bad"}

    def test_async_operator_provisions_real_time(self, lattice):
        """The production loop: every controller on its own cadence over
        the locked cluster mirror; pending pods get capacity without the
        deterministic run_once sequencing."""
        clock = Clock()  # real wall clock — the runtime sleeps for real
        op = Operator(options=Options(registration_delay=0.05),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                      node_pools=[NodePool(name="default")])
        specs = [ControllerSpec(s.name, s.reconcile, interval=0.05)
                 for s in operator_specs(op)]
        runtime = ControllerRuntime(specs).start()
        try:
            for i in range(5):
                op.cluster.add_pod(Pod(name=f"p{i}",
                                       requests={"cpu": "500m",
                                                 "memory": "1Gi"}))
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(p.node_name for p in op.cluster.pods.values()):
                    break
                time.sleep(0.1)
        finally:
            runtime.stop()
        assert all(p.node_name for p in op.cluster.pods.values()), \
            "async runtime failed to bind pods"
        assert not runtime.error_counts, runtime.error_counts
