"""Controller runtime + fan-out tests (reference controller-runtime's
MaxConcurrentReconciles registration, nodeclass/controller.go:298-305, and
workqueue.ParallelizeUntil fan-out, interruption/controller.go:104)."""

import threading
import time

import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.operator.runtime import (
    ControllerRuntime, ControllerSpec, operator_specs,
)
from karpenter_provider_aws_tpu.utils.clock import Clock
from karpenter_provider_aws_tpu.utils.fanout import parallelize


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "t3")])


class TestFanout:
    def test_results_keep_order(self):
        assert parallelize(8, list(range(50)), lambda x: x * 2) == \
            [x * 2 for x in range(50)]

    def test_concurrency_is_bounded(self):
        active, peak = [0], [0]
        lock = threading.Lock()

        def fn(_):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(0.01)
            with lock:
                active[0] -= 1

        parallelize(4, list(range(32)), fn)
        assert 1 < peak[0] <= 4

    def test_exception_propagates(self):
        def fn(x):
            if x == 7:
                raise RuntimeError("boom")
            return x

        with pytest.raises(RuntimeError):
            parallelize(4, list(range(16)), fn)


class TestControllerRuntime:
    def test_controllers_tick_concurrently_and_stop(self):
        counts = {"a": 0, "b": 0}
        runtime = ControllerRuntime([
            ControllerSpec("a", lambda: counts.__setitem__("a", counts["a"] + 1),
                           interval=0.01),
            ControllerSpec("b", lambda: counts.__setitem__("b", counts["b"] + 1),
                           interval=0.01),
        ]).start()
        time.sleep(0.3)
        runtime.stop()
        assert counts["a"] >= 3 and counts["b"] >= 3
        assert not runtime.running
        after = dict(counts)
        time.sleep(0.05)
        assert counts == after, "controllers ticked after stop()"

    def test_crashing_controller_does_not_kill_siblings(self):
        counts = {"ok": 0}

        def bad():
            raise RuntimeError("crash")

        errors = []
        runtime = ControllerRuntime(
            [ControllerSpec("bad", bad, interval=0.01),
             ControllerSpec("ok", lambda: counts.__setitem__("ok", counts["ok"] + 1),
                            interval=0.01)],
            on_error=lambda name, e: errors.append(name)).start()
        time.sleep(0.3)
        runtime.stop()
        assert counts["ok"] >= 3
        assert runtime.error_counts.get("bad", 0) >= 3
        assert set(errors) == {"bad"}

    def test_async_operator_provisions_real_time(self, lattice):
        """The production loop: every controller on its own cadence over
        the locked cluster mirror; pending pods get capacity without the
        deterministic run_once sequencing."""
        from karpenter_provider_aws_tpu.introspect import contention
        contention.lockorder_reset()   # scope the witness to this run
        clock = Clock()  # real wall clock — the runtime sleeps for real
        op = Operator(options=Options(registration_delay=0.05),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                      node_pools=[NodePool(name="default")])
        specs = [ControllerSpec(s.name, s.reconcile, interval=0.05)
                 for s in operator_specs(op)]
        runtime = ControllerRuntime(specs).start()
        try:
            for i in range(5):
                op.cluster.add_pod(Pod(name=f"p{i}",
                                       requests={"cpu": "500m",
                                                 "memory": "1Gi"}))
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(p.node_name for p in op.cluster.pods.values()):
                    break
                time.sleep(0.1)
        finally:
            runtime.stop()
        assert all(p.node_name for p in op.cluster.pods.values()), \
            "async runtime failed to bind pods"
        assert not runtime.error_counts, runtime.error_counts
        # the standing lock-order invariant (docs/reference/linting.md):
        # a threaded run must never witness an acquisition-order cycle
        assert contention.lockorder_cycles() == [], \
            contention.lockorder_detail()


class TestLeaderElection:
    """operator/leaderelection.py — client-go-style lease election: one
    winner, renewal keeps it, a dead holder is taken over after the lease
    duration, a clean release hands over immediately."""

    def _electors(self, lease_duration=15.0):
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            LeaderElector, MemoryLeaseStore)
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        store = MemoryLeaseStore()
        a = LeaderElector(store, "replica-a", lease_duration, clock)
        b = LeaderElector(store, "replica-b", lease_duration, clock)
        return clock, a, b

    def test_single_winner_and_renewal(self):
        clock, a, b = self._electors()
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        # renewal inside the lease keeps leadership against the standby
        for _ in range(10):
            clock.step(5)
            assert a.try_acquire_or_renew() is True
            assert b.try_acquire_or_renew() is False

    def test_dead_holder_taken_over_after_lease_expiry(self):
        clock, a, b = self._electors(lease_duration=15.0)
        assert a.try_acquire_or_renew()
        clock.step(14)
        assert b.try_acquire_or_renew() is False   # not yet expired
        clock.step(2)                              # 16s since renew
        assert b.try_acquire_or_renew() is True
        # the resurrected old holder observes it lost
        assert a.try_acquire_or_renew() is False
        assert a.is_leader is False

    def test_clean_release_hands_over_immediately(self):
        clock, a, b = self._electors()
        assert a.try_acquire_or_renew()
        a.release()
        assert b.try_acquire_or_renew() is True

    def test_file_store_round_trip(self, tmp_path):
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            FileLeaseStore, LeaderElector)
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        store1 = FileLeaseStore(str(tmp_path / "lease.json"))
        store2 = FileLeaseStore(str(tmp_path / "lease.json"))
        a = LeaderElector(store1, "proc-a", 15.0, clock)
        b = LeaderElector(store2, "proc-b", 15.0, clock)
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        a.release()
        assert b.try_acquire_or_renew() is True

    def test_api_lease_store_elects_one_and_fails_over(self):
        """ApiLeaseStore: election rides the apiserver's optimistic
        concurrency (the client-go coordination/v1 path)."""
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            ApiLeaseStore, LeaderElector)
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        a = LeaderElector(ApiLeaseStore(server), "replica-a", 15.0, clock)
        b = LeaderElector(ApiLeaseStore(server), "replica-b", 15.0, clock)
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        # dead holder: takeover after expiry
        clock.step(16)
        assert b.try_acquire_or_renew() is True
        assert a.try_acquire_or_renew() is False
        # clean release hands over immediately
        b.release()
        assert a.try_acquire_or_renew() is True

    def test_api_lease_store_cas_on_stale_read(self):
        """swap() must return False (never split leadership, never raise)
        when another replica wrote between its read and its update — the
        race is simulated by serving swap a stale envelope."""
        import copy
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            ApiLeaseStore, Lease)
        server = FakeAPIServer()
        s1, s2 = ApiLeaseStore(server), ApiLeaseStore(server)
        assert s1.swap(None, Lease("a", 1.0)) is True
        stale = server.get("leases", s1.name)   # s2's in-flight read
        assert s1.swap("a", Lease("a", 2.0)) is True   # a renews: RV bumps
        real_get = server.get
        server.get = lambda kind, name: copy.deepcopy(stale)
        try:
            # s2 acts on the stale read: the server-side RV check makes
            # the CAS fail and swap reports it — no exception, no split
            assert s2.swap("a", Lease("b", 9.0)) is False
        finally:
            del server.get   # restore the class method
        assert real_get("leases", s1.name)["spec"]["holder"] == "a"

    def test_election_lease_stays_out_of_node_lease_mirror(self):
        """The leader-election lease must not be reaped by the ownerless-
        lease GC: the sync applier keeps it out of the mirror."""
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            ApiLeaseStore, LeaderElector)
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=build_lattice([s for s in build_catalog()
                                             if s.family in ("t3",)]),
                      clock=clock, api_server=server)
        elector = LeaderElector(ApiLeaseStore(server), "replica-a",
                                15.0, clock)
        assert elector.try_acquire_or_renew()
        op.sync_once()
        assert "karpenter-tpu-leader-election" not in op.cluster.leases
        assert op.cluster.orphaned_leases() == []
        op.gc.reconcile()   # the lease GC must not touch it
        assert elector.try_acquire_or_renew() is True

    def test_runtime_gates_controllers_on_leadership(self):
        import time as _time
        from karpenter_provider_aws_tpu.operator.leaderelection import (
            LeaderElector, MemoryLeaseStore)
        from karpenter_provider_aws_tpu.operator.runtime import (
            ControllerRuntime, ControllerSpec)

        store = MemoryLeaseStore()
        leader = LeaderElector(store, "leader")
        standby = LeaderElector(store, "standby")
        assert leader.try_acquire_or_renew()  # leader holds the lease

        ticks = {"n": 0}
        rt = ControllerRuntime(
            [ControllerSpec("work", lambda: ticks.__setitem__("n", ticks["n"] + 1),
                            interval=0.01)],
            elector=standby).start()
        try:
            _time.sleep(0.3)
            assert ticks["n"] == 0, "standby's controllers must idle"
            leader.release()
            deadline = _time.monotonic() + 5.0
            while ticks["n"] == 0 and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert ticks["n"] > 0, "controllers must start after winning"
        finally:
            assert rt.stop()
        # stop released the lease for the next replica
        assert store.get() is None


class TestOperatorAdmissionBackstops:
    """Startup checks for objects handed to the Operator programmatically,
    bypassing webhook admission (advisor r3 #2, #4)."""

    def test_disagreeing_storage_configs_rejected(self, lattice):
        from karpenter_provider_aws_tpu.apis import NodeClass
        ncs = {
            "default": NodeClass(name="default"),
            "raid": NodeClass(name="raid", instance_store_policy="RAID0"),
        }
        pools = [NodePool(name="default"),
                 NodePool(name="fast", node_class_ref="raid")]
        with pytest.raises(ValueError, match="storage config"):
            Operator(node_classes=ncs, node_pools=pools)

    def test_unreferenced_disagreeing_storage_config_tolerated(self, lattice):
        """A merely-present NodeClass no pool references must not block
        startup — the solver never uses its storage config."""
        from karpenter_provider_aws_tpu.apis import NodeClass
        ncs = {
            "default": NodeClass(name="default"),
            "raid": NodeClass(name="raid", instance_store_policy="RAID0"),
        }
        Operator(node_classes=ncs,
                 node_pools=[NodePool(name="default")])  # must not raise

    def test_agreeing_storage_configs_accepted(self, lattice):
        from karpenter_provider_aws_tpu.apis import NodeClass
        ncs = {
            "default": NodeClass(name="default"),
            "alt": NodeClass(name="alt", tags={"team": "a"}),
        }
        Operator(node_classes=ncs)  # must not raise

    def test_explicit_lattice_skips_storage_check(self, lattice):
        from karpenter_provider_aws_tpu.apis import NodeClass
        ncs = {
            "default": NodeClass(name="default"),
            "raid": NodeClass(name="raid", instance_store_policy="RAID0"),
        }
        Operator(lattice=lattice, node_classes=ncs)  # caller owns the lattice

    def test_multi_valued_os_pool_rejected(self, lattice):
        from karpenter_provider_aws_tpu.apis import Operator as ReqOp
        from karpenter_provider_aws_tpu.apis import Requirement
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        pool = NodePool(name="both", requirements=[
            Requirement(wk.LABEL_OS, ReqOp.IN, ("linux", "windows"))])
        with pytest.raises(ValueError, match="exactly one OS"):
            Operator(lattice=lattice, node_pools=[pool])

    def test_contradictory_os_constraint_rejected(self, lattice):
        """Label os=windows + requirement In (linux,) intersects to the
        empty set — pool_os would silently pin linux; reject instead."""
        from karpenter_provider_aws_tpu.apis import Operator as ReqOp
        from karpenter_provider_aws_tpu.apis import Requirement
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        pool = NodePool(name="contradiction",
                        labels={wk.LABEL_OS: "windows"},
                        requirements=[
                            Requirement(wk.LABEL_OS, ReqOp.IN, ("linux",))])
        with pytest.raises(ValueError, match="exactly one OS"):
            Operator(lattice=lattice, node_pools=[pool])


class TestAsyncApiMode:
    def test_threaded_runtime_over_apiserver(self, lattice):
        """API mode under the production threaded runtime: pods created
        through the client get capacity with the informer pump running as
        its own controller thread."""
        from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
        from karpenter_provider_aws_tpu.introspect import contention
        contention.lockorder_reset()   # scope the witness to this run
        clock = Clock()
        server = FakeAPIServer(clock=clock)
        op = Operator(options=Options(registration_delay=0.05,
                                      batch_idle_duration=0.05,
                                      batch_max_duration=0.5),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                      api_server=server)
        client = KubeClient(server)
        specs = [ControllerSpec(s.name, s.reconcile,
                                interval=min(s.interval, 0.05))
                 for s in operator_specs(op)]
        assert any(s.name == "statesync" for s in specs)
        runtime = ControllerRuntime(specs).start()
        try:
            for i in range(5):
                client.create_pod(Pod(name=f"p{i}",
                                      requests={"cpu": "500m",
                                                "memory": "1Gi"}))
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(p.node_name for p in client.list_pods()):
                    break
                time.sleep(0.1)
        finally:
            runtime.stop()
        assert all(p.node_name for p in client.list_pods()), \
            "async API mode failed to bind pods"
        assert client.list_nodes()
        assert not runtime.error_counts, runtime.error_counts
        # the standing lock-order invariant: the API-mode fan-out path
        # (api_fanout -> watch_event nesting) records edges, never a cycle
        assert contention.lockorder_cycles() == [], \
            contention.lockorder_detail()


class TestClusterEndpointOverride:
    """The configured CLUSTER_ENDPOINT wins over network discovery
    (reference operator.go:119-124, 224-236)."""

    def test_configured_endpoint_reaches_userdata(self):
        from karpenter_provider_aws_tpu.lattice import (
            build_catalog, build_lattice)
        from karpenter_provider_aws_tpu.operator import Operator, Options
        lat = build_lattice([s for s in build_catalog()
                             if s.family == "m5"])
        op = Operator(options=Options(
            cluster_endpoint="https://override.example:443"), lattice=lat)
        nc = op.node_classes["default"]
        params = op.ami_provider.resolve_launch_parameters(nc, "1.29")
        assert params
        assert "https://override.example:443" in params[0].user_data
        assert op.cloud.network.cluster_endpoint not in params[0].user_data

    def test_discovery_remains_the_default(self):
        from karpenter_provider_aws_tpu.lattice import (
            build_catalog, build_lattice)
        from karpenter_provider_aws_tpu.operator import Operator, Options
        lat = build_lattice([s for s in build_catalog()
                             if s.family == "m5"])
        op = Operator(options=Options(), lattice=lat)
        nc = op.node_classes["default"]
        params = op.ami_provider.resolve_launch_parameters(nc, "1.29")
        assert op.cloud.network.cluster_endpoint in params[0].user_data

    def test_non_https_endpoint_rejected(self):
        from karpenter_provider_aws_tpu.operator import Options
        import pytest
        with pytest.raises(ValueError):
            Options.from_env(cluster_endpoint="http://plain.example")

    def test_assume_role_recorded_on_session(self):
        from karpenter_provider_aws_tpu.lattice import (
            build_catalog, build_lattice)
        from karpenter_provider_aws_tpu.operator import Operator, Options
        lat = build_lattice([s for s in build_catalog()
                             if s.family == "m5"])
        op = Operator(options=Options(
            assume_role_arn="arn:aws:iam::1:role/k"), lattice=lat)
        assert op.cloud.assumed_role_arn == "arn:aws:iam::1:role/k"
        assert ("assume_role", "arn:aws:iam::1:role/k") in op.cloud.calls
