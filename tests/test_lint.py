"""graftlint + lock-order witness tests (docs/reference/linting.md).

Fixture-driven cases per rule (violating and clean snippets compiled
from strings), the baseline add/remove round-trip, the standing "repo
lints clean against the committed baseline" tier-1 gate, the pinned
"re-introducing any rule violation in a scratch file exits non-zero",
and the deliberate lock-inversion thread test pinning that the runtime
witness reports exactly one cycle with both witness stacks.
"""

import ast
import json
import sys
import threading
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint import baseline as baseline_mod                    # noqa: E402
from lint.rules import (BoundedResourceRule, ClockRule,      # noqa: E402
                        DeterminismRule, FrozenEnvelopeRule, LockRule,
                        MetricsRule, PACKAGE, ReasonRule, Violation,
                        default_rules)
from lint.run import run_checks                              # noqa: E402
import lint.run as lint_run                                  # noqa: E402

from karpenter_provider_aws_tpu.introspect import contention  # noqa: E402


def check(rule, source, relpath=f"{PACKAGE}/scratch.py"):
    return rule.check_module(ast.parse(source), relpath, source)


# ---- rule 1: clock discipline ---------------------------------------------

class TestClockRule:
    def test_raw_calls_flagged_including_aliases(self):
        src = (
            "import time\n"
            "import time as _t\n"
            "from time import sleep\n"
            "from datetime import datetime\n"
            "def f():\n"
            "    a = time.time()\n"
            "    b = _t.monotonic()\n"
            "    sleep(1)\n"
            "    d = datetime.now()\n")
        vs = check(ClockRule(), src)
        assert {v.call for v in vs} == {
            "time.time", "time.monotonic", "time.sleep",
            "datetime.datetime.now"}
        assert all(v.rule == "clock-discipline" for v in vs)
        assert all(v.context == "f" for v in vs)

    def test_clock_routed_and_perf_counter_clean(self):
        src = (
            "import time\n"
            "def f(clock):\n"
            "    t0 = time.perf_counter()   # interval self-measurement\n"
            "    now = clock.now()\n"
            "    clock.sleep(0.1)\n"
            "    return clock.monotonic() - t0\n")
        assert check(ClockRule(), src) == []

    def test_utils_clock_is_exempt(self):
        src = "import time\ndef now():\n    return time.time()\n"
        rule = ClockRule()
        assert not rule.applies_to(f"{PACKAGE}/utils/clock.py")
        assert rule.applies_to(f"{PACKAGE}/cli.py")
        assert not rule.applies_to("tools/soak.py")


# ---- rule 2: lock discipline ----------------------------------------------

class TestLockRule:
    def test_blocking_calls_under_lock_flagged(self):
        src = (
            "import time\n"
            "def f(self, fut):\n"
            "    with self._lock:\n"
            "        time.sleep(1)\n"
            "        fut.result()\n"
            "        x.block_until_ready()\n")
        vs = check(LockRule(), src)
        assert {v.call for v in vs} == {
            "time.sleep", "fut.result", "x.block_until_ready"}

    def test_clock_sleep_under_lock_flagged(self):
        src = ("def f(self):\n"
               "    with self._solve_lock:\n"
               "        self._clock.sleep(0.05)\n")
        vs = check(LockRule(), src)
        assert len(vs) == 1 and vs[0].call == "self._clock.sleep"

    def test_subscripted_store_lock_counts(self):
        src = ("import time\n"
               "def f(self, kind):\n"
               "    with self._locks[kind]:\n"
               "        time.sleep(0.1)\n")
        assert len(check(LockRule(), src)) == 1

    def test_outside_lock_and_nested_def_clean(self):
        src = (
            "import time\n"
            "def f(self, fut):\n"
            "    with self._lock:\n"
            "        def later():\n"
            "            time.sleep(1)   # runs outside the hold\n"
            "        cb = lambda: fut.result()\n"
            "    time.sleep(1)\n"
            "    return fut.result()\n")
        assert check(LockRule(), src) == []

    def test_string_join_and_condition_wait_clean(self):
        src = (
            "def f(self, items):\n"
            "    with self._cond:\n"
            "        s = ','.join(items)\n"
            "        self._cond.wait(timeout=0.1)\n")
        assert check(LockRule(), src) == []

    def test_stats_taking_solve_lock_flagged(self):
        src = ("class Solver:\n"
               "    def stats(self):\n"
               "        with self._solve_lock:\n"
               "            return {}\n")
        vs = check(LockRule(), src)
        assert len(vs) == 1
        assert vs[0].call == "stats:_solve_lock"
        assert "solve lock" in vs[0].message

    def test_stats_without_solve_lock_clean(self):
        src = ("class Solver:\n"
               "    def stats(self):\n"
               "        with self._stats_lock:\n"
               "            return {}\n"
               "    def solve(self):\n"
               "        with self._solve_lock:\n"
               "            return 1\n")
        assert check(LockRule(), src) == []


# ---- rule 3: determinism --------------------------------------------------

class TestDeterminismRule:
    def scoped(self):
        return DeterminismRule()

    def test_global_rng_and_unseeded_random_flagged(self):
        src = (
            "import random\n"
            "import numpy as np\n"
            "def f():\n"
            "    a = random.random()\n"
            "    b = random.Random()\n"
            "    c = np.random.rand(3)\n")
        vs = check(self.scoped(), src, f"{PACKAGE}/weather/scratch.py")
        assert {v.call for v in vs} == {
            "random.random", "random.Random", "numpy.random.rand"}

    def test_seeded_random_and_datetime_scope(self):
        src = (
            "import random\n"
            "from datetime import datetime\n"
            "def f(seed, t):\n"
            "    rng = random.Random(f'{seed}:{t}')\n"
            "    when = datetime.now()\n")
        vs = check(self.scoped(), src, f"{PACKAGE}/solver/scratch.py")
        assert [v.call for v in vs] == ["datetime.datetime.now"]

    def test_scoping_is_weather_and_solver_only(self):
        rule = self.scoped()
        assert rule.applies_to(f"{PACKAGE}/weather/simulator.py")
        assert rule.applies_to(f"{PACKAGE}/solver/solve.py")
        assert not rule.applies_to(f"{PACKAGE}/cli.py")


# ---- rule 4: frozen-envelope discipline -----------------------------------

class TestFrozenEnvelopeRule:
    def scoped(self):
        return FrozenEnvelopeRule(scopes=(f"{PACKAGE}/scratch.py",))

    def test_mutators_on_envelope_flagged(self):
        src = (
            "def _on_pod(self, type_, name, obj, old):\n"
            "    obj['metadata']['finalizers'].append('x')\n"
            "    obj['spec']['nodeName'] = 'n1'\n"
            "    meta = obj['metadata']\n"
            "    meta.update({'a': 1})\n"
            "    del old['spec']['x']\n")
        vs = check(self.scoped(), src)
        assert {v.call for v in vs} == {
            "obj.append", "obj[...]=", "meta.update", "del old[...]"}
        assert all(v.rule == "frozen-envelope" for v in vs)

    def test_deepcopy_thaw_clean(self):
        src = (
            "import copy\n"
            "def _on_pod(self, type_, name, obj, old):\n"
            "    mine = copy.deepcopy(obj)\n"
            "    mine['spec']['nodeName'] = 'n1'\n"
            "    mine['metadata']['finalizers'].append('x')\n")
        assert check(self.scoped(), src) == []

    def test_rebind_after_nested_mutation_still_flagged(self):
        """Taint transfer runs in SOURCE order: a later rebind of a
        derived name must not retroactively launder a mutation nested
        earlier in a branch (the ast.walk breadth-first bug)."""
        src = (
            "def _on_pod(self, type_, name, obj, old):\n"
            "    spec = obj['spec']\n"
            "    if name:\n"
            "        spec['nodeName'] = 'x'\n"
            "    spec = {}\n")
        vs = check(self.scoped(), src)
        assert [v.call for v in vs] == ["spec[...]="]

    def test_mutation_before_taint_is_clean(self):
        """The mirror image: mutating a private name BEFORE it is later
        re-bound to envelope state must not flag."""
        src = (
            "def _on_pod(self, type_, name, obj, old):\n"
            "    acc = {}\n"
            "    if name:\n"
            "        acc['n'] = 1\n"
            "    acc = obj['spec']\n"
            "    return acc\n")
        assert check(self.scoped(), src) == []

    def test_mutator_inside_statement_expression_flagged(self):
        """Mutator calls embedded in a statement's own expressions (an
        if-test, a return value) are caught, in order."""
        src = (
            "def _on_pod(self, type_, name, obj, old):\n"
            "    if obj['metadata']['finalizers'].pop():\n"
            "        return old.setdefault('x', 1)\n")
        vs = check(self.scoped(), src)
        assert {v.call for v in vs} == {"obj.pop", "old.setdefault"}

    def test_reads_and_nonhandlers_clean(self):
        src = (
            "def _on_pod(self, type_, name, obj, old):\n"
            "    spec = obj['spec']\n"
            "    return spec.get('nodeName')\n"
            "def helper(self, obj):\n"
            "    obj['x'] = 1   # not a handler: no old param, no _on_\n")
        assert check(self.scoped(), src) == []


# ---- rule 5: metrics discipline -------------------------------------------

class TestMetricsRule:
    DECLARED = {"karpenter_pods_scheduled_total"}
    DOCS = "...karpenter_pods_scheduled_total..."

    def rule(self):
        return MetricsRule(declared=set(self.DECLARED),
                           docs_text=self.DOCS)

    def test_undeclared_series_flagged(self):
        src = "def f(reg):\n    reg.counter('karpenter_bogus_total')\n"
        vs = check(self.rule(), src)
        assert len(vs) == 1 and vs[0].call == "karpenter_bogus_total"
        assert "not declared" in vs[0].message

    def test_declared_but_undocumented_flagged(self):
        rule = MetricsRule(declared={"karpenter_x_total"},
                           docs_text="other stuff")
        src = "def f(reg):\n    reg.counter('karpenter_x_total')\n"
        vs = check(rule, src)
        assert len(vs) == 1 and "missing from docs" in vs[0].message

    def test_declared_and_documented_clean(self):
        src = ("def f(reg, m):\n"
               "    reg.counter('karpenter_pods_scheduled_total')\n"
               "    m.get('karpenter_pods_scheduled_total')\n"
               "    m.get('not_a_metric')\n")
        assert check(self.rule(), src) == []

    def test_collect_declared_reads_metrics_py(self):
        declared = MetricsRule.collect_declared(
            (REPO / PACKAGE / "metrics.py").read_text())
        assert "karpenter_pods_scheduled_total" in declared
        assert "karpenter_lock_wait_seconds" in declared
        # the lattice gauge surface comes from wire_lattice_metrics
        assert ("karpenter_cloudprovider_instance_type_offering_available"
                in declared)


# ---- rule 6: reason-code discipline ----------------------------------------

class TestReasonRule:
    DECLARED = {"ice-hold", "no-offering"}

    def rule(self):
        return ReasonRule(declared=set(self.DECLARED))

    def test_undeclared_reason_literal_flagged(self):
        src = ("from karpenter_provider_aws_tpu.solver.taxonomy "
               "import reason\n"
               "def f():\n"
               "    return reason('made-up-code', 'detail')\n")
        vs = check(self.rule(), src)
        assert len(vs) == 1 and vs[0].call == "made-up-code"
        assert vs[0].rule == "reason-code"
        assert "not declared in solver/taxonomy.py" in vs[0].message

    def test_undeclared_code_label_flagged(self):
        src = "def f(m):\n    m.inc(1, code='bogus')\n"
        vs = check(self.rule(), src)
        assert len(vs) == 1 and vs[0].call == "bogus"

    def test_declared_literals_and_variables_clean(self):
        src = ("from karpenter_provider_aws_tpu.solver import taxonomy\n"
               "def f(m, c):\n"
               "    taxonomy.reason('ice-hold', 'x')\n"
               "    m.inc(1, code='no-offering')\n"
               "    m.inc(1, code=c)\n")   # dynamic: the runtime assert owns it
        assert check(self.rule(), src) == []

    def test_alias_cannot_dodge(self):
        src = ("from karpenter_provider_aws_tpu.solver.taxonomy "
               "import reason as _r\n"
               "def f():\n"
               "    return _r('sneaky', 'x')\n")
        vs = check(self.rule(), src)
        assert len(vs) == 1 and vs[0].call == "sneaky"

    def test_taxonomy_module_itself_exempt(self):
        assert not self.rule().applies_to(
            f"{PACKAGE}/solver/taxonomy.py")

    def test_collect_declared_reads_taxonomy_py(self):
        declared = ReasonRule.collect_declared(
            (REPO / PACKAGE / "solver" / "taxonomy.py").read_text())
        from karpenter_provider_aws_tpu.solver import taxonomy as tx
        assert tx.CODES <= declared

    def test_uncoded_sentinel_is_not_declared(self):
        """The UNCODED parse-failure sentinel must stay a lint error:
        reason('uncoded', ...) passes the lint only to crash the runtime
        assert (review regression)."""
        declared = ReasonRule.collect_declared(
            (REPO / PACKAGE / "solver" / "taxonomy.py").read_text())
        assert "uncoded" not in declared
        rule = ReasonRule(declared=declared)
        src = ("from karpenter_provider_aws_tpu.solver.taxonomy "
               "import reason\n"
               "def f():\n    return reason('uncoded')\n")
        assert len(check(rule, src)) == 1

    def test_repo_reason_literals_all_declared(self):
        """Every reason()/code= literal in the package is declared —
        the standing lockstep gate, rule-scoped (no baseline traffic)."""
        rule = [r for r in default_rules(REPO)
                if r.name == "reason-code"][0]
        vs = []
        for py in (REPO / PACKAGE).rglob("*.py"):
            rel = py.relative_to(REPO).as_posix()
            if rule.applies_to(rel):
                src = py.read_text()
                vs += rule.check_module(ast.parse(src), rel, src)
        assert vs == [], [str(v) for v in vs]


# ---- rule 7: bounded-resource discipline -----------------------------------

class TestBoundedResourceRule:
    def test_unprobed_bounded_deque_flagged(self):
        src = ("import collections\n"
               "class Ring:\n"
               "    def __init__(self):\n"
               "        self._ring = collections.deque(maxlen=256)\n")
        vs = check(BoundedResourceRule(), src)
        assert len(vs) == 1
        assert vs[0].rule == "bounded-resource"
        assert vs[0].call == "deque(maxlen)"
        assert vs[0].context == "Ring.__init__"
        assert "headroom probe" in vs[0].message

    def test_alias_and_positional_maxlen_cannot_dodge(self):
        src = ("from collections import deque as dq\n"
               "def f():\n"
               "    a = dq(maxlen=8)\n"
               "    b = dq([], 8)\n")
        vs = check(BoundedResourceRule(), src)
        assert len(vs) == 2

    def test_module_with_headroom_probe_clean(self):
        src = ("import collections\n"
               "class Ring:\n"
               "    def __init__(self):\n"
               "        self._ring = collections.deque(maxlen=256)\n"
               "    def headroom_probe(self):\n"
               "        return {'depth': float(len(self._ring)),\n"
               "                'capacity': 256.0, 'kind': 'ring'}\n")
        assert check(BoundedResourceRule(), src) == []

    def test_module_calling_register_probe_clean(self):
        src = ("import collections\n"
               "def wire(hr):\n"
               "    ring = collections.deque(maxlen=256)\n"
               "    hr.register_probe('ring', lambda: {\n"
               "        'depth': float(len(ring)), 'capacity': 256.0})\n")
        assert check(BoundedResourceRule(), src) == []

    def test_unbounded_and_none_maxlen_clean(self):
        src = ("import collections\n"
               "def f():\n"
               "    a = collections.deque()\n"
               "    b = collections.deque(maxlen=None)\n"
               "    c = collections.deque([1, 2])\n")
        assert check(BoundedResourceRule(), src) == []

    def test_scoping_is_package_only(self):
        rule = BoundedResourceRule()
        assert rule.applies_to(f"{PACKAGE}/state/cluster.py")
        assert not rule.applies_to("tools/soak.py")
        assert not rule.applies_to("tests/test_headroom.py")

    def test_repo_bounded_buffers_all_probed_or_baselined(self):
        """Every deque(maxlen) module in the package either exposes a
        headroom probe or carries a reasoned baseline entry — the
        standing lockstep gate, mirroring the reason-code one."""
        rule = [r for r in default_rules(REPO)
                if r.name == "bounded-resource"][0]
        vs = []
        for py in (REPO / PACKAGE).rglob("*.py"):
            rel = py.relative_to(REPO).as_posix()
            if rule.applies_to(rel):
                src = py.read_text()
                vs += rule.check_module(ast.parse(src), rel, src)
        entries = [e for e in baseline_mod.load(
            REPO / "tools" / "lint" / "baseline.json")
            if e["rule"] == "bounded-resource"]
        un, used, stale = baseline_mod.apply(vs, entries)
        assert un == [], [str(v) for v in un]
        assert stale == [], "stale bounded-resource baseline entries"
        for e in entries:
            assert str(e.get("reason", "")).strip(), e

    def test_instrumented_modules_have_no_violations(self):
        """The structures the saturation observatory instruments lint
        clean WITHOUT baseline help — their probes are the exemption."""
        rule = BoundedResourceRule()
        for rel in (f"{PACKAGE}/state/cluster.py",
                    f"{PACKAGE}/solver/explain.py",
                    f"{PACKAGE}/introspect/sampler.py",
                    f"{PACKAGE}/introspect/slo.py",
                    f"{PACKAGE}/introspect/profiler.py",
                    f"{PACKAGE}/kube/apiserver.py",
                    f"{PACKAGE}/events.py"):
            src = (REPO / rel).read_text()
            assert rule.check_module(ast.parse(src), rel, src) == [], rel


# ---- baseline round-trip ---------------------------------------------------

class TestBaseline:
    V = Violation("clock-discipline", f"{PACKAGE}/cli.py", 553, "main",
                  "time.monotonic", "raw wall-clock call")

    def test_entry_suppresses_and_removal_resurfaces(self):
        entry = {"rule": "clock-discipline", "file": f"{PACKAGE}/cli.py",
                 "call": "time.monotonic", "reason": "serve deadline"}
        un, used, stale = baseline_mod.apply([self.V], [entry])
        assert un == [] and used == [entry] and stale == []
        # remove the entry: the violation resurfaces
        un, used, stale = baseline_mod.apply([self.V], [])
        assert un == [self.V]

    def test_context_wildcard_and_mismatch(self):
        wrong_call = {"rule": "clock-discipline",
                      "file": f"{PACKAGE}/cli.py",
                      "call": "time.sleep", "reason": "x"}
        un, used, stale = baseline_mod.apply([self.V], [wrong_call])
        assert un == [self.V] and stale == [wrong_call]
        pinned_ctx = {"rule": "clock-discipline",
                      "file": f"{PACKAGE}/cli.py",
                      "call": "time.monotonic", "context": "main",
                      "reason": "x"}
        un, _, _ = baseline_mod.apply([self.V], [pinned_ctx])
        assert un == []

    def test_stale_and_reasonless_entries_are_problems(self):
        stale_e = {"rule": "determinism", "file": "nope.py", "call": "x",
                   "reason": "y"}
        noreason = {"rule": "clock-discipline", "file": f"{PACKAGE}/cli.py",
                    "call": "time.monotonic", "reason": "  "}
        un, used, stale = baseline_mod.apply([self.V], [stale_e, noreason])
        probs = baseline_mod.problems([stale_e, noreason], stale)
        assert any("stale" in p for p in probs)
        assert any("no reason" in p for p in probs)

    def test_save_load_round_trip(self, tmp_path):
        p = tmp_path / "baseline.json"
        entries = [{"rule": "r", "file": "f.py", "call": "c",
                    "reason": "because"}]
        baseline_mod.save(p, entries)
        assert baseline_mod.load(p) == entries
        # version guard
        p.write_text(json.dumps({"version": 99, "entries": []}))
        with pytest.raises(ValueError):
            baseline_mod.load(p)


# ---- the standing repo gate ------------------------------------------------

SCRATCH_VIOLATIONS = {
    "clock-discipline":
        "import time\ndef f():\n    return time.time()\n",
    "lock-discipline":
        "import time\ndef f(self):\n"
        "    with self._lock:\n        time.sleep(1)\n",
    "determinism": None,   # needs a scoped path; handled below
    "frozen-envelope": None,
    "metrics-discipline":
        "def f(reg):\n    reg.counter('karpenter_never_declared_total')\n",
}


class TestRepoGate:
    def test_repo_lints_clean_against_committed_baseline(self):
        """The standing tier-1 twin of ci.sh gate 2: every violation in
        the tree is either fixed or baselined with a reason."""
        violations, errors = run_checks(REPO)
        assert errors == []
        entries = baseline_mod.load(REPO / "tools" / "lint" /
                                    "baseline.json")
        assert len(entries) <= 10, "baseline budget is 10 entries"
        un, used, stale = baseline_mod.apply(violations, entries)
        assert un == [], "\n".join(str(v) for v in un)
        assert baseline_mod.problems(entries, stale) == []

    @pytest.mark.parametrize("rule,rel,src", [
        ("clock-discipline", "scratch.py",
         SCRATCH_VIOLATIONS["clock-discipline"]),
        ("lock-discipline", "scratch.py",
         SCRATCH_VIOLATIONS["lock-discipline"]),
        ("determinism", "weather/scratch.py",
         "import random\ndef f():\n    return random.random()\n"),
        ("frozen-envelope", "kube/informer.py",
         "def _on_x(self, type_, name, obj, old):\n"
         "    obj['spec']['x'] = 1\n"),
        ("metrics-discipline", "scratch.py",
         SCRATCH_VIOLATIONS["metrics-discipline"]),
        ("reason-code", "scratch.py",
         "def f(m):\n    m.inc(1, code='bogus-code')\n"),
        ("bounded-resource", "scratch.py",
         "import collections\ndef f():\n"
         "    return collections.deque(maxlen=5)\n"),
    ])
    def test_scratch_violation_fails_the_gate(self, tmp_path, rule, rel,
                                              src):
        """Re-introducing any of the seven rule violations in a scratch
        file makes run.py exit non-zero (the acceptance pin)."""
        pkg = tmp_path / PACKAGE
        (pkg / Path(rel).parent).mkdir(parents=True, exist_ok=True)
        (pkg / rel).write_text(src)
        # a metrics catalog so metrics-discipline has a declared set
        (pkg / "metrics.py").write_text(
            "def wire(reg):\n"
            "    reg.counter('karpenter_pods_scheduled_total', 'h')\n")
        rc = lint_run.main(["--check", "--root", str(tmp_path),
                            "--baseline", str(tmp_path / "baseline.json")])
        assert rc == 1
        violations, _ = run_checks(tmp_path)
        assert any(v.rule == rule for v in violations), violations

    def test_clean_scratch_tree_passes(self, tmp_path):
        pkg = tmp_path / PACKAGE
        pkg.mkdir(parents=True)
        (pkg / "ok.py").write_text(
            "def f(clock):\n    return clock.now()\n")
        rc = lint_run.main(["--check", "--root", str(tmp_path),
                            "--baseline", str(tmp_path / "baseline.json")])
        assert rc == 0

    def test_update_baseline_round_trip(self, tmp_path, capsys):
        """--update-baseline accepts current violations but writes EMPTY
        reasons, so --check stays red until a human justifies them; with
        reasons filled in, the gate goes green; fixing the violation then
        turns the entry stale and the gate red again."""
        pkg = tmp_path / PACKAGE
        pkg.mkdir(parents=True)
        bad = pkg / "scratch.py"
        bad.write_text(SCRATCH_VIOLATIONS["clock-discipline"])
        bl = tmp_path / "baseline.json"
        assert lint_run.main(["--check", "--root", str(tmp_path),
                              "--baseline", str(bl)]) == 1
        assert lint_run.main(["--update-baseline", "--root", str(tmp_path),
                              "--baseline", str(bl)]) == 0
        # reasonless entries keep the gate red
        assert lint_run.main(["--check", "--root", str(tmp_path),
                              "--baseline", str(bl)]) == 1
        entries = baseline_mod.load(bl)
        for e in entries:
            e["reason"] = "fixture: wall-clock-only"
        baseline_mod.save(bl, entries)
        assert lint_run.main(["--check", "--root", str(tmp_path),
                              "--baseline", str(bl)]) == 0
        # fix the violation: the entry is now stale and the gate is red
        bad.write_text("def f(clock):\n    return clock.now()\n")
        assert lint_run.main(["--check", "--root", str(tmp_path),
                              "--baseline", str(bl)]) == 1
        out = capsys.readouterr().out
        assert "stale baseline entry" in out


# ---- the lock-order witness ------------------------------------------------

class TestLockOrderWitness:
    def setup_method(self):
        contention.lockorder_reset()

    def teardown_method(self):
        # the inversion test records a REAL cycle: it must never poison
        # the standing no-cycle assertions later tests make
        contention.lockorder_reset()

    def test_nested_acquire_records_edge_no_cycle(self):
        a, b = contention.lock("low_a_lock"), contention.lock("low_b_lock")
        with a:
            with b:
                pass
        st = contention.lockorder_stats()
        assert st["edges"] == 1 and st["cycles"] == 0
        d = contention.lockorder_detail()
        assert "low_a_lock -> low_b_lock" in d["edges"]
        assert d["edges"]["low_a_lock -> low_b_lock"]["stack"]

    def test_sequential_acquires_record_no_edge(self):
        a, b = contention.lock("seq_a_lock"), contention.lock("seq_b_lock")
        with a:
            pass
        with b:
            pass
        assert contention.lockorder_stats()["edges"] == 0

    def test_reentrant_rlock_records_no_self_edge(self):
        r = contention.rlock("reent_lock")
        with r:
            with r:
                pass
        assert contention.lockorder_stats()["edges"] == 0

    def test_deliberate_inversion_reports_exactly_one_cycle_with_both_stacks(self):
        """Two threads, opposite acquisition order (serialized so the
        test never actually deadlocks): the witness must report EXACTLY
        one cycle, carrying both edges' witness stacks."""
        a = contention.lock("inv_a_lock")
        b = contention.lock("inv_b_lock")

        def order_ab():
            with a:
                with b:
                    pass

        def order_ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=order_ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=order_ba)
        t2.start()
        t2.join()

        cycles = contention.lockorder_cycles()
        assert cycles == [["inv_a_lock", "inv_b_lock"]]
        st = contention.lockorder_stats()
        assert st["edges"] == 2 and st["cycles"] == 1
        d = contention.lockorder_detail()
        assert len(d["cycles"]) == 1
        members = d["cycles"][0]["edges"]
        assert [m["edge"] for m in members] == [
            "inv_a_lock -> inv_b_lock", "inv_b_lock -> inv_a_lock"]
        for m in members:
            assert m["stack"], "each cycle edge must carry a witness stack"
            assert any("test_lint.py" in fr for fr in m["stack"]), m["stack"]

    def test_condition_wait_reacquire_keeps_edges_sane(self):
        """Condition.wait releases and re-acquires through the wrapper:
        the held-set stays balanced (no phantom edges accumulate)."""
        outer = contention.lock("cw_outer_lock")
        cond = contention.condition("cw_cond")
        with outer:
            with cond:
                cond.wait(timeout=0.01)
        st = contention.lockorder_stats()
        # outer->cond witnessed (twice: entry + wait re-acquire); never
        # the reverse, never a cycle
        d = contention.lockorder_detail()["edges"]
        assert "cw_outer_lock -> cw_cond" in d
        assert "cw_cond -> cw_outer_lock" not in d
        assert st["cycles"] == 0

    def test_stats_provider_shape_and_disabled_flag(self):
        st = contention.lockorder_stats()
        assert set(st) == {"edges", "cycles", "ordered_acquires",
                           "enabled"}
        assert all(isinstance(v, float) for v in st.values())

    def test_pprof_route_serves_lockorder(self):
        from karpenter_provider_aws_tpu import introspect
        a = contention.lock("route_a_lock")
        b = contention.lock("route_b_lock")
        with a:
            with b:
                pass
        body, ctype = introspect.debug_doc("/debug/pprof/lockorder", {})
        assert ctype == "application/json"
        doc = json.loads(body)
        assert "route_a_lock -> route_b_lock" in doc["edges"]
        assert doc["cycles"] == []


# ---- kpctl surfaces --------------------------------------------------------

class TestKpctlLockorder:
    @pytest.fixture()
    def kpctl(self):
        import kpctl
        return kpctl

    def test_top_contention_row_gains_lockorder_cell(self, kpctl):
        doc = {"providers": {
            "contention": {"locks": 1, "a_wait_p99_ms": 1.0,
                           "a_contended": 2},
            "lockorder": {"edges": 3.0, "cycles": 0.0,
                          "ordered_acquires": 9.0, "enabled": 1.0},
        }}
        lines = kpctl._render_top(doc, "srv")
        cont = next(l for l in lines if l.startswith("CONTENTION"))
        assert "LOCKORDER 3 edges / 0 cycles" in cont
        assert "DEADLOCK" not in cont
        doc["providers"]["lockorder"]["cycles"] = 1.0
        cont = next(l for l in kpctl._render_top(doc, "srv")
                    if l.startswith("CONTENTION"))
        assert "DEADLOCK RISK" in cont

    def test_top_tolerates_error_provider_shape(self, kpctl):
        """The registry's {"error"} provider shape drops the LOCKORDER
        cell, not the view (the PR 5 WRITER-row contract)."""
        doc = {"providers": {
            "contention": {"locks": 1, "a_wait_p99_ms": 1.0,
                           "a_contended": 2},
            "lockorder": {"error": "boom"},
        }}
        lines = kpctl._render_top(doc, "srv")
        cont = next(l for l in lines if l.startswith("CONTENTION"))
        assert "LOCKORDER" not in cont and "a p99" in cont

    def test_cmd_lockorder_renders_graph_and_cycles(self, kpctl, capsys):
        class FakeClient:
            def __init__(self, doc):
                self.doc = doc

            def request(self, method, path):
                assert path == "/debug/pprof/lockorder"
                return self.doc

        class Args:
            stacks = False

        doc = {"enabled": True,
               "edges": {"a -> b": {"count": 4, "stack": ["f.py:1:g"]}},
               "cycles": []}
        rc = kpctl.cmd_lockorder(FakeClient(doc), Args())
        out = capsys.readouterr().out
        assert rc == 0 and "1 edges, 0 cycles" in out and "a -> b" in out
        doc["cycles"] = [{"locks": ["a", "b"], "edges": [
            {"edge": "a -> b", "count": 4, "stack": ["f.py:1:g"]},
            {"edge": "b -> a", "count": 1, "stack": ["h.py:2:k"]}]}]
        rc = kpctl.cmd_lockorder(FakeClient(doc), Args())
        out = capsys.readouterr().out
        assert rc == 1
        assert "CYCLE (potential deadlock): a -> b -> a" in out
        assert "f.py:1:g" in out and "h.py:2:k" in out

    def test_cmd_lockorder_tolerates_error_shape(self, kpctl, capsys):
        class FakeClient:
            def request(self, method, path):
                return {"error": "provider blew up"}

        class Args:
            stacks = False

        rc = kpctl.cmd_lockorder(FakeClient(), Args())
        assert rc == 1
        assert "unavailable" in capsys.readouterr().out


class TestOperatorWiring:
    def test_lockorder_provider_registered(self, request):
        """Operator._wire_introspection registers the lockorder provider
        (the kpctl top cell and sampler rings read it)."""
        from karpenter_provider_aws_tpu import introspect
        from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                        build_lattice)
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        clock = FakeClock()
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family == "m5"][:4])
        Operator(options=Options(), lattice=lattice,
                 cloud=FakeCloud(clock), clock=clock)
        snap = introspect.registry().collect()
        assert "lockorder" in snap
        assert set(snap["lockorder"]) >= {"edges", "cycles"}
