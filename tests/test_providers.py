"""Provider-layer tests: subnet/SG/instance-profile/AMI/launch-template/
pricing/version providers, NodeClass controller, admission webhooks.

Behavioral spec: reference pkg/providers/* and pkg/controllers/nodeclass
(see each provider's docstring for file:line cites).
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.apis.objects import (
    MetadataOptions, NodeClass, NodeClassSelectorTerm, NodePoolDisruption,
)
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.cloud.network import Image
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.providers import (
    AMIProvider, InstanceProfileProvider, LaunchTemplateProvider,
    PricingProvider, SecurityGroupProvider, SubnetProvider, VersionProvider,
)
from karpenter_provider_aws_tpu.utils.clock import FakeClock
from karpenter_provider_aws_tpu.webhooks import (
    AdmissionError, admit_node_class, admit_node_pool,
)

_FAMILIES = ("m5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture()
def cloud():
    return FakeCloud(FakeClock())


def nodeclass(**kw):
    kw.setdefault("name", "default")
    kw.setdefault("role", "KarpenterNodeRole-sim")
    return NodeClass(**kw)


class TestSubnetProvider:
    def test_discovery_by_cluster_tag(self, cloud):
        p = SubnetProvider(cloud, cloud.clock)
        from karpenter_provider_aws_tpu.lattice.catalog import ZONES
        subs = p.list(nodeclass())
        assert len(subs) == len(ZONES)
        assert {s.zone for s in subs} == set(ZONES)

    def test_discovery_by_id(self, cloud):
        p = SubnetProvider(cloud, cloud.clock)
        nc = nodeclass(subnet_selector_terms=[NodeClassSelectorTerm(id="subnet-0001")])
        assert [s.id for s in p.list(nc)] == ["subnet-0001"]

    def test_zonal_choice_prefers_free_ips_with_inflight(self, cloud):
        p = SubnetProvider(cloud, cloud.clock)
        # add a second subnet in zone a with more free IPs
        from karpenter_provider_aws_tpu.cloud.network import Subnet
        cloud.network.subnets["subnet-9999"] = Subnet(
            id="subnet-9999", zone="us-west-2a", cidr="10.9.0.0/24",
            available_ips=500, tags={"kubernetes.io/cluster/sim": "owned"})
        zs = p.zonal_subnets_for_launch(nodeclass())
        assert zs["us-west-2a"].id == "subnet-9999"
        # book 300 in-flight IPs: the original subnet becomes the best
        p.update_inflight_ips("subnet-9999", 300)
        zs = p.zonal_subnets_for_launch(nodeclass())
        assert zs["us-west-2a"].id == "subnet-0001"
        # bookings decay after the describe-cache window re-baselines
        p._clock.step(61)
        zs = p.zonal_subnets_for_launch(nodeclass())
        assert zs["us-west-2a"].id == "subnet-9999"


class TestSecurityGroupAndProfile:
    def test_sg_discovery_by_name(self, cloud):
        p = SecurityGroupProvider(cloud, cloud.clock)
        nc = nodeclass(security_group_selector_terms=[NodeClassSelectorTerm(name="nodes")])
        assert [g.name for g in p.list(nc)] == ["nodes"]

    def test_profile_create_is_deterministic_and_idempotent(self, cloud):
        p = InstanceProfileProvider(cloud, cloud.clock)
        n1 = p.create(nodeclass())
        n2 = p.create(nodeclass())
        assert n1 == n2 and n1.startswith("karpenter_")
        assert cloud.network.get_instance_profile(n1).role == "KarpenterNodeRole-sim"

    def test_profile_role_change_reconciles(self, cloud):
        p = InstanceProfileProvider(cloud, cloud.clock)
        name = p.create(nodeclass())
        p._cache.flush()
        p.create(nodeclass(role="OtherRole"))
        assert cloud.network.get_instance_profile(name).role == "OtherRole"

    def test_user_managed_profile_never_deleted(self, cloud):
        p = InstanceProfileProvider(cloud, cloud.clock)
        nc = nodeclass(role=None, instance_profile="my-profile")
        assert p.create(nc) == "my-profile"
        p.delete(nc)  # no-op, no exception


class TestAMIProvider:
    def test_ssm_default_discovery_per_arch(self, cloud):
        p = AMIProvider(cloud, cloud.clock)
        amis = p.list(nodeclass(ami_family="AL2023"), "1.29")
        assert {a.arch for a in amis} == {"amd64", "arm64"}
        assert all(a.id.startswith("ami-al2023") for a in amis)

    def test_every_family_default_resolves(self, cloud):
        """Regression (round-1 ADVICE): the fake seeded AL2 SSM keys under a
        path the AL2 strategy never queries, so AL2 NodeClasses resolved
        zero AMIs and stayed NotReady forever. The fake now derives its keys
        from each strategy's default_ami_ssm_parameters(); every non-Custom
        family must resolve its defaults."""
        from karpenter_provider_aws_tpu.providers.amifamily import AMI_FAMILIES
        p = AMIProvider(cloud, cloud.clock)
        for name, fam in AMI_FAMILIES.items():
            expected = fam.default_ami_ssm_parameters("1.29")
            if not expected:   # Custom: selector terms required, no defaults
                continue
            amis = p.list(nodeclass(name=f"nc-{name.lower()}", ami_family=name), "1.29")
            assert {a.arch for a in amis} == set(expected), name

    def test_selector_terms_override_defaults(self, cloud):
        p = AMIProvider(cloud, cloud.clock)
        nc = nodeclass(ami_family="Custom",
                       ami_selector_terms=[NodeClassSelectorTerm(name="al2-amd64-v1.29")])
        amis = p.list(nc, "1.29")
        assert [a.id for a in amis] == ["ami-al2-amd64"]

    def test_newest_per_arch_wins(self, cloud):
        cloud.network.images["ami-newer"] = Image(
            id="ami-newer", name="custom", arch="amd64", creation_date=9_999.0,
            tags={"team": "ml"})
        cloud.network.images["ami-older"] = Image(
            id="ami-older", name="custom", arch="amd64", creation_date=1.0,
            tags={"team": "ml"})
        p = AMIProvider(cloud, cloud.clock)
        nc = nodeclass(ami_family="Custom",
                       ami_selector_terms=[NodeClassSelectorTerm(tags=(("team", "ml"),))])
        amis = p.list(nc, "1.29")
        assert [a.id for a in amis] == ["ami-newer"]

    def test_user_data_per_family(self, cloud):
        p = AMIProvider(cloud, cloud.clock)
        al2023 = p.resolve_launch_parameters(nodeclass(ami_family="AL2023"), "1.29")
        assert any("NodeConfig" in lp.user_data for lp in al2023)
        br = p.resolve_launch_parameters(nodeclass(ami_family="Bottlerocket"), "1.29")
        assert any("[settings.kubernetes]" in lp.user_data for lp in br)


class TestLaunchTemplateProvider:
    def _provider(self, cloud):
        sg = SecurityGroupProvider(cloud, cloud.clock)
        ip = InstanceProfileProvider(cloud, cloud.clock)
        ami = AMIProvider(cloud, cloud.clock)
        return LaunchTemplateProvider(cloud, sg, ip, ami, cloud.clock)

    def test_ensure_all_creates_per_arch_and_is_idempotent(self, cloud):
        p = self._provider(cloud)
        lts = p.ensure_all(nodeclass(), "1.29")
        assert len(lts) == 2  # amd64 + arm64 AMIs
        n_before = len(cloud.network.launch_templates)
        lts2 = p.ensure_all(nodeclass(), "1.29")
        assert len(cloud.network.launch_templates) == n_before
        assert {l.name for l in lts} == {l.name for l in lts2}

    def test_content_change_creates_new_template(self, cloud):
        p = self._provider(cloud)
        p.ensure_all(nodeclass(), "1.29")
        n1 = len(cloud.network.launch_templates)
        p.ensure_all(nodeclass(user_data="echo hi"), "1.29")
        assert len(cloud.network.launch_templates) == n1 + 2

    def test_cache_eviction_gcs_cloud_template(self, cloud):
        clock = cloud.clock
        p = self._provider(cloud)
        p.ensure_all(nodeclass(), "1.29")
        assert len(cloud.network.launch_templates) == 2
        clock.step(400)  # past the 5-min LT cache TTL
        p.cleanup()
        assert len(cloud.network.launch_templates) == 0

    def test_delete_all_for_nodeclass(self, cloud):
        p = self._provider(cloud)
        p.ensure_all(nodeclass(), "1.29")
        assert p.delete_all(nodeclass()) == 2
        assert len(cloud.network.launch_templates) == 0


class TestPricing:
    def test_od_overlay_keeps_local_zone_premium(self, lattice):
        """A 12h Pricing-API overlay reports ONE regional OD price; the
        rebuild must re-apply the local-zone premium, not broadcast the
        regional price into every zone."""
        import numpy as np
        from karpenter_provider_aws_tpu.lattice.catalog import (
            LOCAL_ZONES, od_zone_multiplier)
        p = PricingProvider(lattice)
        ti = lattice.name_to_idx["m5.large"]
        ci = lattice.capacity_types.index("on-demand")
        p.update_on_demand_pricing({"m5.large": 0.1})
        for zi, z in enumerate(lattice.zones):
            if not lattice.available[ti, zi, ci]:
                continue
            assert lattice.price[ti, zi, ci] == pytest.approx(
                0.1 * od_zone_multiplier(z), rel=1e-6)
        lz = next(iter(LOCAL_ZONES))
        zi = lattice.zones.index(lz)
        if lattice.available[ti, zi, ci]:
            assert lattice.price[ti, zi, ci] > np.float32(0.1)
        p.reset()

    def test_static_fallback_prices(self, lattice):
        p = PricingProvider(lattice)
        od = p.on_demand_price("m5.large")
        assert 0 < od < 1
        sp = p.spot_price("m5.large", lattice.zones[0])
        assert 0 < sp < od

    def test_dynamic_override_reaches_solver(self, lattice):
        import copy
        lat = copy.deepcopy(lattice)
        from karpenter_provider_aws_tpu.solver import Solver, build_problem
        solver = Solver(lat)
        p = PricingProvider(lat)
        # make one cheap type absurdly expensive: the solver must avoid it
        problem = build_problem([Pod(name="x", requests={"cpu": "1", "memory": "1Gi"})],
                                [NodePool(name="default")], lat)
        plan0 = solver.solve(problem)
        chosen = plan0.new_nodes[0].instance_type
        p.update_on_demand_pricing({chosen: 10_000.0})
        p.update_spot_pricing({(chosen, z): 10_000.0 for z in lat.zones})
        problem = build_problem([Pod(name="y", requests={"cpu": "1", "memory": "1Gi"})],
                                [NodePool(name="default")], lat)
        plan1 = solver.solve(problem)
        assert plan1.new_nodes[0].instance_type != chosen

    def test_version_provider(self, cloud):
        v = VersionProvider(cloud, cloud.clock)
        assert v.get() == "1.29"


class TestNodeClassController:
    def test_status_hydration(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        op.run_once()
        nc = op.node_classes["default"]
        assert len(nc.status_subnets) == 5
        assert len(nc.status_security_groups) == 2
        assert len(nc.status_amis) == 2
        assert nc.status_instance_profile
        assert nc.status_conditions["Ready"]

    def test_finalizer_blocks_until_claims_gone(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        op.cluster.add_pod(Pod(name="p", requests={"cpu": "500m", "memory": "1Gi"}))
        op.settle()
        op.nodeclass_controller.delete("default")
        op.run_once()
        assert "default" in op.node_classes, "delete must block while claims exist"
        op.cluster.delete_pod("p")
        (claim,) = op.cluster.claims.values()
        op.termination.delete_claim(claim.name)
        op.settle(max_rounds=10)
        op.run_once()
        assert "default" not in op.node_classes
        assert len(op.cloud.network.launch_templates) == 0
        assert not op.cloud.network.instance_profiles


class TestWebhooks:
    def test_nodepool_defaulting(self):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        pool = admit_node_pool(NodePool(name="p"))
        keys = {r.key for r in pool.requirements}
        assert wk.LABEL_CAPACITY_TYPE in keys and wk.LABEL_ARCH in keys

    def test_nodepool_validation_rejects_bad_budget(self):
        from karpenter_provider_aws_tpu.apis.objects import DisruptionBudget
        pool = NodePool(name="p", disruption=NodePoolDisruption(
            budgets=[DisruptionBudget(nodes="lots")]))
        with pytest.raises(AdmissionError):
            admit_node_pool(pool)

    def test_nodepool_rejects_restricted_key(self):
        from karpenter_provider_aws_tpu.apis import Operator as ReqOp, Requirement
        pool = NodePool(name="p", requirements=[
            Requirement("kubernetes.io/hostname", ReqOp.IN, ("n1",))])
        with pytest.raises(AdmissionError):
            admit_node_pool(pool)

    def test_nodeclass_role_xor_profile(self):
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r", instance_profile="p"))
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x"))
        admit_node_class(NodeClass(name="x", role="r"))

    def test_nodeclass_custom_family_needs_selectors(self):
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r", ami_family="Custom"))

    def test_nodeclass_storage_validation(self):
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r",
                                       instance_store_policy="RAID5"))
        admit_node_class(NodeClass(name="x", role="r",
                                   instance_store_policy="RAID0"))
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r",
                                       block_device_mappings=[{"volume_size_mib": 100}]))
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r", block_device_mappings=[
                {"device_name": "/dev/xvda", "root_volume": True},
                {"device_name": "/dev/xvdb", "root_volume": True}]))
        with pytest.raises(AdmissionError):
            admit_node_class(NodeClass(name="x", role="r", block_device_mappings=[
                {"device_name": "/dev/xvda", "volume_size_mib": -5}]))
        for bad in (True, float("nan")):
            with pytest.raises(AdmissionError):
                admit_node_class(NodeClass(name="x", role="r", block_device_mappings=[
                    {"device_name": "/dev/xvda", "volume_size_mib": bad}]))
        admit_node_class(NodeClass(name="x", role="r", block_device_mappings=[
            {"device_name": "/dev/xvda", "root_volume": True,
             "volume_size_mib": 100 * 1024.0}]))

    def test_nodeclass_metadata_options(self):
        nc = NodeClass(name="x", role="r",
                       metadata_options=MetadataOptions(http_tokens="sometimes"))
        with pytest.raises(AdmissionError):
            admit_node_class(nc)


class TestLaunchPathIntegration:
    def test_launch_attaches_template_subnet_and_image(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        op.cluster.add_pod(Pod(name="p", requests={"cpu": "500m", "memory": "1Gi"}))
        op.settle()
        (claim,) = op.cluster.claims.values()
        inst = op.cloud.instances[
            claim.provider_id.rsplit("/", 1)[1]]
        assert inst.tags.get("launch-template", "").startswith("karpenter.sim/")
        assert inst.tags.get("subnet-id", "").startswith("subnet-")
        assert claim.image_id and claim.image_id.startswith("ami-")
        # the chosen subnet's in-flight IPs were booked
        assert op.subnet_provider._inflight


class TestOpsReviewRegressions:
    def test_misconfigured_nodeclass_does_not_crash_reconcile(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock,
                      node_classes={"default": NodeClass(name="default")})  # no role
        op.cluster.add_pod(Pod(name="p", requests={"cpu": "500m", "memory": "1Gi"}))
        r = op.provisioner.provision_once()   # must not raise
        assert r.launch_failures == 1
        assert not op.cluster.claims, "failed launch must roll the claim back"
        assert op.recorder.events(reason="LaunchFailed")
        op.run_once()  # whole loop stays alive

    def test_malformed_queue_message_does_not_poison(self, lattice):
        clock = FakeClock()
        from karpenter_provider_aws_tpu.interruption import FakeQueue
        q = FakeQueue("x")
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock, interruption_queue=q)
        q.send({"source": "aws.ec2",
                "detail-type": "EC2 Spot Instance Interruption Warning"})  # no detail
        assert op.interruption.reconcile() == 1
        assert len(q) == 0

    def test_cluster_name_threads_to_discovery(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0, cluster_name="prod"),
                      lattice=lattice, clock=clock)
        op.cluster.add_pod(Pod(name="p", requests={"cpu": "500m", "memory": "1Gi"}))
        rounds = op.settle()
        assert rounds < 50 and len(op.cluster.nodes) == 1

    def test_nodeclass_hash_annotation_stamped(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        op.run_once()
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        assert wk.ANNOTATION_NODECLASS_HASH in op.node_classes["default"].annotations

    def test_active_launch_template_survives_ttl(self, cloud):
        sg = SecurityGroupProvider(cloud, cloud.clock)
        ip = InstanceProfileProvider(cloud, cloud.clock)
        ami = AMIProvider(cloud, cloud.clock)
        p = LaunchTemplateProvider(cloud, sg, ip, ami, cloud.clock)
        p.ensure_all(nodeclass(), "1.29")
        for _ in range(3):   # steady use across several TTL windows
            cloud.clock.step(200)
            p.ensure_all(nodeclass(), "1.29")
            p.cleanup()
        assert len(cloud.network.launch_templates) == 2, \
            "actively-used templates must not be GC'd"

    def test_unknown_ami_family_degrades_not_crashes(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock,
                      node_classes={"bad": NodeClass(name="bad", role="r",
                                                     ami_family="Al2023")})
        op.run_once()   # must not raise
        assert not op.node_classes["bad"].status_conditions["Ready"]
        assert op.recorder.events(reason="NodeClassResolveFailed")

    def test_negative_budget_rejected(self):
        from karpenter_provider_aws_tpu.apis.objects import DisruptionBudget
        pool = NodePool(name="p", disruption=NodePoolDisruption(
            budgets=[DisruptionBudget(nodes="-10%")]))
        with pytest.raises(AdmissionError):
            admit_node_pool(pool)


class TestAMIDeprecation:
    def test_deprecated_ami_excluded_from_defaults(self, cloud):
        """A newer-but-deprecated image must not win default resolution."""
        p = AMIProvider(cloud, cloud.clock)
        nc = nodeclass()
        resolved = {a.arch: a.id for a in p.list(nc, "1.29")}
        # plant a deprecated image newer than the current amd64 default
        cloud.network.images["ami-deprecated"] = Image(
            id="ami-deprecated", name="newer-but-pulled", arch="amd64",
            creation_date=9e9, deprecated=True)
        # alias the SSM default parameter at it (simulates a bad publish)
        fam_params = list(cloud.network.ssm_parameters)
        for k in fam_params:
            if "amazon-linux-2023" in k and "x86_64" in k:
                cloud.network.ssm_parameters[k] = "ami-deprecated"
        p.reset()
        resolved2 = {a.arch: a.id for a in p.list(nc, "1.29")}
        assert resolved2.get("amd64") != "ami-deprecated"
        # arm64 resolution unaffected
        assert resolved2.get("arm64") == resolved.get("arm64")


class TestSubnetInflightExpiry:
    def test_bookings_expire_with_describe_window(self, cloud):
        """In-flight IP bookings decay after the subnet cache TTL, when a
        refreshed describe would reflect them for real (subnet.go:148-204)."""
        from karpenter_provider_aws_tpu.providers.subnet import SUBNET_TTL
        p = SubnetProvider(cloud, cloud.clock)
        nc = nodeclass()
        chosen = p.zonal_subnets_for_launch(nc)
        zone = sorted(chosen)[0]
        sid = chosen[zone].id
        # book out nearly every IP in the chosen subnet
        p.update_inflight_ips(sid, ips=245)
        free_now = chosen[zone].available_ips - p._inflight_for(sid)
        assert free_now <= 5
        cloud.clock.step(SUBNET_TTL + 1)
        assert p._inflight_for(sid) == 0


class TestLaunchTemplateFailover:
    def _providers(self, cloud):
        sg = SecurityGroupProvider(cloud, cloud.clock)
        prof = InstanceProfileProvider(cloud, cloud.clock)
        ami = AMIProvider(cloud, cloud.clock)
        return LaunchTemplateProvider(cloud, sg, prof, ami, cloud.clock)

    def test_standby_hydrates_instead_of_recreating(self, cloud):
        """A replica taking over leadership hydrates existing templates
        from the cloud (launchtemplate.go:355-370) and ensure_all reuses
        them instead of re-creating."""
        nc = nodeclass()
        lt1 = self._providers(cloud).ensure_all(nc, "1.29")
        n_before = len(cloud.network.launch_templates)
        standby = self._providers(cloud)
        hydrated = standby.hydrate()
        assert hydrated == n_before
        lt2 = standby.ensure_all(nc, "1.29")
        assert len(cloud.network.launch_templates) == n_before
        assert {t.name for t in lt1} == {t.name for t in lt2}

    def test_distinct_cluster_dns_distinct_templates(self, cloud):
        """Per-pool kubelet ClusterDNS parameterizes the userdata, so two
        pools with different DNS launch from different templates."""
        nc = nodeclass()
        p = self._providers(cloud)
        a = {t.name for t in p.ensure_all(nc, "1.29", cluster_dns="10.100.0.10")}
        b = {t.name for t in p.ensure_all(nc, "1.29", cluster_dns="fd00::53")}
        assert a.isdisjoint(b)

    def test_windows_resolves_amd64_only(self, cloud):
        nc = nodeclass(ami_family="Windows")
        lts = self._providers(cloud).ensure_all(nc, "1.29")
        archs = {cloud.network.images[t.image_id].arch for t in lts}
        assert archs == {"amd64"}


class TestPricingControllerCadence:
    def test_refresh_every_12h_only(self, lattice):
        from karpenter_provider_aws_tpu.providers.pricing import (
            PRICING_REFRESH_SECONDS, PricingController)
        clock = FakeClock()  # epoch (1e6 s) already exceeds the window
        p = PricingProvider(lattice, clock)
        c = PricingController(p, clock)
        v0 = lattice.price_version
        assert c.reconcile()           # first pass refreshes
        assert lattice.price_version > v0
        v1 = lattice.price_version
        clock.step(PRICING_REFRESH_SECONDS / 2)
        assert not c.reconcile()       # mid-window: no refresh
        assert lattice.price_version == v1
        clock.step(PRICING_REFRESH_SECONDS)
        assert c.reconcile()           # past the window
        assert lattice.price_version > v1


class TestIsolatedVPC:
    def test_od_overlay_skipped_but_spot_applies(self, lattice):
        """ISOLATED_VPC: the Pricing API (no VPC endpoint) is never
        consulted — OD overlays are dropped and static prices serve —
        while spot prices (DescribeSpotPriceHistory, an EC2 API with a
        VPC endpoint) still update (reference pricing.go:150-163)."""
        p = PricingProvider(lattice, FakeClock(), isolated_vpc=True)
        base = p.on_demand_price("m5.large")
        assert p.update_on_demand_pricing({"m5.large": 99.0}) == 0
        assert p.on_demand_price("m5.large") == base
        zone = lattice.zones[0]
        assert p.update_spot_pricing({("m5.large", zone): 0.011}) == 1
        assert p.spot_price("m5.large", zone) == pytest.approx(0.011)
        p.reset()

    def test_option_env_layer(self, monkeypatch):
        from karpenter_provider_aws_tpu.operator.options import Options
        monkeypatch.setenv("ISOLATED_VPC", "true")
        assert Options.from_env().isolated_vpc
