"""Scale / soak / chaos harness (the reference's stratum-4 analog).

FakeClock-driven ports of the reference scale suite:
- node-dense 500-node scale-up (1 pod/node via hostname anti-affinity),
  ref test/suites/scale/provisioning_test.go:72-118
- pod-dense scale-up (110 pods/node via kubelet maxPods, .large sizes),
  ref provisioning_test.go:119-157
- the deprovisioning matrix — consolidation, emptiness, expiration and
  drift running simultaneously across four NodePools, plus interruption —
  ref deprovisioning_test.go:113-120,327-681
- ICE chaos during scale-up (capacity restored mid-flight)

Every scenario asserts convergence AND the leak invariants: all pods
bound, every running cloud instance belongs to a live claim, every claim
has a registered node, nothing orphaned.
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOp, Pod, Requirement
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import NodePoolDisruption, PodAffinityTerm
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
from karpenter_provider_aws_tpu.interruption import FakeQueue, spot_interruption
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.lattice.overhead import KubeletConfiguration
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


class Harness:
    """One scale scenario over either writer stratum.

    ``direct``: the deterministic simulation stratum (DirectWriter,
    mutations straight into the ClusterState mirror). ``api``: the
    envtest analog — every mutation this harness makes goes through the
    typed client against the fake apiserver, controllers write through
    ApiWriter, and the mirror only changes when informers deliver watch
    events. The reference's controllers only exist behind the API
    (cmd/controller/main.go:47-53), so the API stratum is where
    informer-lag and conflict-retry bugs reproduce — running the SAME
    500-node matrix/storm/chaos scenarios in both strata is the point
    (round-5 item: API mode as the primary stratum at scale)."""

    def __init__(self, lattice, clock, stratum, node_pools=None,
                 options=None, cloud=None, interruption_queue=None):
        self.stratum = stratum
        self.client = None
        kw = dict(options=options or Options(registration_delay=1.0),
                  lattice=lattice, clock=clock,
                  cloud=cloud or FakeCloud(clock),
                  node_pools=node_pools,
                  interruption_queue=interruption_queue)
        if stratum == "api":
            from karpenter_provider_aws_tpu.kube import (FakeAPIServer,
                                                         KubeClient)
            server = FakeAPIServer(clock=clock)
            kw["api_server"] = server
            self.op = Operator(**kw)
            self.client = KubeClient(server)
        else:
            self.op = Operator(**kw)

    def __getattr__(self, name):
        return getattr(self.op, name)

    # ---- mutations through the stratum's proper seam -----------------

    def add_pod(self, pod: Pod) -> None:
        if self.client is not None:
            self.client.create_pod(pod)
        else:
            self.op.cluster.add_pod(pod)

    def delete_pod(self, name: str) -> None:
        if self.client is not None:
            self.client.delete_pod(name)
        else:
            self.op.cluster.delete_pod(name)

    def add_pdb(self, pdb) -> None:
        if self.client is not None:
            self.client.create_pdb(pdb)
        else:
            self.op.cluster.add_pdb(pdb)

    def update_pool(self, pool) -> None:
        """Template change (drift): server-side in API mode so the config
        watch delivers it, in-place in direct mode."""
        if self.client is not None:
            self.client.update_nodepool(pool)

    def assert_mirror_consistent(self) -> None:
        """API stratum: the informer-fed mirror agrees with the server."""
        if self.client is None:
            return
        assert ({c.name for c in self.client.list_nodeclaims()}
                == set(self.op.cluster.claims))
        assert ({n.name for n in self.client.list_nodes()}
                == set(self.op.cluster.nodes))
        assert ({p.name for p in self.client.list_pods()}
                == set(self.op.cluster.pods))


@pytest.fixture(params=["direct", "api"])
def stratum(request):
    return request.param


def assert_no_leaks(env):
    """Zero leaked instances / claims / nodes (the scale suite's core
    post-condition: EventuallyExpect...Count equalities + cleanup)."""
    running = {i.id: i for i in env.cloud.instances.values()
               if i.state == "running"}
    live_claims = {c.name: c for c in env.cluster.claims.values()
                   if not c.deletion_timestamp}
    # every live claim's instance is running
    for claim in live_claims.values():
        assert claim.provider_id, f"claim {claim.name} never launched"
        iid = parse_instance_id(claim.provider_id)
        assert iid in running, f"claim {claim.name} instance {iid} not running"
    # every running instance belongs to a live claim (no leaked instances)
    claim_iids = {parse_instance_id(c.provider_id)
                  for c in live_claims.values() if c.provider_id}
    for iid in running:
        assert iid in claim_iids, f"instance {iid} leaked (no claim)"
    # every live claim has a registered node
    for claim in live_claims.values():
        assert env.cluster.node_for_claim(claim.name) is not None, \
            f"claim {claim.name} has no node"


def assert_all_bound(env):
    unbound = [p.name for p in env.cluster.pods.values()
               if not p.is_daemonset and p.node_name is None]
    assert not unbound, f"{len(unbound)} pods unbound: {unbound[:5]}"


def converge(env, rounds, step=2.0):
    """Drive the full controller loop; stop early once quiescent (no
    pending pods, no in-flight claims, no in-flight disruptions)."""
    for _ in range(rounds):
        env.run_once()
        env.clock.step(step)
        if (not env.cluster.pending_pods()
                and not env.disruption._in_flight
                and all(env.cluster.node_for_claim(c.name) is not None
                        for c in env.cluster.claims.values()
                        if not c.deletion_timestamp)):
            # one extra pass so terminations finalize
            env.run_once()
            return


class TestNodeDenseScaleUp:
    def test_500_nodes_one_pod_each(self, lattice, stratum):
        """provisioning_test.go:82-118: 500 replicas with hostname
        anti-affinity -> exactly 500 nodes, every pod bound — in BOTH
        writer strata."""
        clock = FakeClock()
        env = Harness(lattice, clock, stratum,
                      node_pools=[NodePool(name="default")])
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("app", "dense"),), anti=True)]
        for i in range(500):
            env.add_pod(Pod(
                name=f"d-{i}", labels={"app": "dense"},
                requests={"cpu": "250m", "memory": "256Mi"},
                pod_affinity=list(anti)))
        env.settle(max_rounds=30)
        assert len(env.cluster.claims) == 500
        assert len(env.cluster.nodes) == 500
        assert_all_bound(env)
        assert_no_leaks(env)
        # one pod per node (the anti-affinity contract held at scale)
        per_node = {}
        for p in env.cluster.pods.values():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert max(per_node.values()) == 1
        env.assert_mirror_consistent()

    def test_pod_dense_110_per_node(self, lattice, stratum):
        """provisioning_test.go:119-157: 6600 pods at 110/node density on
        .large sizes -> 60 nodes."""
        replicas_per_node, node_count = 110, 60
        kc = KubeletConfiguration(max_pods=replicas_per_node)
        dense_lattice = build_lattice(
            [s for s in build_catalog() if s.family in _FAMILIES], kc=kc)
        clock = FakeClock()
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_INSTANCE_SIZE, ReqOp.IN, ("large",)),
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
        env = Harness(dense_lattice, clock, stratum, node_pools=[pool])
        for i in range(replicas_per_node * node_count):
            env.add_pod(Pod(name=f"p-{i}",
                            requests={"cpu": "10m", "memory": "50Mi"}))
        env.settle(max_rounds=30)
        assert_all_bound(env)
        assert_no_leaks(env)
        assert len(env.cluster.nodes) == node_count
        # density held: no node exceeds maxPods
        per_node = {}
        for p in env.cluster.pods.values():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert max(per_node.values()) <= replicas_per_node
        env.assert_mirror_consistent()


class TestDeprovisioningMatrix:
    """deprovisioning_test.go:113-120: consolidation, emptiness,
    expiration, and drift run SIMULTANEOUSLY across four NodePools."""

    METHODS = ("consolidation", "emptiness", "expiration", "drift")

    def _matrix_env(self, lattice, stratum, nodes_per_pool=5,
                    pods_per_node=4):
        clock = FakeClock()
        pools = []
        for m in self.METHODS:
            pools.append(NodePool(
                name=m, labels={"testing/deprovisioning-type": m},
                requirements=[Requirement(wk.LABEL_CAPACITY_TYPE,
                                          ReqOp.IN, ("on-demand",))],
                disruption=NodePoolDisruption(
                    consolidate_after=30.0,
                    expire_after=100000.0 if m == "expiration" else None)))
        env = Harness(lattice, clock, stratum, node_pools=pools)
        # pods pinned to their pool via nodeSelector; hostname
        # anti-affinity within a group caps one GROUP pod per node, sized
        # so pods_per_node groups fill a node
        for m in self.METHODS:
            for g in range(pods_per_node):
                anti = [PodAffinityTerm(
                    topology_key=wk.LABEL_HOSTNAME,
                    label_selector=(("grp", f"{m}-{g}"),), anti=True)]
                for i in range(nodes_per_pool):
                    env.add_pod(Pod(
                        name=f"{m}-{g}-{i}", labels={"grp": f"{m}-{g}"},
                        node_selector={"testing/deprovisioning-type": m},
                        requests={"cpu": "800m", "memory": "1536Mi"},
                        pod_affinity=list(anti)))
        env.settle(max_rounds=40)
        return env

    def test_all_methods_simultaneously(self, lattice, stratum):
        nodes_per_pool = 5
        env = self._matrix_env(lattice, stratum,
                               nodes_per_pool=nodes_per_pool)
        assert_all_bound(env)
        assert_no_leaks(env)
        by_pool_before = {m: [c for c in env.cluster.claims.values()
                              if c.node_pool == m] for m in self.METHODS}
        for m in self.METHODS:
            assert len(by_pool_before[m]) >= nodes_per_pool - 1

        # fire every method at once:
        # consolidation: shrink its pods so they repack onto fewer nodes
        for p in [p for p in list(env.cluster.pods.values())
                  if p.name.startswith("consolidation-")]:
            env.delete_pod(p.name)
        for i in range(3):
            env.add_pod(Pod(
                name=f"consolidation-tiny-{i}",
                node_selector={"testing/deprovisioning-type": "consolidation"},
                requests={"cpu": "100m", "memory": "128Mi"}))
        # emptiness: drain every pod from its pool
        for p in [p for p in list(env.cluster.pods.values())
                  if p.name.startswith("emptiness-")]:
            env.delete_pod(p.name)
        # expiration: jump the clock past expire_after (100000s)
        env.clock.step(100001)
        # drift: mutate the pool template so the stamped hash mismatches
        # (API stratum: server-side, so the config watch delivers it)
        env.node_pools["drift"].labels["drift-marker"] = "v2"
        env.update_pool(env.node_pools["drift"])

        converge(env, rounds=300, step=5.0)
        assert_all_bound(env)
        assert_no_leaks(env)

        # emptiness pool fully deprovisioned
        assert not [c for c in env.cluster.claims.values()
                    if c.node_pool == "emptiness"]
        # consolidation pool shrank
        cons = [c for c in env.cluster.claims.values()
                if c.node_pool == "consolidation"]
        assert 1 <= len(cons) < nodes_per_pool
        # expiration pool: every original claim replaced
        old = {c.name for c in by_pool_before["expiration"]}
        now = {c.name for c in env.cluster.claims.values()
               if c.node_pool == "expiration"}
        assert not (old & now), "expired claims still alive"
        assert now, "expiration pool has no replacement capacity"
        # drift pool: every claim stamped with the NEW template hash
        from karpenter_provider_aws_tpu.controllers.provisioning import nodepool_hash
        want = nodepool_hash(env.node_pools["drift"])
        for c in env.cluster.claims.values():
            if c.node_pool == "drift":
                assert c.annotations.get(wk.ANNOTATION_NODEPOOL_HASH) == want
        env.assert_mirror_consistent()

    def test_interruption_storm(self, lattice, stratum):
        """deprovisioning_test.go:681+ scaled: spot-interrupt EVERY node at
        once; all are drained, replaced, and pods rebind — both strata."""
        clock = FakeClock()
        queue = FakeQueue("interruptions")
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",))])
        env = Harness(lattice, clock, stratum, node_pools=[pool],
                      interruption_queue=queue)
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("app", "storm"),), anti=True)]
        for i in range(10):
            env.add_pod(Pod(
                name=f"s-{i}", labels={"app": "storm"},
                requests={"cpu": "500m", "memory": "1Gi"},
                pod_affinity=list(anti)))
        env.settle(max_rounds=30)
        assert len(env.cluster.claims) == 10
        interrupted = {parse_instance_id(c.provider_id)
                       for c in env.cluster.claims.values()}
        for iid in interrupted:
            queue.send(spot_interruption(iid))
        converge(env, rounds=120, step=3.0)
        assert_all_bound(env)
        assert_no_leaks(env)
        # every interrupted instance is gone; capacity was replaced
        for c in env.cluster.claims.values():
            assert parse_instance_id(c.provider_id) not in interrupted
        assert len(env.cluster.claims) == 10
        env.assert_mirror_consistent()


class TestIceChaos:
    def test_scale_up_through_ice(self, lattice, stratum):
        """Chaos: the cheapest offerings are ICE'd mid-scale-up; the
        launch path falls through its flexible-type overrides, the ICE
        cache masks the dead offerings, and the wave still lands — in
        both writer strata."""
        clock = FakeClock()
        cloud = FakeCloud(clock)
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
        env = Harness(lattice, clock, stratum, node_pools=[pool],
                      cloud=cloud)
        # pre-compute what an unconstrained solve would choose, then ICE it
        probe = Operator(options=Options(registration_delay=1.0),
                         lattice=lattice, cloud=FakeCloud(FakeClock()),
                         clock=FakeClock(), node_pools=[
                             NodePool(name="default", requirements=[
                                 Requirement(wk.LABEL_CAPACITY_TYPE,
                                             ReqOp.IN, ("on-demand",))])])
        for i in range(40):
            probe.cluster.add_pod(Pod(name=f"x-{i}",
                                      requests={"cpu": "1", "memory": "2Gi"}))
        probe.settle(max_rounds=20)
        first_choice = {(c.instance_type, c.zone)
                        for c in probe.cluster.claims.values()}
        for itype, zone in first_choice:
            cloud.set_capacity("on-demand", itype, zone, 0)

        for i in range(40):
            env.add_pod(Pod(name=f"x-{i}",
                            requests={"cpu": "1", "memory": "2Gi"}))
        env.settle(max_rounds=40)
        assert_all_bound(env)
        assert_no_leaks(env)
        # nothing landed on a dead offering
        for c in env.cluster.claims.values():
            assert cloud.capacity_pools.get(("on-demand", c.instance_type, c.zone)) != 0
        # the ICE cache remembers at least one dead offering
        assert any(True for _ in env.unavailable.entries())
        env.assert_mirror_consistent()


class TestKitchenSink:
    """Every major subsystem interacting at once: a reserved limited
    pool, Exists-segregated teams, a custom-label ratio spread, PDBs,
    a scheduled disruption freeze, spot interruptions, and ICE chaos —
    converging with zero leaks and every invariant held."""

    def test_everything_at_once(self, lattice, stratum):
        from karpenter_provider_aws_tpu.apis import PodDisruptionBudget
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, TopologySpreadConstraint)
        clock = FakeClock(start=12 * 86400.0 + 1800.0)  # 00:30 UTC — the
        # teams pool's nightly freeze window (00:00-01:00) is LIVE for
        # the whole ~5-minute simulated timeline
        queue = FakeQueue("interruptions")
        pools = [
            # reserved capacity first: pinned type, capped, weight 50
            NodePool(name="reserved", weight=50, limits={"cpu": "8"},
                     requirements=[
                         Requirement(wk.LABEL_INSTANCE_TYPE, ReqOp.IN,
                                     ("c5.2xlarge",)),
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                     ("on-demand",))]),
            # team segregation via Exists; nightly maintenance freeze
            NodePool(name="teams",
                     requirements=[
                         Requirement("company.com/team", ReqOp.EXISTS, ()),
                         Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                     ("on-demand",))],
                     disruption=NodePoolDisruption(
                         consolidate_after=10.0,
                         budgets=[DisruptionBudget(
                             nodes="0", schedule="0 0 * * *",
                             duration=3600.0)])),
            # the 2:1 spot/od ratio split pair
            NodePool(name="spot-spread", requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",)),
                Requirement("cs", ReqOp.IN, ("2", "3"))]),
            NodePool(name="od-spread", requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",)),
                Requirement("cs", ReqOp.IN, ("1",))]),
        ]
        env = Harness(lattice, clock, stratum, node_pools=pools,
                      interruption_queue=queue)
        # workloads
        for i in range(6):   # generic (no selector) -> reserved fills
            env.add_pod(Pod(  # first, overflow spills elsewhere
                name=f"gen{i}", requests={"cpu": "2", "memory": "2Gi"}))
        for t in ("team-a", "team-b"):
            for i in range(2):
                env.add_pod(Pod(
                    name=f"{t}-{i}", labels={"app": t},
                    requests={"cpu": "500m", "memory": "1Gi"},
                    node_selector={"company.com/team": t}))
        for i in range(6):   # ratio-spread workload
            env.add_pod(Pod(
                name=f"web{i}", labels={"app": "web"},
                requests={"cpu": "1", "memory": "2Gi"},
                topology_spread=[TopologySpreadConstraint(
                    max_skew=1, topology_key="cs",
                    label_selector=(("app", "web"),))]))
        env.add_pdb(PodDisruptionBudget(
            name="web-pdb", label_selector={"app": "web"}, max_unavailable=1))
        env.settle(max_rounds=60)
        assert_all_bound(env)
        assert_no_leaks(env)

        # invariants
        by_pool = {}
        for c in env.cluster.claims.values():
            by_pool.setdefault(c.node_pool, []).append(c)
        assert by_pool.get("reserved"), "reserved pool never engaged"
        reserved_cpu = sum(
            lattice.capacity[lattice.name_to_idx[c.instance_type]][0]
            for c in by_pool["reserved"])
        assert 0 < reserved_cpu <= 8000
        # the nightly freeze is LIVE: the teams pool admits zero
        # voluntary disruptions right now
        assert env.disruption._allowed_disruptions(
            env.node_pools["teams"], "Underutilized") == 0
        team_nodes = {}
        for c in by_pool.get("teams", []):
            team_nodes.setdefault(c.labels.get("company.com/team"), []).append(c)
        assert set(team_nodes) == {"team-a", "team-b"}
        web_by_domain = {}
        for node_name, pods in env.cluster.pods_by_node().items():
            d = env.cluster.nodes[node_name].labels.get("cs")
            for p in pods:
                if p.labels.get("app") == "web":
                    web_by_domain[d] = web_by_domain.get(d, 0) + 1
        assert set(web_by_domain) == {"1", "2", "3"}
        assert max(web_by_domain.values()) - min(web_by_domain.values()) <= 1

        # chaos: spot-interrupt every spot node; drains respect the web
        # PDB (maxUnavailable=1) yet all pods converge back bound
        for c in list(env.cluster.claims.values()):
            if c.capacity_type == "spot":
                queue.send(spot_interruption(parse_instance_id(c.provider_id)))
        converge(env, rounds=80, step=2.0)
        assert_all_bound(env)
        assert_no_leaks(env)
        env.assert_mirror_consistent()


class TestApiModeScale:
    """The envtest stratum at scale: a few hundred pods through the
    watch/list protocol, then a deletion wave consolidating down — the
    apiserver seam under the same load shapes the direct stratum runs."""

    def test_scale_up_and_consolidate_through_api(self, lattice):
        from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, clock=clock, api_server=server)
        client = KubeClient(server)
        for i in range(300):
            client.create_pod(Pod(
                name=f"w{i}", requests={"cpu": "1", "memory": "2Gi"}))
        op.settle(max_rounds=80)
        pods = client.list_pods()
        assert all(p.node_name for p in pods), \
            sum(1 for p in pods if not p.node_name)
        n_before = len(client.list_nodes())
        assert n_before >= 3
        # mirror/server agreement at scale
        assert {n.name for n in client.list_nodes()} == set(op.cluster.nodes)
        # delete 80% through the API → consolidation shrinks the fleet
        for i in range(240):
            client.delete_pod(f"w{i}")
        for _ in range(50):
            op.run_once()
            clock.step(30.0)
        op.settle(max_rounds=40)   # land any mid-flight drain/replace
        survivors = client.list_pods()
        assert len(survivors) == 60 and all(p.node_name for p in survivors)
        n_after = len(client.list_nodes())
        assert n_after < n_before, (n_before, n_after)
        assert {c.name for c in client.list_nodeclaims()} == \
            set(op.cluster.claims)
