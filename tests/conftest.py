"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI, so sharding tests run against
XLA's host-platform device virtualization (the same path the driver's
dryrun_multichip uses). Must run before jax initializes its backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# the environment's sitecustomize force-registers the 'axon' TPU platform
# ahead of JAX_PLATFORMS; pin the cpu backend explicitly for tests
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
