"""Pipelined-solve tests (docs/concepts/performance.md "Pipelining &
the tunnel link").

The overlapped solve path exists to hide the tunneled link's ~100 ms
round trip, and its entire safety argument is DETERMINISM: async
dispatch, double-buffered wave uploads, and the resident-input delta
cache may only move work off the critical path — never change a single
byte of the resulting plan. These tests pin that contract:

- pipelined vs sequential solves produce byte-identical NodePlans
  (node placements, prices, feasible sets) on cfg5-shaped and
  wave-split problems,
- the degradation ladder still engages under FaultInjector device
  failures mid-pipeline, with no half-decoded plan leaking,
- the resident-input delta cache returns exactly the uploaded bytes
  under deltas, bulk changes, layout growth, and key collisions,
- the Solve admission window (batcher/solve_window.py) coalesces
  concurrent callers and isolates per-caller failures,
- an idle batcher bucket parks without periodic wakeups and measures
  its max window from the FIRST arrival.
"""

import json
import threading
import time

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.apis import serde
from karpenter_provider_aws_tpu.batcher import (Batcher, BatcherOptions,
                                                SolveWindow)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import (FaultInjector, Solver,
                                               build_problem)
from karpenter_provider_aws_tpu.solver.pipeline import (STAGES,
                                                        ResidentInputCache,
                                                        StageTimer)

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


def diverse_pods(n, prefix="u"):
    """n pods with n DISTINCT scheduling signatures."""
    return [Pod(name=f"{prefix}{i}",
                requests={"cpu": f"{100 + i}m",
                          "memory": f"{256 + (i % 8) * 64}Mi"})
            for i in range(n)]


def cfg5_shaped_pods(n=3000):
    """A scaled cfg5 shape: a few dozen signatures over many pods, with
    selector variety — the north-star workload's structure without its
    50k-pod bulk."""
    from karpenter_provider_aws_tpu.apis import wellknown as wk
    rng = np.random.default_rng(0)
    shapes = []
    for _ in range(30):
        cpu = int(rng.choice([100, 250, 500, 1000, 2000]))
        mem = int(rng.choice([256, 512, 1024, 2048, 4096]))
        sel = {}
        if rng.random() < 0.25:
            sel[wk.LABEL_INSTANCE_CATEGORY] = str(rng.choice(["m", "c", "r"]))
        shapes.append(({"cpu": f"{cpu}m", "memory": f"{mem}Mi"}, sel))
    counts = rng.multinomial(n, np.ones(30) / 30)
    pods = []
    for s, ((req, sel), k) in enumerate(zip(shapes, counts)):
        pods += [Pod(name=f"s{s}-{i}", requests=req, node_selector=sel)
                 for i in range(k)]
    return pods


def canonical(plan) -> str:
    """The plan's byte-comparable identity: everything except wall-clock
    timings and path provenance, which NAME the path taken and so
    legitimately differ between the two modes (one shared key list —
    serde.plan_semantic_dict — so every parity site stays in sync)."""
    return json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)


def assert_nothing_dropped(plan, n_pods):
    scheduled = (sum(len(x.pods) for x in plan.new_nodes)
                 + sum(len(v) for v in plan.existing_assignments.values()))
    assert scheduled + len(plan.unschedulable) == n_pods


class TestPlanParity:
    """Pipelined and sequential solves are byte-identical — the overlap
    moves work in time, never in effect."""

    def test_cfg5_shaped_parity(self, lattice):
        pods = cfg5_shaped_pods(3000)
        pools = [NodePool(name="default")]
        seq = Solver(lattice, pipeline=False)
        pipe = Solver(lattice, pipeline=True)
        p_seq = seq.solve(build_problem(pods, pools, lattice))
        p_pipe = pipe.solve(build_problem(pods, pools, lattice))
        assert not p_seq.pipelined and p_pipe.pipelined
        assert canonical(p_seq) == canonical(p_pipe)
        assert_nothing_dropped(p_pipe, len(pods))
        assert pipe.pipeline_stats["async_solves"] >= 1

    def test_wave_split_parity(self, lattice):
        """The double-buffered wave pipeline prefetches wave k+1's inputs
        inside wave k's compute window and still produces the sequential
        planner's exact plan (carry state is handled at the stage
        boundary)."""
        pods = diverse_pods(200)
        pools = [NodePool(name="default")]
        seq = Solver(lattice, pipeline=False)
        pipe = Solver(lattice, pipeline=True)
        seq.inject_faults(FaultInjector(g_limit=64))
        pipe.inject_faults(FaultInjector(g_limit=64))
        p_seq = seq.solve(build_problem(pods, pools, lattice))
        p_pipe = pipe.solve(build_problem(pods, pools, lattice))
        assert p_seq.solver_path == p_pipe.solver_path == "wave-split"
        assert p_seq.waves == p_pipe.waves == 4
        assert canonical(p_seq) == canonical(p_pipe)
        # every wave but the last was prefetched during its predecessor
        assert pipe.pipeline_stats["prefetched_waves"] == p_pipe.waves - 1
        assert seq.pipeline_stats["prefetched_waves"] == 0

    def test_steady_state_delta_cache_engages(self, lattice):
        """A reconcile-loop-shaped workload (the same problem re-solved)
        re-uploads ZERO blocks after the first pass, and every pass still
        yields the identical plan."""
        pods = cfg5_shaped_pods(1500)
        pools = [NodePool(name="default")]
        pipe = Solver(lattice, pipeline=True)
        first = pipe.solve(build_problem(pods, pools, lattice))
        shipped_after_first = pipe._resident.blocks_shipped
        for _ in range(2):
            again = pipe.solve(build_problem(pods, pools, lattice))
            assert canonical(again) == canonical(first)
        stats = pipe._resident.stats()
        assert stats["hits"] >= 2
        # identical fused inputs → the delta shipped nothing new
        assert stats["blocks_shipped"] == shipped_after_first
        assert stats["blocks_resident"] > 0

    def test_pipeline_toggle_runtime(self, lattice):
        """set_pipeline flips the path live; both directions keep plan
        identity."""
        pods = diverse_pods(40)
        pools = [NodePool(name="default")]
        s = Solver(lattice, pipeline=True)
        a = s.solve(build_problem(pods, pools, lattice))
        s.set_pipeline(False)
        b = s.solve(build_problem(pods, pools, lattice))
        assert a.pipelined and not b.pipelined
        assert canonical(a) == canonical(b)


class TestFaultsMidPipeline:
    """Device failures inside the overlapped path: the ladder engages
    exactly as in sequential mode and no half-decoded plan leaks."""

    def test_transient_device_error_parity(self, lattice):
        pods = diverse_pods(24)
        pools = [NodePool(name="default")]
        seq = Solver(lattice, pipeline=False)
        pipe = Solver(lattice, pipeline=True)
        seq.inject_faults(FaultInjector(device_errors=1))
        pipe.inject_faults(FaultInjector(device_errors=1))
        p_seq = seq.solve(build_problem(pods, pools, lattice))
        p_pipe = pipe.solve(build_problem(pods, pools, lattice))
        assert p_pipe.device_retries == p_seq.device_retries == 1
        assert p_pipe.solver_path == "device" and not p_pipe.degraded
        assert canonical(p_seq) == canonical(p_pipe)

    def test_wave_fault_mid_pipeline(self, lattice):
        """A device error while waves are in flight: the whole solve
        retries (the ladder), then the wave pipeline completes — nothing
        dropped, parity intact."""
        pods = diverse_pods(150)
        pools = [NodePool(name="default")]
        seq = Solver(lattice, pipeline=False)
        pipe = Solver(lattice, pipeline=True)
        seq.inject_faults(FaultInjector(g_limit=64, device_errors=1))
        pipe.inject_faults(FaultInjector(g_limit=64, device_errors=1))
        p_seq = seq.solve(build_problem(pods, pools, lattice))
        p_pipe = pipe.solve(build_problem(pods, pools, lattice))
        assert p_pipe.solver_path == "wave-split"
        assert p_pipe.device_retries == 1
        assert_nothing_dropped(p_pipe, 150)
        assert canonical(p_seq) == canonical(p_pipe)

    def test_persistent_failure_reaches_host_ffd(self, lattice):
        """The bottom rung under pipelining: host FFD engages, the plan
        is complete (not a torn pipeline state), and it matches the
        sequential solver's fallback byte for byte."""
        pods = diverse_pods(30)
        pools = [NodePool(name="default")]
        seq = Solver(lattice, pipeline=False)
        pipe = Solver(lattice, pipeline=True)
        seq.inject_faults(FaultInjector(device_errors=10))
        pipe.inject_faults(FaultInjector(device_errors=10))
        p_seq = seq.solve(build_problem(pods, pools, lattice))
        p_pipe = pipe.solve(build_problem(pods, pools, lattice))
        assert p_pipe.solver_path == "host-ffd"
        assert p_pipe.degraded and p_pipe.degraded_reason == "device-error"
        assert_nothing_dropped(p_pipe, 30)
        assert not p_pipe.unschedulable
        assert canonical(p_seq) == canonical(p_pipe)


class TestStageTimings:
    def test_plan_carries_stage_ms(self, lattice):
        pods = diverse_pods(20)
        plan = Solver(lattice, pipeline=True).solve(
            build_problem(pods, [NodePool(name="default")], lattice))
        assert plan.stage_ms
        assert set(plan.stage_ms) <= set(STAGES)
        for stage in ("compute", "download", "decode"):
            assert plan.stage_ms[stage] >= 0.0
        assert all(v >= 0.0 for v in plan.stage_ms.values())

    def test_sequential_plan_also_timed(self, lattice):
        plan = Solver(lattice, pipeline=False).solve(
            build_problem(diverse_pods(20), [NodePool(name="default")],
                          lattice))
        assert plan.stage_ms and not plan.pipelined

    def test_wave_split_accumulates_stages(self, lattice):
        s = Solver(lattice, pipeline=True)
        s.inject_faults(FaultInjector(g_limit=64))
        plan = s.solve(build_problem(diverse_pods(200),
                                     [NodePool(name="default")], lattice))
        assert plan.waves == 4
        # four waves' worth of compute accumulated into one plan record
        assert plan.stage_ms["compute"] > 0.0
        assert plan.stage_ms["upload"] >= 0.0

    def test_serde_roundtrip_preserves_stages(self, lattice):
        plan = Solver(lattice, pipeline=True).solve(
            build_problem(diverse_pods(12), [NodePool(name="default")],
                          lattice))
        back = serde.plan_from_dict(
            json.loads(json.dumps(serde.plan_to_dict(plan))))
        assert back.pipelined == plan.pipelined is True
        assert set(back.stage_ms) == set(plan.stage_ms)
        for k, v in plan.stage_ms.items():
            assert back.stage_ms[k] == pytest.approx(v, abs=1e-3)

    def test_stage_timer_accumulates_and_merges(self):
        t = StageTimer()
        with t.span("upload"):
            pass
        with t.span("upload"):
            pass
        t.add("compute", 0.002)
        other = StageTimer()
        other.add("compute", 0.001)
        other.add("decode", 0.004)
        t.merge(other.ms)
        assert t.ms["compute"] == pytest.approx(3.0)
        assert t.ms["decode"] == pytest.approx(4.0)
        assert t.ms["upload"] >= 0.0

    def test_provisioner_observes_stage_metric(self, lattice):
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        for p in diverse_pods(10):
            op.cluster.add_pod(p)
        op.provisioner.provision_once()
        m = op.metrics.get("karpenter_solver_stage_duration_seconds")
        assert m is not None
        assert m.count(stage="compute") >= 1
        assert m.count(stage="decode") >= 1


class TestResidentInputCache:
    def _roundtrip(self, cache, key, buf):
        out = np.asarray(cache.upload(key, buf))
        assert out.dtype == np.uint8 and out.shape == buf.shape
        np.testing.assert_array_equal(out, buf)

    def test_cold_then_delta(self):
        cache = ResidentInputCache(block=64)
        rng = np.random.default_rng(1)
        buf = rng.integers(0, 255, 1000, dtype=np.uint8)
        self._roundtrip(cache, ("k",), buf)
        assert cache.misses == 1 and cache.hits == 0
        buf2 = buf.copy()
        buf2[130:140] ^= 0xFF    # one dirty block
        self._roundtrip(cache, ("k",), buf2)
        assert cache.hits == 1
        assert 1 <= cache.blocks_shipped <= 2
        assert cache.blocks_resident > 0

    def test_identical_reupload_ships_nothing(self):
        cache = ResidentInputCache(block=64)
        buf = np.arange(500, dtype=np.uint8)
        self._roundtrip(cache, ("k",), buf)
        self._roundtrip(cache, ("k",), buf.copy())
        assert cache.hits == 1 and cache.blocks_shipped == 0

    def test_bulk_change_falls_back_to_full_upload(self):
        cache = ResidentInputCache(block=64)
        rng = np.random.default_rng(2)
        buf = rng.integers(0, 255, 4096, dtype=np.uint8)
        self._roundtrip(cache, ("k",), buf)
        flipped = (buf ^ 0xFF)   # every block dirty
        self._roundtrip(cache, ("k",), flipped)
        assert cache.misses == 2 and cache.blocks_shipped == 0

    def test_layout_growth_restores(self):
        cache = ResidentInputCache(block=64)
        self._roundtrip(cache, ("k",), np.zeros(100, np.uint8))
        self._roundtrip(cache, ("k",), np.ones(5000, np.uint8))
        assert cache.misses == 2

    def test_key_collision_is_only_a_perf_event(self):
        """Two different problems aliasing one key must still each read
        back their own bytes — the diff runs against actual content."""
        cache = ResidentInputCache(block=64)
        a = np.full(300, 7, np.uint8)
        b = np.full(300, 9, np.uint8)
        self._roundtrip(cache, ("k",), a)
        self._roundtrip(cache, ("k",), b)
        self._roundtrip(cache, ("k",), a)

    def test_eviction_bound(self):
        cache = ResidentInputCache(max_entries=4, block=64)
        for i in range(10):
            self._roundtrip(cache, ("k", i), np.full(64, i, np.uint8))
        assert len(cache._entries) <= 4


class TestSolveWindow:
    def test_concurrent_callers_coalesce_and_fan_out(self, lattice):
        solver = Solver(lattice, pipeline=True)
        window = SolveWindow(
            solver, options=BatcherOptions(idle_seconds=0.05,
                                           max_seconds=0.5, max_items=8))
        pools = [NodePool(name="default")]
        results = {}
        barrier = threading.Barrier(4)

        def call(i):
            barrier.wait()
            results[i] = window.solve_relaxed(
                diverse_pods(10 + i, prefix=f"w{i}-"), pools)

        threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert set(results) == {0, 1, 2, 3}
        for i, plan in results.items():
            # positional fan-out: each caller got ITS OWN problem's plan
            assert_nothing_dropped(plan, 10 + i)
            names = {n for node in plan.new_nodes for n in node.pods}
            assert all(n.startswith(f"w{i}-") for n in names)
        assert window.batches >= 1
        assert window.coalesced >= 2   # at least one fused drain happened

    def test_exception_isolated_to_its_caller(self, lattice):
        solver = Solver(lattice, pipeline=True)
        window = SolveWindow(
            solver, options=BatcherOptions(idle_seconds=0.05,
                                           max_seconds=0.5, max_items=8))
        pools = [NodePool(name="default")]
        outcomes = {}
        barrier = threading.Barrier(2)

        def good():
            barrier.wait()
            outcomes["good"] = window.solve_relaxed(diverse_pods(5), pools)

        def bad():
            barrier.wait()
            try:
                # not iterable pods → this caller's request fails
                window.solve_relaxed(object(), pools)
                outcomes["bad"] = None
            except Exception as e:
                outcomes["bad"] = e

        ts = [threading.Thread(target=good), threading.Thread(target=bad)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert isinstance(outcomes["bad"], Exception)
        assert_nothing_dropped(outcomes["good"], 5)

    def test_sidecar_serves_through_window(self, lattice):
        """serve(admission_window=True) wires the window in front of the
        Solve RPC path."""
        from karpenter_provider_aws_tpu.parallel.sidecar import SolverService
        solver = Solver(lattice, pipeline=True)
        svc = SolverService(solver, window=SolveWindow(solver))
        req = {"pods": [serde.pod_to_dict(p) for p in diverse_pods(6)],
               "nodePools": [serde.nodepool_to_dict(NodePool(name="default"))]}
        out = json.loads(svc.solve(json.dumps(req).encode()).decode())
        plan = serde.plan_from_dict(out)
        assert_nothing_dropped(plan, 6)
        assert svc.window.batches == 1


class TestBatcherPark:
    def test_idle_bucket_parks_without_wakeups(self):
        calls = []
        b = Batcher(lambda reqs: [calls.append(len(reqs)) or r for r in reqs],
                    BatcherOptions(idle_seconds=0.01, max_seconds=0.2))
        assert b.add("x") == "x"
        bucket = next(iter(b._buckets.values()))
        worker = bucket.thread
        assert worker is not None and worker.is_alive()
        # drained: the worker parks on the event — many idle windows
        # later it has NOT cycled (no timeout wakeups), just waits
        time.sleep(0.1)
        assert worker.is_alive()
        assert not bucket.wakeup.is_set()
        assert not bucket.pending
        # the SAME worker serves the next batch (persistent, reused)
        assert b.add("y") == "y"
        assert bucket.thread is worker
        assert calls == [1, 1]

    def test_max_window_measured_from_first_arrival(self):
        """A steady drip of arrivals inside the idle window must not
        extend the batch past max_seconds FROM THE FIRST ARRIVAL."""
        executed = threading.Event()
        b = Batcher(lambda reqs: [executed.set() or r for r in reqs],
                    BatcherOptions(idle_seconds=0.05, max_seconds=0.15,
                                   max_items=1000))
        stop = time.monotonic() + 0.6

        def drip():
            while time.monotonic() < stop and not executed.is_set():
                try:
                    b.add("d", timeout=2.0)
                    return
                except Exception:
                    return

        t0 = time.monotonic()
        threads = [threading.Thread(target=drip) for _ in range(3)]
        threads[0].start()
        time.sleep(0.04)
        threads[1].start()
        time.sleep(0.04)
        threads[2].start()
        assert executed.wait(timeout=1.0)
        elapsed = time.monotonic() - t0
        # 0.15 s max window + generous scheduling slack — far under the
        # ~0.05*N unbounded extension the drip would otherwise cause
        assert elapsed < 0.5
        for t in threads:
            t.join(5)

    def test_max_items_still_flushes_immediately(self):
        b = Batcher(lambda reqs: list(reqs),
                    BatcherOptions(idle_seconds=5.0, max_seconds=30.0,
                                   max_items=1))
        t0 = time.monotonic()
        assert b.add("x", timeout=5.0) == "x"
        assert time.monotonic() - t0 < 2.0
