"""Degradation-ladder tests (docs/concepts/degradation.md).

The paper's perf story assumes pending pods collapse to a few thousand
scheduling signatures; these tests are the adversarial counterpart: a
batch too diverse for the compiled bucket set must wave-split, and every
device-path failure mode — injected deterministically via
solver/faults.py — must land on the host-FFD fallback with metrics
incremented and ZERO pods silently dropped. Plus the satellite
robustness fixes that ride the same PR (eventsink retention re-list,
kpctl rendering, non-__init__ Pods in _selector_keys).
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.errors import (SolverCapacityError,
                                               SolverDeviceError,
                                               is_retryable_solver_error)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import (FaultInjector, Solver,
                                               build_problem, ffd_oracle)

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture()
def solver(lattice):
    # function-scoped: fault injectors and degraded counters are per-test
    # state (jit caches are process-global, so this stays cheap)
    return Solver(lattice)


def diverse_pods(n, prefix="u"):
    """n pods with n DISTINCT scheduling signatures (unique cpu requests
    defeat signature dedup the way an adversarial tenant mix would)."""
    return [Pod(name=f"{prefix}{i}",
                requests={"cpu": f"{100 + i}m",
                          "memory": f"{256 + (i % 8) * 64}Mi"})
            for i in range(n)]


def scheduled_count(plan):
    return (sum(len(x.pods) for x in plan.new_nodes)
            + sum(len(v) for v in plan.existing_assignments.values()))


def assert_nothing_dropped(plan, n_pods):
    """Every pod is either placed or explicitly unschedulable — the
    ladder's core contract: degrade latency, never drop pods silently."""
    assert scheduled_count(plan) + len(plan.unschedulable) == n_pods
    names = set()
    for node in plan.new_nodes:
        names.update(node.pods)
    for pods in plan.existing_assignments.values():
        names.update(pods)
    names.update(plan.unschedulable)
    assert len(names) == n_pods


class TestErrorTaxonomy:
    def test_capacity_terminal_device_retryable(self):
        assert not SolverCapacityError("full", axis="B").retryable
        assert SolverDeviceError("boom").retryable
        assert is_retryable_solver_error(SolverDeviceError("boom"))
        assert not is_retryable_solver_error(SolverCapacityError("full"))
        assert not is_retryable_solver_error(RuntimeError("boom"))

    def test_capacity_error_names_axis(self):
        assert SolverCapacityError("bins", axis="B").axis == "B"


class TestWaveSplit:
    def test_small_batch_stays_on_device(self, solver, lattice):
        pods = diverse_pods(24)
        plan = solver.solve(build_problem(pods, [NodePool(name="default")],
                                          lattice))
        assert plan.solver_path == "device"
        assert not plan.degraded and plan.waves == 1
        assert_nothing_dropped(plan, 24)

    def test_wave_split_engages_and_holds_cost_envelope(self, solver, lattice):
        """A batch over the (injected) group ceiling wave-splits and packs
        within the ≤2% FFD envelope — open-bin state carries between
        waves, so later waves fill earlier waves' headroom."""
        pods = diverse_pods(200)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        assert problem.G == 200
        solver.inject_faults(FaultInjector(g_limit=64))
        plan = solver.solve(problem)
        assert plan.solver_path == "wave-split"
        assert plan.degraded and plan.degraded_reason == "g-overflow"
        assert plan.waves == 4  # ceil(200 / 64)
        assert_nothing_dropped(plan, 200)
        assert not plan.unschedulable
        oracle = ffd_oracle(problem)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02
        assert solver.degraded_counts.get("wave_split", 0) == 1
        assert solver.faults.fired.get("g_overflow", 0) == 1

    def test_wave_split_fills_existing_capacity(self, solver, lattice):
        """Real existing headroom is consumed across waves exactly once
        (running usage carries), never double-booked."""
        from karpenter_provider_aws_tpu.solver import ExistingBin
        from karpenter_provider_aws_tpu.apis.resources import R
        ti = lattice.name_to_idx["m5.2xlarge"]
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.2xlarge",
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros((R,), np.float32))]
        pods = diverse_pods(80)
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                existing=existing)
        solver.inject_faults(FaultInjector(g_limit=32))
        plan = solver.solve(problem)
        assert plan.solver_path == "wave-split"
        assert_nothing_dropped(plan, 80)
        # whatever landed on node-a fits its allocatable
        placed = plan.existing_assignments.get("node-a", [])
        req_of = {n: g.req for g in problem.groups for n in g.pod_names}
        total = sum((req_of[n] for n in placed),
                    np.zeros((R,), np.float32))
        assert (total <= lattice.alloc[ti] + 1e-2).all()
        # no pseudo wave-bin names leak into the plan
        assert all(not k.startswith("__wave") for k in plan.existing_assignments)

    def test_5000_signature_batch_parity(self, solver, lattice):
        """The acceptance batch at full size, solver-level: 5,120 distinct
        signatures wave-split end to end within 2% of sequential FFD."""
        pods = diverse_pods(5120)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        assert problem.G == 5120
        solver.inject_faults(FaultInjector(g_limit=256))
        plan = solver.solve(problem)
        assert plan.solver_path == "wave-split"
        assert plan.waves == 20
        assert_nothing_dropped(plan, 5120)
        assert not plan.unschedulable
        oracle = ffd_oracle(problem)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02


class TestHostFallback:
    def test_bucket_exhaustion_falls_back(self, solver, lattice):
        """Bin-table growth exhaustion no longer drops the leftover as
        unschedulable: host FFD (unbounded bins) schedules everything."""
        # 60 node-sized pods (one bin each): far over the faked ceiling
        pods = [Pod(name=f"b{i}", requests={"cpu": "60", "memory": "64Gi"})
                for i in range(60)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.inject_faults(FaultInjector(b_limit=32))
        plan = solver.solve(problem)
        assert plan.solver_path == "host-ffd"
        assert plan.degraded and plan.degraded_reason == "b-exhausted"
        assert_nothing_dropped(plan, 60)
        assert not plan.unschedulable
        assert solver.faults.fired.get("b_exhausted", 0) >= 1
        assert any("host FFD" in w for w in plan.warnings)

    def test_device_error_retries_then_recovers(self, solver, lattice):
        pods = diverse_pods(12)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.inject_faults(FaultInjector(device_errors=1))
        plan = solver.solve(problem)
        assert plan.solver_path == "device"
        assert not plan.degraded
        assert plan.device_retries == 1
        assert solver.degraded_counts.get("device_retry", 0) == 1
        assert_nothing_dropped(plan, 12)

    def test_persistent_device_error_falls_back(self, solver, lattice):
        pods = diverse_pods(12)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.inject_faults(FaultInjector(device_errors=10))
        plan = solver.solve(problem)
        assert plan.solver_path == "host-ffd"
        assert plan.degraded and plan.degraded_reason == "device-error"
        assert_nothing_dropped(plan, 12)
        assert not plan.unschedulable
        # fallback plan quality equals the oracle by construction
        oracle = ffd_oracle(problem)
        assert plan.new_node_cost == pytest.approx(oracle.new_node_cost)

    def test_host_side_bug_goes_straight_to_fallback(self, solver, lattice,
                                                     monkeypatch):
        """A deterministic non-retryable failure must NOT pay the blind
        backoff-and-retry (the same input would fail identically) and must
        not be laundered into reason='device-error' — the taxonomy's
        retryable contract, enforced by the ladder."""
        pods = diverse_pods(12)
        problem = build_problem(pods, [NodePool(name="default")], lattice)

        def boom(self, problem, mesh=None, t0=None):
            raise KeyError("host-side bug")

        monkeypatch.setattr(Solver, "_solve_device", boom)
        plan = solver.solve(problem)
        assert plan.solver_path == "host-ffd"
        assert plan.degraded and plan.degraded_reason == "internal-error"
        assert plan.device_retries == 0
        assert solver.degraded_counts.get("device_retry", 0) == 0
        assert_nothing_dropped(plan, 12)

    def test_fallback_plan_carries_feasible_sets(self, solver, lattice):
        """Degraded plans feed the SAME launch path: every node needs its
        CreateFleet flexibility lists."""
        pods = diverse_pods(8)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.inject_faults(FaultInjector(device_errors=10))
        plan = solver.solve(problem)
        assert plan.new_nodes
        for node in plan.new_nodes:
            assert node.feasible_types
            assert node.instance_type in node.feasible_types
            assert node.zone in node.feasible_zones
            assert np.isfinite(node.price_per_hour)

    def test_relaxed_solve_reports_worst_rung(self, solver, lattice):
        """solve_relaxed aggregates provenance: one degraded round is
        never laundered into a clean-looking plan."""
        pods = diverse_pods(10)
        solver.inject_faults(FaultInjector(device_errors=10))
        plan = solver.solve_relaxed(pods, [NodePool(name="default")])
        assert plan.solver_path == "host-ffd"
        assert plan.degraded


class TestProvisionerDegraded:
    def _operator(self, lattice):
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        return Operator(options=Options(registration_delay=1.0),
                        lattice=lattice, cloud=FakeCloud(clock), clock=clock)

    def test_high_g_batch_end_to_end(self, lattice):
        """A high-G batch flows through the provisioning controller: no
        exception, claims launched for every planned node, the degraded
        metric incremented, a SolverDegraded event published, and zero
        pods dropped (all nominated or explicitly unschedulable)."""
        op = self._operator(lattice)
        op.solver.inject_faults(FaultInjector(g_limit=64))
        pods = diverse_pods(150)
        for p in pods:
            op.cluster.add_pod(p)
        result = op.provisioner.provision_once()
        assert result.degraded and result.degraded_reason == "g-overflow"
        assert result.plan.solver_path == "wave-split"
        assert result.launch_failures == 0
        assert result.pods_scheduled + result.pods_unschedulable == 150
        assert result.pods_unschedulable == 0
        m = op.metrics.get("karpenter_solver_degraded_total")
        assert m.value(path="wave-split", reason="g-overflow") >= 1
        assert op.recorder.events(reason="SolverDegraded")
        # every created claim launched
        assert result.launched == len(result.created_claims) > 0

    def test_device_failure_end_to_end(self, lattice):
        op = self._operator(lattice)
        op.solver.inject_faults(FaultInjector(device_errors=10))
        for p in diverse_pods(20):
            op.cluster.add_pod(p)
        result = op.provisioner.provision_once()
        assert result.degraded
        assert result.plan.solver_path == "host-ffd"
        assert result.pods_scheduled == 20
        m = op.metrics.get("karpenter_solver_degraded_total")
        assert m.value(path="host-ffd", reason="device-error") >= 1
        # transparent recovery: clearing the fault restores the device path
        op.solver.inject_faults(None)
        for p in diverse_pods(5, prefix="v"):
            op.cluster.add_pod(p)
        result = op.provisioner.provision_once()
        assert not result.degraded
        assert result.plan.solver_path == "device"

    def test_solver_exception_yields_partial_result(self, lattice):
        """Even a failure the ladder cannot absorb returns a PARTIAL
        result (pods stay pending) instead of killing the pass."""
        op = self._operator(lattice)
        for p in diverse_pods(5):
            op.cluster.add_pod(p)

        def boom(*a, **kw):
            raise RuntimeError("catastrophic")

        op.provisioner.solver = type("S", (), {"solve_relaxed": boom,
                                               "lattice": lattice})()
        result = op.provisioner.provision_once()
        assert result.plan is None
        assert result.degraded and result.degraded_reason == "solve-error"
        assert op.recorder.events(reason="SolverFailed")
        m = op.metrics.get("karpenter_solver_degraded_total")
        assert m.value(path="none", reason="solve-error") == 1
        # nothing was consumed: all pods still pending for the next pass
        assert len(op.cluster.pending_pods()) == 5
        # the early return must not freeze the end-of-pass gauge at its
        # previous value: the whole stuck batch reads as unschedulable
        assert result.pods_unschedulable == 5
        g = op.metrics.get("karpenter_pods_unschedulable")
        assert g.value() == 5


class TestWireMetrics:
    def test_degradation_series_registered(self):
        from karpenter_provider_aws_tpu.metrics import (Registry,
                                                        wire_core_metrics)
        m = wire_core_metrics(Registry())
        assert m["solver_degraded"].name == "karpenter_solver_degraded_total"
        assert m["solver_device_retries"].name == \
            "karpenter_solver_device_retries_total"
        assert m["solver_waves"].name == "karpenter_solver_wave_count"


class TestSatellites:
    def test_selector_keys_tolerates_bare_pods(self, lattice):
        """A Pod built without __init__ (serde fast paths, test doubles)
        must read as 'no selectors', not raise KeyError."""
        from karpenter_provider_aws_tpu.solver.problem import _selector_keys
        bare = object.__new__(Pod)
        bare.__dict__.update(name="bare", requests={"cpu": "1"})
        assert _selector_keys([bare], []) == frozenset()

    def test_eventsink_ages_out_external_events(self):
        """Events written by OTHER actors age out under the retention
        ceiling once the sink re-lists."""
        from karpenter_provider_aws_tpu.events import Event
        from karpenter_provider_aws_tpu.kube.apiserver import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.eventsink import ApiEventSink
        api = FakeAPIServer()
        sink = ApiEventSink(api, retained=10, relist_every=4)

        def publish(i):
            sink(Event(time=float(i), type="Normal", reason="r",
                       object_kind="Pod", object_name=f"p{i}", message="m"))

        for i in range(3):
            publish(i)
        # an external writer floods the store behind the sink's back
        # (non-numeric tails: adoption orders them before any sink name)
        for i in range(25):
            api.create("events", {"name": f"external-x{i}", "time": 0.0,
                                  "type": "Normal", "reason": "x",
                                  "objectKind": "Pod", "objectName": "q",
                                  "message": "m"})
        assert len(api.list("events")[0]) == 28
        for i in range(3, 3 + 8):   # crosses the relist_every=4 boundary
            publish(i)
        items, _ = api.list("events")
        assert len(items) <= 10
        # the newest sink-written events survive
        names = {o["metadata"]["name"] for o in items}
        assert f"ev-{3 + 8:06d}" in names

    def test_kpctl_unit_normalization(self, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        assert kpctl._cores("12000m") == "12"
        assert kpctl._cores("500m") == "0.5"
        assert kpctl._cores("48") == "48"
        assert kpctl._cores("-") == "-"
        assert kpctl._mem("2048Mi") == "2Gi"
        assert kpctl._mem("1.5Gi") == "1536Mi"
        assert kpctl._mem("64Gi") == "64Gi"
        assert kpctl._mem("-") == "-"

    def test_kpctl_age_anchors_to_server_clock(self, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl

        class FakeClient:
            def request(self, method, path, doc=None, stream=False):
                return {"items": [{"metadata": {"name": "e1"}}],
                        "resourceVersion": 7, "serverTime": 1000.0}

        monkeypatch.setattr(kpctl, "_SERVER_NOW", None)
        kpctl._list(FakeClient(), "events")
        assert kpctl._SERVER_NOW == 1000.0
        # ages render on the SERVER clock: an event stamped at server
        # time 940 is 60s old regardless of the local wall clock
        assert kpctl._age(940.0) == "60s"

    def test_kpctl_single_get_adopts_server_clock(self, monkeypatch):
        """`kpctl get KIND NAME` must anchor ages to the server clock too:
        every httpserver response carries X-Server-Time (the list-body
        serverTime field only covers the no-name path)."""
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        from karpenter_provider_aws_tpu.apis import serde
        from karpenter_provider_aws_tpu.kube import (FakeAPIServer,
                                                     install_admission)
        from karpenter_provider_aws_tpu.kube.httpserver import serve

        class FrozenClock:
            def now(self):
                return 5000.0

        s = FakeAPIServer(clock=FrozenClock())
        install_admission(s)
        httpd = serve(s, 0)
        try:
            c = kpctl.Client(f"http://127.0.0.1:{httpd.server_address[1]}")
            spec = serde.pod_to_dict(
                Pod(name="p0", requests={"cpu": "1", "memory": "1Gi"}))
            c.request("POST", "/apis/pods", spec)
            monkeypatch.setattr(kpctl, "_SERVER_NOW", None)
            obj = c.request("GET", "/apis/pods/p0")
            assert obj["metadata"]["name"] == "p0"
            assert kpctl._SERVER_NOW == 5000.0
        finally:
            httpd.shutdown()

    def test_soak_fault_schedule_parser(self, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import soak
        sched = soak.parse_fault_schedule(
            "60:g-limit=64, 30:device-error, 120:clear")
        assert sched == [(30.0, "device-error", None),
                         (60.0, "g-limit", 64), (120.0, "clear", None)]
        s = Solver.__new__(Solver)   # only inject_faults/faults needed
        s._solve_lock = __import__("threading").RLock()
        s.faults = None
        soak.apply_fault(s, "g-limit", 64)
        soak.apply_fault(s, "device-error", None)
        assert s.faults.g_limit == 64 and s.faults.device_errors == 3
        soak.apply_fault(s, "clear", None)
        assert s.faults is None
        with pytest.raises(SystemExit):
            soak.parse_fault_schedule("oops")
