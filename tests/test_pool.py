"""Solver-pool failover (parallel/pool.py; docs/reference/solver-pool.md):
circuit-breaker state machine on the injected clock, deadline-bounded
RPCs split by purpose, least-outstanding failover routing, the local
solve as the final rung only when the whole pool is dark, and the
control-plane weather (SidecarOutage) that drives all of it."""

import time

import pytest

from karpenter_provider_aws_tpu import trace
from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import Solver
from karpenter_provider_aws_tpu.solver import taxonomy as tx
from karpenter_provider_aws_tpu.parallel.pool import (
    CircuitBreaker, SOLVE_DEADLINE_MULTIPLIER, SolverPool,
    derive_solve_deadline, parse_addresses)
from karpenter_provider_aws_tpu.parallel.sidecar import (
    ChaosSidecar, HEALTH_TIMEOUT_SECONDS, SidecarProtocolError,
    SolverClient, classify_sidecar_failure)
from karpenter_provider_aws_tpu.trace import FlightRecorder
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5")])


def mkpods(n=4):
    return [Pod(name=f"p{i}", requests={"cpu": "500m", "memory": "1Gi"})
            for i in range(n)]


POOLS = [NodePool(name="default")]


@pytest.fixture()
def two_sidecars(lattice, tmp_path):
    s0 = ChaosSidecar(Solver(lattice), f"unix:{tmp_path}/s0.sock").start()
    s1 = ChaosSidecar(Solver(lattice), f"unix:{tmp_path}/s1.sock").start()
    yield s0, s1
    s0.set_hang(False)
    s1.set_hang(False)
    s0.kill()
    s1.kill()


def mkpool(lattice, sidecars, clock, **kw):
    # generous default: the first solve in a fresh process pays an XLA
    # compile; hang-specific tests override with a short deadline (the
    # handler stalls before any solve, so compile cost never applies)
    kw.setdefault("solve_deadline", 15.0)
    return SolverPool(lattice, ",".join(s.address for s in sidecars),
                      clock=clock, **kw)


# ---------------------------------------------------------------------------


class TestAddressParsing:
    def test_comma_list_with_whitespace(self):
        assert parse_addresses(" unix:/a.sock, host:50051 ,") == \
            ("unix:/a.sock", "host:50051")

    def test_sequence_accepted(self):
        assert parse_addresses(["a", "b"]) == ("a", "b")

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            parse_addresses(" , ")

    def test_options_layering_env_and_validation(self, monkeypatch):
        from karpenter_provider_aws_tpu.operator.options import Options
        monkeypatch.setenv("SOLVER_ADDRESSES", "unix:/a.sock,unix:/b.sock")
        assert Options.from_env().solver_address == \
            "unix:/a.sock,unix:/b.sock"
        # the singular legacy var still works, the plural wins
        monkeypatch.delenv("SOLVER_ADDRESSES")
        monkeypatch.setenv("SOLVER_ADDRESS", "unix:/c.sock")
        assert Options.from_env().solver_address == "unix:/c.sock"
        # a SET-BUT-EMPTY plural (the deploy template's placeholder)
        # counts as unset — it must not shadow the legacy var
        monkeypatch.setenv("SOLVER_ADDRESSES", "")
        assert Options.from_env().solver_address == "unix:/c.sock"
        with pytest.raises(ValueError):
            Options(solver_address=" , ").validate()
        with pytest.raises(ValueError):
            Options(solver_solve_deadline=-1.0).validate()
        with pytest.raises(ValueError):
            Options(solver_health_deadline=0.0).validate()


class TestDeadlines:
    def test_solve_deadline_derives_from_latency_budget(self):
        assert derive_solve_deadline(0.2) == pytest.approx(
            0.2 * SOLVE_DEADLINE_MULTIPLIER)

    def test_pool_derives_when_unset(self, lattice):
        p = SolverPool(lattice, "unix:/nowhere.sock", clock=FakeClock(),
                       latency_budget_seconds=0.2)
        assert p.solve_deadline == pytest.approx(10.0)
        assert p.health_deadline == pytest.approx(1.0)

    def test_explicit_deadline_wins(self, lattice):
        p = SolverPool(lattice, "unix:/nowhere.sock", clock=FakeClock(),
                       solve_deadline=3.5)
        assert p.solve_deadline == 3.5

    def test_health_rpc_has_its_own_short_deadline(self, lattice,
                                                   tmp_path):
        """Satellite pin: liveness against a HUNG sidecar returns in
        about the health deadline (~1 s), never the solve timeout."""
        sc = ChaosSidecar(Solver(lattice),
                          f"unix:{tmp_path}/hung.sock").start()
        try:
            client = SolverClient(sc.address, timeout=60.0)
            assert client.health()["ok"]
            assert client.health_timeout == HEALTH_TIMEOUT_SECONDS
            sc.set_hang(True)
            t0 = time.perf_counter()
            import grpc
            with pytest.raises(grpc.RpcError) as ei:
                client.health()
            elapsed = time.perf_counter() - t0
            assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            # well under the old shared 60 s solve timeout
            assert elapsed < 5.0
            client.close()
        finally:
            sc.set_hang(False)
            sc.kill()


class TestCircuitBreaker:
    def test_consecutive_failures_open_then_probe_recloses(self):
        clk = FakeClock()
        br = CircuitBreaker(clk, name="t")
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open" and br.opens == 1
        # probation rides the INJECTED clock, never wall time
        assert not br.probe_due()
        clk.step(60.0)
        assert br.probe_due()
        br.begin_probe()
        assert br.state == "half-open"
        br.record_success()
        assert br.state == "closed" and br.consecutive_failures == 0

    def test_half_open_failure_reopens_with_backoff(self):
        clk = FakeClock()
        br = CircuitBreaker(clk, name="t2")
        for _ in range(3):
            br.record_failure()
        first_window = br._probe_at - clk.monotonic()
        clk.step(60.0)
        br.begin_probe()
        br.record_failure()     # probe failed: re-open, doubled window
        assert br.state == "open" and br.opens == 2
        second_window = br._probe_at - clk.monotonic()
        # jitter is [0.5, 1.5): a doubled base strictly dominates even
        # max-jitter-first vs min-jitter-second comparisons on average,
        # so compare against the deterministic base bounds instead
        assert first_window <= br.open_seconds * 1.5
        assert second_window <= br.open_seconds * 2 * 1.5
        assert second_window >= br.open_seconds * 2 * 0.5

    def test_fatal_failure_opens_immediately(self):
        br = CircuitBreaker(FakeClock(), name="t3")
        br.record_failure(fatal=True)
        assert br.state == "open"

    def test_success_resets_streak(self):
        br = CircuitBreaker(FakeClock(), name="t4")
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"

    def test_jitter_is_deterministic_per_name(self):
        a1 = CircuitBreaker(FakeClock(), name="same")
        a2 = CircuitBreaker(FakeClock(), name="same")
        for br in (a1, a2):
            for _ in range(3):
                br.record_failure()
        assert a1._probe_at == a2._probe_at

    def test_backoff_caps_at_max(self):
        clk = FakeClock()
        br = CircuitBreaker(clk, name="cap", open_seconds=2.0,
                            max_open_seconds=30.0)
        for _ in range(12):
            for _ in range(3):
                br.record_failure()
            clk.step(100.0)
            br.begin_probe()
        for _ in range(3):
            br.record_failure()
        assert br._probe_at - clk.monotonic() <= 30.0 * 1.5


class TestFailover:
    def test_healthy_pool_delegates_no_failover(self, lattice,
                                                two_sidecars):
        pool = mkpool(lattice, two_sidecars, FakeClock())
        plan = pool.solve_relaxed(mkpods(), POOLS)
        assert not plan.degraded and not plan.unschedulable
        st = pool.pool_stats()
        assert st["delegated_solves"] == 1 and st["failovers"] == 0
        pool.close()

    def test_dead_endpoint_fails_over_to_survivor(self, lattice,
                                                  two_sidecars):
        s0, s1 = two_sidecars
        clock = FakeClock()
        pool = mkpool(lattice, two_sidecars, clock)
        s0.kill()
        plan = pool.solve_relaxed(mkpods(), POOLS)
        # the pass SUCCEEDED on the survivor: not degraded, but the
        # burned attempt is recorded (failover counter + plan warning)
        assert not plan.degraded
        st = pool.pool_stats()
        assert st["failovers"] >= 1 and st["ep1_solves"] == 1
        assert any("sidecar-unreachable" in w for w in plan.warnings)
        pool.close()

    def test_outstanding_balanced_on_unexpected_exception(self, lattice,
                                                          two_sidecars):
        """An exception OUTSIDE the expected (RpcError, protocol) set
        must still balance the outstanding counter — a leaked +1 would
        permanently demote the endpoint in least-outstanding routing."""
        pool = mkpool(lattice, two_sidecars, FakeClock())

        class _Boom(RuntimeError):
            pass

        def explode(*a, **k):
            raise _Boom("not an rpc failure")

        pool.endpoints[0].client().solve = explode
        with pytest.raises(_Boom):
            pool.solve_relaxed(mkpods(), POOLS)
        assert pool.endpoints[0].outstanding == 0
        pool.close()

    def test_least_outstanding_routing_deterministic_tie_break(
            self, lattice, two_sidecars):
        pool = mkpool(lattice, two_sidecars, FakeClock())
        order = pool._routable()
        # all-zero outstanding: index breaks the tie
        assert [ep.index for ep in order] == [0, 1]
        pool.endpoints[0].outstanding = 2
        assert [ep.index for ep in pool._routable()] == [1, 0]
        pool.close()

    def test_whole_pool_dark_goes_local_pool_exhausted(self, lattice,
                                                       two_sidecars):
        s0, s1 = two_sidecars
        pool = mkpool(lattice, two_sidecars, FakeClock())
        s0.kill()
        s1.kill()
        plan = pool.solve_relaxed(mkpods(), POOLS)
        assert plan.degraded
        assert plan.degraded_reason == tx.POOL_EXHAUSTED
        assert not plan.unschedulable      # the local rung still places
        st = pool.pool_stats()
        assert st["local_solves"] == 1
        assert pool.degraded_counts.get(tx.POOL_EXHAUSTED) == 1
        pool.close()

    def test_open_breakers_skip_straight_to_local(self, lattice,
                                                  two_sidecars):
        s0, s1 = two_sidecars
        clock = FakeClock()
        pool = mkpool(lattice, two_sidecars, clock)
        s0.kill()
        s1.kill()
        for _ in range(3):
            pool.solve_relaxed(mkpods(), POOLS)
        st = pool.pool_stats()
        assert st["ep0_state"] == 2 and st["ep1_state"] == 2
        before = st["failovers"]
        pool.solve_relaxed(mkpods(), POOLS)
        # no routable endpoint: the pass pays ZERO failed RPC attempts
        assert pool.pool_stats()["failovers"] == before
        pool.close()

    def test_junk_response_classifies_and_falls_through(self, lattice,
                                                        two_sidecars):
        """Satellite pin: garbage back from a sidecar is a SIDECAR
        failure (failover / local rung), never a JSONDecodeError out of
        the pass."""
        s0, s1 = two_sidecars
        pool = mkpool(lattice, two_sidecars, FakeClock())
        s0.set_junk(True)
        plan = pool.solve_relaxed(mkpods(), POOLS)
        assert not plan.degraded           # survivor carried it
        assert any("sidecar-unreachable" in w for w in plan.warnings)
        # both junking: the local rung answers, still no decode error
        s1.set_junk(True)
        plan = pool.solve_relaxed(mkpods(), POOLS)
        assert plan.degraded
        assert plan.degraded_reason == tx.POOL_EXHAUSTED
        pool.close()

    def test_recovery_probe_recloses_breaker_and_delegation_resumes(
            self, lattice, two_sidecars):
        s0, s1 = two_sidecars
        clock = FakeClock()
        pool = mkpool(lattice, two_sidecars, clock)
        s0.kill()
        s1.kill()
        for _ in range(3):
            pool.solve_relaxed(mkpods(), POOLS)
        assert pool.pool_stats()["healthy"] == 0
        s0.restart()
        s1.restart()
        clock.step(120.0)
        pool.check_endpoints()
        st = pool.pool_stats()
        assert st["healthy"] == 2
        plan = pool.solve_relaxed(mkpods(), POOLS)
        assert not plan.degraded
        assert pool.pool_stats()["delegated_solves"] >= 1
        pool.close()


class TestHang:
    def test_hung_sidecar_bounded_by_deadline_plus_one_failover(
            self, lattice, two_sidecars):
        """Satellite pin (threaded hang): the sidecar ACCEPTS and
        stalls; the pass completes within the solve deadline + one
        failover, the breaker opens (deadline-class = fatal), and a
        half-open probe re-closes it after the sidecar recovers.
        FakeClock drives probation — the only real time spent is the
        deliberately short RPC deadline itself."""
        s0, s1 = two_sidecars
        clock = FakeClock()
        pool = mkpool(lattice, two_sidecars, clock, solve_deadline=0.5)
        pool.solve_relaxed(mkpods(), POOLS)        # warm both paths
        s0.set_hang(True)
        t0 = time.perf_counter()
        plan = pool.solve_relaxed(mkpods(), POOLS)
        elapsed = time.perf_counter() - t0
        assert not plan.degraded                   # survivor carried it
        # deadline (0.5 s) + the survivor's solve + slack — nowhere near
        # the old 60 s stall
        assert elapsed < 10.0
        st = pool.pool_stats()
        assert st["ep0_state"] == 2                # opened on ONE hang
        assert any(tx.SIDECAR_HUNG in w for w in plan.warnings)
        # recovery: release the hang, step probation, probe re-closes
        s0.set_hang(False)
        clock.step(120.0)
        pool.check_endpoints()
        assert pool.pool_stats()["ep0_state"] == 0
        pool.close()


class TestRemoteSolverHardening:
    def test_classify_table(self):
        import grpc

        class _Dead(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.UNAVAILABLE

        class _Hung(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.DEADLINE_EXCEEDED

        assert classify_sidecar_failure(_Dead()) == tx.SIDECAR_UNREACHABLE
        assert classify_sidecar_failure(_Hung()) == tx.SIDECAR_HUNG
        assert classify_sidecar_failure(
            SidecarProtocolError("junk")) == tx.SIDECAR_UNREACHABLE

    def test_single_remote_solver_junk_falls_back_local(self, lattice,
                                                        tmp_path):
        """Satellite pin: the legacy single-address RemoteSolver also
        classifies a junk response as sidecar failure and takes the
        local rung with a coded reason."""
        from karpenter_provider_aws_tpu.parallel.sidecar import RemoteSolver
        sc = ChaosSidecar(Solver(lattice),
                          f"unix:{tmp_path}/junk.sock").start()
        try:
            sc.set_junk(True)
            rs = RemoteSolver(lattice, sc.address)
            plan = rs.solve_relaxed(mkpods(), POOLS)
            assert plan.degraded
            assert plan.degraded_reason == tx.SIDECAR_UNREACHABLE
            assert not plan.unschedulable
            assert rs.degraded_counts.get(tx.SIDECAR_UNREACHABLE) == 1
            rs.client.close()
        finally:
            sc.kill()

    def test_taxonomy_codes_declared(self):
        for code in (tx.SIDECAR_HUNG, tx.SIDECAR_UNREACHABLE,
                     tx.POOL_EXHAUSTED):
            assert code in tx.CODES
            assert tx.code_of(tx.reason(code, "detail")) == code


class TestTraceContinuity:
    def test_failover_pass_records_one_connected_trace(self, lattice,
                                                       two_sidecars):
        """Satellite pin: a pass that fails over mid-ladder still
        records ONE connected trace — the failed attempt span marked
        status=error with the coded reason, and the winning endpoint's
        sidecar spans in the same tree."""
        s0, s1 = two_sidecars
        rec = FlightRecorder(ring=64, retained=16,
                             latency_budget_ms=60000.0)
        trace.enable(rec)
        try:
            pool = mkpool(lattice, two_sidecars, FakeClock())
            s0.kill()
            with trace.span("provision.test") as root:
                trace_id = root.trace_id
                plan = pool.solve_relaxed(mkpods(), POOLS)
            assert not plan.degraded
            spans = rec.get(trace_id)
            assert spans, "no spans recorded for the failover pass"
            by_name = {}
            for s in spans:
                by_name.setdefault(s.name, []).append(s)
            attempts = by_name.get("solver.remote", [])
            assert len(attempts) == 2
            failed = [s for s in attempts if s.status == "error"]
            won = [s for s in attempts if s.status == "ok"]
            assert len(failed) == 1 and len(won) == 1
            assert failed[0].attrs.get("address") == s0.address
            assert failed[0].attrs.get("reason") == tx.SIDECAR_UNREACHABLE
            assert won[0].attrs.get("address") == s1.address
            # the winning endpoint's in-process sidecar spans landed in
            # the SAME tree (one trace id end to end)
            assert "sidecar.solve" in by_name
            assert all(s.trace_id == trace_id for s in spans)
            # every parent resolves inside the tree — no orphans
            ids = {s.span_id for s in spans}
            for s in spans:
                assert s.parent_id is None or s.parent_id in ids
            pool.close()
        finally:
            trace.disable()
            trace.get_tracer().recorder = None


class TestPoolObservation:
    def test_stats_report_endpoint_that_solved(self, lattice,
                                               two_sidecars):
        pool = mkpool(lattice, two_sidecars, FakeClock())
        pool.solve_relaxed(mkpods(), POOLS)
        st = pool.pool_stats()
        assert st["endpoints"] == 2 and st["healthy"] == 2
        assert st["ep0_solves"] + st["ep1_solves"] == 1
        assert st["ep0_address"] == two_sidecars[0].address
        # solver stats stay non-blocking and carry the pool's mesh view
        sst = pool.stats()
        assert "mesh_devices" in sst
        pool.close()

    def test_breaker_states_map(self, lattice, two_sidecars):
        s0, s1 = two_sidecars
        pool = mkpool(lattice, two_sidecars, FakeClock())
        s0.kill()
        s1.kill()
        for _ in range(3):
            pool.solve_relaxed(mkpods(), POOLS)
        assert pool.breaker_states() == {s0.address: "open",
                                         s1.address: "open"}
        pool.close()

    def test_operator_wires_pool_and_gauges(self, lattice, two_sidecars):
        from karpenter_provider_aws_tpu import introspect
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        s0, s1 = two_sidecars
        clock = FakeClock()
        op = Operator(options=Options(
            registration_delay=0.5,
            solver_address=f"{s0.address},{s1.address}",
            solver_solve_deadline=2.0),
            lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        assert isinstance(op.solver, SolverPool)
        assert op.solver.solve_deadline == 2.0
        assert "solver_pool" in introspect.registry().names()
        for i in range(4):
            op.cluster.add_pod(Pod(name=f"g{i}",
                                   requests={"cpu": "500m",
                                             "memory": "1Gi"}))
        op.settle(max_rounds=20)
        assert not op.cluster.pending_pods()
        op.emit_gauges()
        text = op.metrics.render()
        assert "karpenter_solver_pool_endpoints 2.0" in text
        assert "karpenter_solver_pool_healthy_endpoints 2.0" in text
        assert f'karpenter_solver_pool_breaker_state{{endpoint="{s0.address}"}} 0.0' in text
        from karpenter_provider_aws_tpu.metrics import lint_exposition
        assert lint_exposition(text) == []
        op.solver.close()

    def test_kpctl_top_pool_row(self, lattice, two_sidecars):
        import importlib
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        kpctl = importlib.import_module("kpctl")
        s0, s1 = two_sidecars
        doc = {"providers": {"solver_pool": {
            "endpoints": 2, "healthy": 1, "failovers": 3,
            "delegated_solves": 7, "local_solves": 1,
            "ep0_state": 2, "ep1_state": 0}}}
        lines = kpctl._render_top(doc, "t")
        row = next(ln for ln in lines if ln.startswith("POOL"))
        assert "2 endpoints (1 healthy)" in row
        assert "failovers 3" in row and "local 1" in row
        assert "open,closed" in row
        # provider errored ({"error": ...}): the row degrades, the view
        # survives
        doc = {"providers": {"solver_pool": {"error": "boom"}}}
        assert any(ln.startswith("POOL")
                   for ln in kpctl._render_top(doc, "t"))


class TestSidecarOutageWeather:
    def test_scenario_round_trip_and_unknown_fields(self):
        from karpenter_provider_aws_tpu.weather import (SidecarOutage,
                                                        WeatherScenario)
        sc = WeatherScenario(name="x", sidecar_outages=(
            SidecarOutage(at=5.0, duration=10.0, endpoint=1,
                          mode="hang", restart_after=False),))
        rt = WeatherScenario.from_json(sc.to_json())
        assert rt == sc
        # pre-PR-13 scenario JSON (no field) still loads
        d = sc.to_dict()
        d.pop("sidecar_outages")
        assert WeatherScenario.from_dict(d).sidecar_outages == ()

    def test_simulator_drives_outage_and_restore(self, lattice,
                                                 two_sidecars):
        from karpenter_provider_aws_tpu.weather import (SidecarOutage,
                                                        WeatherScenario,
                                                        WeatherSimulator)
        s0, s1 = two_sidecars
        sc = WeatherScenario(
            name="t", tick_seconds=1.0, reprice_every=0,
            sidecar_outages=(
                SidecarOutage(at=2.0, duration=3.0, endpoint=0,
                              mode="kill"),
                SidecarOutage(at=3.0, duration=2.0, endpoint=1,
                              mode="junk")))
        sim = WeatherSimulator(sc, lattice, seed=1,
                               sidecars=[s0, s1])
        sim.step(4)    # ticks 0-3: kill lands on tick 2, junk on tick 3
        assert not s0.alive
        assert s1.service._junk
        sim.step(2)    # ticks 4-5: both windows close on tick 5
        assert s0.alive                    # restart_after default
        assert not s1.service._junk
        kinds = [e["kind"] for e in sim.timeline
                 if e["kind"].startswith("sidecar")]
        assert kinds == ["sidecar-outage", "sidecar-outage",
                         "sidecar-restore", "sidecar-restore"]
        assert sim.counters["sidecar_outages"] == 2
        assert sim.counters["sidecar_restores"] == 2

    def test_stop_restores_sidecars(self, lattice, two_sidecars):
        from karpenter_provider_aws_tpu.weather import (SidecarOutage,
                                                        WeatherScenario,
                                                        WeatherSimulator)
        s0, s1 = two_sidecars
        sc = WeatherScenario(
            name="t", tick_seconds=1.0, reprice_every=0,
            sidecar_outages=(
                SidecarOutage(at=0.0, duration=100.0, endpoint=0,
                              mode="kill"),
                SidecarOutage(at=0.0, duration=100.0, endpoint=1,
                              mode="hang")))
        sim = WeatherSimulator(sc, lattice, seed=1, sidecars=[s0, s1])
        sim.step(2)
        assert not s0.alive and s1.service._hanging
        sim.stop()
        assert s0.alive and not s1.service._hanging

    def test_replay_identical_with_no_handles(self, lattice):
        from karpenter_provider_aws_tpu.weather import (WeatherSimulator,
                                                        named)
        sc = named("blackout")
        ticks = int(sc.duration_seconds / sc.tick_seconds) + 5
        a = WeatherSimulator.replay(sc, lattice, ticks, seed=13)
        b = WeatherSimulator.replay(sc, lattice, ticks, seed=13)
        assert a == b
        ev = [e["kind"] for e in a if e["kind"].startswith("sidecar")]
        assert ev.count("sidecar-outage") == 3
        assert ev.count("sidecar-restore") == 3

    def test_blackout_in_library_and_full_blackout_window(self):
        from karpenter_provider_aws_tpu.weather import (NAMED_SCENARIOS,
                                                        load_scenario)
        assert "blackout" in NAMED_SCENARIOS
        sc = load_scenario("blackout")
        assert sc.sidecar_outages
        modes = {o.mode for o in sc.sidecar_outages}
        assert {"kill", "hang", "junk"} <= modes
        import sys
        from pathlib import Path
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        import importlib
        soak = importlib.import_module("soak")
        # the scripted kill+hang overlap IS a full 2-endpoint blackout,
        # and a third endpoint would break it
        assert soak.full_blackout_scripted(sc, 2)
        assert not soak.full_blackout_scripted(sc, 3)

    def test_outage_beyond_handle_list_is_recorded_not_applied(
            self, lattice):
        from karpenter_provider_aws_tpu.weather import (SidecarOutage,
                                                        WeatherScenario,
                                                        WeatherSimulator)
        sc = WeatherScenario(
            name="t", tick_seconds=1.0, reprice_every=0,
            sidecar_outages=(SidecarOutage(at=0.0, duration=2.0,
                                           endpoint=7, mode="kill"),))
        sim = WeatherSimulator(sc, lattice, seed=1, sidecars=[])
        sim.step(4)    # must not raise; timeline stays deterministic
        assert [e["kind"] for e in sim.timeline
                if e["kind"].startswith("sidecar")] == \
            ["sidecar-outage", "sidecar-restore"]
