"""Tools tests: the hack/docs + allocatable-diff analogs keep working
(reference tools/allocatable-diff/main.go; hack/docs/*_gen_docs.go)."""

import csv
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))


class TestGenDocs:
    def test_generates_all_reference_pages(self, tmp_path):
        import gen_docs
        rc = gen_docs.main(["--out-dir", str(tmp_path)])
        assert rc == 0
        types = (tmp_path / "instance-types.md").read_text()
        assert "m5.large" in types and "Allocatable" in types
        metrics = (tmp_path / "metrics.md").read_text()
        assert "karpenter_nodeclaims_disrupted_total" in metrics
        assert "karpenter_cloudprovider_instance_type_offering_available" in metrics
        settings = (tmp_path / "settings.md").read_text()
        assert "--cluster-name" in settings and "CLUSTER_NAME" in settings

    def test_checked_in_docs_are_current(self):
        """docs/reference/ must match what the generator produces (the
        reference CI regenerates docs the same way)."""
        import gen_docs
        import tempfile
        repo = Path(__file__).resolve().parent.parent
        with tempfile.TemporaryDirectory() as td:
            gen_docs.main(["--out-dir", td])
            for page in ("instance-types.md", "metrics.md", "settings.md"):
                fresh = (Path(td) / page).read_text()
                checked_in = (repo / "docs" / "reference" / page).read_text()
                assert fresh == checked_in, \
                    f"docs/reference/{page} is stale — run tools/gen_docs.py"


class TestAllocatableDiff:
    def test_writes_csv_and_diffs_reported(self, tmp_path):
        import allocatable_diff
        reported = tmp_path / "reported.csv"
        with open(reported, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["instance_type", "cpu_m", "memory_mib"])
            w.writerow(["m5.large", "1930", "7000"])
        out = tmp_path / "diff.csv"
        rc = allocatable_diff.main(["--out-file", str(out),
                                    "--reported", str(reported)])
        assert rc == 0
        rows = {r["instance_type"]: r for r in csv.DictReader(open(out))}
        assert len(rows) > 700
        m5 = rows["m5.large"]
        assert "memory_diff_mib" in m5 and m5["reported_cpu_m"] == "1930"
        # capacity >= allocatable always
        assert float(m5["capacity_memory_mib"]) > float(m5["allocatable_memory_mib"])
