"""Tools tests: the hack/docs + allocatable-diff analogs keep working
(reference tools/allocatable-diff/main.go; hack/docs/*_gen_docs.go)."""

import csv
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))


class TestGenDocs:
    def test_generates_all_reference_pages(self, tmp_path):
        import gen_docs
        rc = gen_docs.main(["--out-dir", str(tmp_path)])
        assert rc == 0
        types = (tmp_path / "instance-types.md").read_text()
        assert "m5.large" in types and "Allocatable" in types
        metrics = (tmp_path / "metrics.md").read_text()
        assert "karpenter_nodeclaims_disrupted_total" in metrics
        assert "karpenter_cloudprovider_instance_type_offering_available" in metrics
        settings = (tmp_path / "settings.md").read_text()
        assert "--cluster-name" in settings and "CLUSTER_NAME" in settings

    def test_checked_in_docs_are_current(self):
        """docs/reference/ must match what the generator produces (the
        reference CI regenerates docs the same way)."""
        import gen_docs
        import tempfile
        repo = Path(__file__).resolve().parent.parent
        with tempfile.TemporaryDirectory() as td:
            gen_docs.main(["--out-dir", td])
            for page in ("instance-types.md", "metrics.md", "settings.md",
                         "compatibility.md"):
                fresh = (Path(td) / page).read_text()
                checked_in = (repo / "docs" / "reference" / page).read_text()
                assert fresh == checked_in, \
                    f"docs/reference/{page} is stale — run tools/gen_docs.py"


class TestAllocatableDiff:
    def test_writes_csv_and_diffs_reported(self, tmp_path):
        import allocatable_diff
        reported = tmp_path / "reported.csv"
        with open(reported, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["instance_type", "cpu_m", "memory_mib"])
            w.writerow(["m5.large", "1930", "7000"])
        out = tmp_path / "diff.csv"
        rc = allocatable_diff.main(["--out-file", str(out),
                                    "--reported", str(reported)])
        assert rc == 0
        rows = {r["instance_type"]: r for r in csv.DictReader(open(out))}
        assert len(rows) > 700
        m5 = rows["m5.large"]
        assert "memory_diff_mib" in m5 and m5["reported_cpu_m"] == "1930"
        # capacity >= allocatable always
        assert float(m5["capacity_memory_mib"]) > float(m5["allocatable_memory_mib"])


class TestKompat:
    """tools/kompat.py — the reference tools/kompat analog: matrix render,
    validation lints, and the app↔k8s compatibility check."""

    def test_render_and_validate_shipped_matrix(self):
        import kompat
        name, rows = kompat.load_matrix()
        assert name == "karpenter-tpu" and rows
        assert kompat.validate(rows) == []
        md = kompat.render(name, rows)
        assert "KARPENTER-TPU" in md and "Kubernetes" in md
        assert f"{rows[0].min_k8s[0]}.{rows[0].min_k8s[1]}" in md

    def test_check_inside_and_outside_range(self):
        import kompat
        _, rows = kompat.load_matrix()
        lo, hi = rows[0].min_k8s, rows[0].max_k8s
        assert kompat.check(rows, "0.1.0", f"{lo[0]}.{lo[1]}") is not None
        assert kompat.check(rows, "0.1.0", f"{hi[0]}.{hi[1] + 1}") is None
        # wildcard pattern matching: 0.1.x covers any 0.1.* but an app
        # line absent from the matrix never matches
        assert kompat.check(rows, "0.1.7", f"{lo[0]}.{lo[1]}") is not None
        assert kompat.check(rows, "0.9.0", f"{lo[0]}.{lo[1]}") is None

    def test_validate_flags_bad_ranges(self):
        import kompat
        bad = [kompat.Row("0.1.x", (1, 28), (1, 26))]
        assert kompat.validate(bad)
        regress = [kompat.Row("0.1.x", (1, 24), (1, 28)),
                   kompat.Row("0.2.x", (1, 24), (1, 27))]
        assert any("regressed" in e for e in kompat.validate(regress))

    def test_version_provider_pairs_with_matrix(self):
        """The live control-plane version the version provider discovers
        must be accepted by the shipped matrix (the operator's pre-flight
        check an operator would run)."""
        import kompat
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.providers.version import VersionProvider
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        import karpenter_provider_aws_tpu as pkg
        v = VersionProvider(FakeCloud(FakeClock())).get()
        _, rows = kompat.load_matrix()
        # the SHIPPED version must be covered by the shipped matrix
        assert kompat.check(rows, pkg.__version__, v) is not None, (pkg.__version__, v)


class TestWebhookPdb:
    def test_pdb_validation_one_of(self):
        import pytest
        from karpenter_provider_aws_tpu.apis import PodDisruptionBudget
        from karpenter_provider_aws_tpu.webhooks import (
            AdmissionError, admit_pdb, validate_pdb)
        ok = PodDisruptionBudget(name="x", max_unavailable=1)
        assert validate_pdb(ok) == [] and admit_pdb(ok) is ok
        assert validate_pdb(PodDisruptionBudget(name="x"))           # neither
        assert validate_pdb(PodDisruptionBudget(name="x", max_unavailable=1,
                                                min_available=1))    # both
        assert validate_pdb(PodDisruptionBudget(name="x", min_available=-1))
        with pytest.raises(AdmissionError):
            admit_pdb(PodDisruptionBudget(name="x"))


class TestDeflake:
    def test_deflake_runs_and_reports(self):
        """One clean repetition over a tiny fast module proves the harness
        loop, seed variation, and exit-code plumbing."""
        import deflake
        rc = deflake.main(["-n", "2", "tests/test_units.py"])
        assert rc == 0


class TestDebugDumpers:
    def test_snapshot_and_dump(self):
        """debug.snapshot/dump_state over a live control plane (the
        reference's test/pkg/debug watcher analog)."""
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.debug import Monitor, dump_state, snapshot
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "t3")])
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        mon = Monitor(op)
        mon.sample()
        for i in range(3):
            op.cluster.add_pod(Pod(name=f"p{i}",
                                   requests={"cpu": "1", "memory": "2Gi"}))
        s0 = mon.sample()
        assert s0["pending_pods"] == 3 and s0["nodes"] == 0
        op.settle()
        s1 = mon.sample()
        assert s1["pending_pods"] == 0 and s1["nodes"] >= 1
        assert s1["cost_per_hour"] > 0
        text = dump_state(op)
        assert "control-plane dump" in text
        assert "p0" in text and "phase=Initialized" in text
        summ = mon.summary()
        assert summ["samples"] == 3 and summ["peak_pending_pods"] == 3

    def test_monitor_writes_artifact(self, tmp_path):
        import json
        from karpenter_provider_aws_tpu.debug import Monitor
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("t3",)])
        op = Operator(options=Options(), lattice=lattice, clock=FakeClock())
        mon = Monitor(op)
        mon.sample(); mon.sample()
        out = tmp_path / "ts.json"
        mon.write(str(out))
        doc = json.loads(out.read_text())
        assert len(doc["samples"]) == 2
        assert doc["summary"]["samples"] == 2
