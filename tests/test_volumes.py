"""Persistent Volume zonal topology.

Behavioral spec: reference website concepts/scheduling.md:389-398 — the
scheduler follows Pod → PVC → StorageClass, restricts new nodes to the
class's allowedTopologies for unbound WaitForFirstConsumer claims, pins to
the PV's zone once one exists, and later consumers of the claim follow it.
"""

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, PersistentVolumeClaim, Pod, Requirement,
    StorageClass,
)
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def vol_pod(name, claims):
    return Pod(name=name, requests={"cpu": "1", "memory": "2Gi"},
               volume_claims=list(claims))


class TestVolumeTopologySolve:
    def test_unbound_wffc_restricts_to_allowed_topologies(self, solver, lattice):
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert all(n.zone in ("us-west-2a", "us-west-2b") for n in plan.new_nodes)
        assert all(z in ("us-west-2a", "us-west-2b")
                   for n in plan.new_nodes for z in n.feasible_zones)

    def test_bound_pv_pins_exact_zone(self, solver, lattice):
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs",
                                              bound_zone="us-west-2c")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert [n.zone for n in plan.new_nodes] == ["us-west-2c"]

    def test_bound_pv_outside_pool_zones_is_unschedulable(self, solver, lattice):
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, ("us-west-2a",))])
        pvcs = {"data": PersistentVolumeClaim(name="data",
                                              bound_zone="us-west-2c")}
        problem = build_problem([vol_pod("p0", ["data"])], [pool], lattice,
                                pvcs=pvcs)
        plan = solver.solve(problem)
        assert "p0" in plan.unschedulable

    def test_distinct_claims_distinct_groups(self, solver, lattice):
        pvcs = {"a": PersistentVolumeClaim(name="a", bound_zone="us-west-2a"),
                "b": PersistentVolumeClaim(name="b", bound_zone="us-west-2b")}
        problem = build_problem([vol_pod("pa", ["a"]), vol_pod("pb", ["b"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zone_of = {p: n.zone for n in plan.new_nodes for p in n.pods}
        assert zone_of["pa"] == "us-west-2a" and zone_of["pb"] == "us-west-2b"

    def test_unknown_pvc_warns_but_schedules(self, solver, lattice):
        problem = build_problem([vol_pod("p0", ["ghost"])],
                                [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert any("unknown PVC" in w for w in plan.warnings)

    def test_unknown_storage_class_warns(self, solver, lattice):
        pvcs = {"data": PersistentVolumeClaim(name="data",
                                              storage_class="missing")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert any("unknown StorageClass" in w for w in plan.warnings)

    def test_shared_claim_pin_respects_consumer_constraints(self, solver, lattice):
        """The shared-claim pin must come from the INTERSECTION of consumer
        zone constraints: two pods requiring us-west-2b sharing a claim
        allowed in 2a/2b must land in 2b, not be rejected by a naive
        first-eligible 2a pin."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    node_selector={wk.LABEL_ZONE: "us-west-2b"},
                    volume_claims=["data"]) for i in range(2)]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert {n.zone for n in plan.new_nodes} == {"us-west-2b"}

    def test_shared_claim_pin_follows_sibling_bound_claim(self, solver, lattice):
        """A consumer whose OTHER claim is bound to 2b drags the shared
        unbound claim's pin to 2b for every consumer."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs"),
                "pinB": PersistentVolumeClaim(name="pinB",
                                              bound_zone="us-west-2b")}
        pods = [vol_pod("pa", ["pinB", "data"]), vol_pod("pb", ["data"])]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert {n.zone for n in plan.new_nodes} == {"us-west-2b"}

    def test_shared_unbound_claim_pins_one_zone(self, solver, lattice):
        """Same-batch consumers of one unbound WFFC claim must land in ONE
        zone — the bind would otherwise strand the losers."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        pods = [vol_pod(f"p{i}", ["data"]) for i in range(6)]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zones = {n.zone for n in plan.new_nodes}
        assert len(zones) == 1 and zones <= {"us-west-2a", "us-west-2b"}


class TestVolumeBindingLifecycle:
    def test_wffc_binds_on_landing_and_pins_successor(self, lattice):
        """First consumer lands somewhere in the allowed zones; the PV binds
        to that zone; a later pod using the same claim follows it."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(
            StorageClass(name="ebs", zones=("us-west-2a", "us-west-2b")))
        env.cluster.add_pvc(PersistentVolumeClaim(name="data", storage_class="ebs"))
        env.cluster.add_pod(vol_pod("first", ["data"]))
        env.settle()
        pod = env.cluster.pods["first"]
        assert pod.node_name
        zone = env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE]
        assert zone in ("us-west-2a", "us-west-2b")
        assert env.cluster.pvcs["data"].bound_zone == zone
        # the first consumer goes away; a successor reuses the claim
        env.cluster.delete_pod("first")
        env.cluster.add_pod(vol_pod("second", ["data"]))
        env.settle()
        pod2 = env.cluster.pods["second"]
        assert pod2.node_name
        assert env.cluster.nodes[pod2.node_name].labels[wk.LABEL_ZONE] == zone

    def test_cross_batch_consumer_converges_before_registration(self, lattice):
        """A consumer arriving while the first consumer's node is still
        registering must see the claim already pinned (bound at launch
        success, not at node registration)."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=30.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(
            StorageClass(name="ebs", zones=("us-west-2a", "us-west-2b")))
        env.cluster.add_pvc(PersistentVolumeClaim(name="data", storage_class="ebs"))
        env.cluster.add_pod(vol_pod("first", ["data"]))
        env.provisioner.provision_once()          # launch; node NOT registered
        (claim,) = env.cluster.claims.values()
        assert claim.zone is not None
        assert env.cluster.pvcs["data"].bound_zone == claim.zone
        env.cluster.add_pod(vol_pod("second", ["data"]))
        env.settle()
        for name in ("first", "second"):
            pod = env.cluster.pods[name]
            assert pod.node_name
            assert (env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE]
                    == env.cluster.pvcs["data"].bound_zone)

    def test_immediate_binding_pins_before_any_pod(self, lattice):
        """Immediate StorageClass: the PV exists before the first consumer;
        the pod follows the claim's zone."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(StorageClass(
            name="io2", zones=("us-west-2c",), binding_mode="Immediate"))
        env.cluster.add_pvc(PersistentVolumeClaim(name="fast", storage_class="io2"))
        assert env.cluster.pvcs["fast"].bound_zone == "us-west-2c"
        env.cluster.add_pod(vol_pod("p0", ["fast"]))
        env.settle()
        pod = env.cluster.pods["p0"]
        assert pod.node_name
        assert env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE] == "us-west-2c"


class TestVolumeAttachLimits:
    """Per-node CSI volume attach limits (reference
    troubleshooting.md:277-299: the core scheduler counts CSI volumes
    against the CSINode attach limit; in-tree plugins publish no limits)."""

    def test_lattice_carries_attach_limits(self, lattice):
        from karpenter_provider_aws_tpu.apis.resources import axis
        from karpenter_provider_aws_tpu.lattice.overhead import ebs_attach_limit
        vol = lattice.alloc[:, axis("attachable-volumes")]
        assert (vol >= 1).all()
        for i, s in enumerate(lattice.specs):
            assert vol[i] == ebs_attach_limit(s.hypervisor, s.enis)

    def _claim_heavy(self, n_pods, claims_each, sc="gp3"):
        pvcs = {}
        pods = []
        for i in range(n_pods):
            names = [f"c{i}-{j}" for j in range(claims_each)]
            for c in names:
                pvcs[c] = PersistentVolumeClaim(name=c, storage_class=sc)
            pods.append(vol_pod(f"v{i}", names))
        return pods, pvcs

    def test_attach_limit_spreads_nodes(self, solver, lattice):
        """8 pods x 5 distinct claims = 40 attachments: more than one
        m5/c5-size node's slot budget, though cpu/memory alone would
        happily co-locate them."""
        from karpenter_provider_aws_tpu.apis.resources import axis
        pods, pvcs = self._claim_heavy(8, 5)
        scs = {"gp3": StorageClass(name="gp3")}
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        vol_ax = axis("attachable-volumes")
        for node in plan.new_nodes:
            ti = lattice.name_to_idx[node.instance_type]
            attached = sum(5 for p in node.pods)
            assert attached <= lattice.alloc[ti, vol_ax]

    def test_in_tree_provisioner_warns_and_skips(self, solver, lattice):
        pods, pvcs = self._claim_heavy(2, 2, sc="gp2-intree")
        scs = {"gp2-intree": StorageClass(
            name="gp2-intree", provisioner="kubernetes.io/aws-ebs")}
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        from karpenter_provider_aws_tpu.apis.resources import axis
        assert all(g.req[axis("attachable-volumes")] == 0
                   for g in problem.groups)
        assert any("in-tree" in w for w in problem.warnings)

    def test_bound_pods_consume_attach_slots(self, lattice):
        """Resident volume pods reduce an existing node's remaining slots."""
        from karpenter_provider_aws_tpu.apis.objects import Node
        from karpenter_provider_aws_tpu.apis.resources import axis
        from karpenter_provider_aws_tpu.state.cluster import ClusterState
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        cluster = ClusterState(FakeClock())
        itype = "m5.4xlarge"
        node = Node(name="n0", provider_id="aws:///us-west-2a/i-0",
                    labels={wk.LABEL_INSTANCE_TYPE: itype,
                            wk.LABEL_ZONE: "us-west-2a",
                            wk.LABEL_CAPACITY_TYPE: "on-demand"},
                    ready=True)
        cluster.add_node(node)
        cluster.add_pvc(PersistentVolumeClaim(name="c0", storage_class="gp3",
                                              bound_zone="us-west-2a"))
        cluster.add_storage_class(StorageClass(name="gp3"))
        bound = vol_pod("resident", ["c0"])
        bound.node_name = "n0"
        cluster.add_pod(bound)
        bins = cluster.existing_bins(lattice)
        assert len(bins) == 1
        assert bins[0].used[axis("attachable-volumes")] == 1

    def test_alloc_override_nan_falls_back_to_lattice(self, solver, lattice):
        """A node reporting only cpu/memory keeps the lattice's attach
        limit instead of a zero that would evict every volume pod."""
        from karpenter_provider_aws_tpu.apis.resources import axis, canonical_to_vec
        from karpenter_provider_aws_tpu.solver.problem import ExistingBin
        import numpy as np
        itype = "m5.4xlarge"
        ti = lattice.name_to_idx[itype]
        ov = canonical_to_vec({"cpu": 15000.0, "memory": 60000.0,
                               "pods": 110.0}, missing=np.nan)
        existing = [ExistingBin(
            name="n0", node_pool="default", instance_type=itype,
            zone="us-west-2a", capacity_type="on-demand",
            used=np.zeros_like(lattice.alloc[ti]), alloc_override=ov)]
        pvcs = {"c0": PersistentVolumeClaim(name="c0", storage_class="gp3",
                                            bound_zone="us-west-2a")}
        scs = {"gp3": StorageClass(name="gp3")}
        problem = build_problem([vol_pod("v0", ["c0"])],
                                [NodePool(name="default")], lattice,
                                existing=existing, pvcs=pvcs,
                                storage_classes=scs)
        vol_ax = axis("attachable-volumes")
        assert problem.e_alloc[0, vol_ax] == lattice.alloc[ti, vol_ax]
        assert problem.e_alloc[0, axis("cpu")] == 15000.0
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert plan.existing_assignments.get("n0") == ["v0"]

    def test_shared_claim_dedups_on_node(self, lattice):
        """Two resident pods sharing one RWO claim hold ONE attach slot."""
        from karpenter_provider_aws_tpu.apis.objects import Node
        from karpenter_provider_aws_tpu.apis.resources import axis
        from karpenter_provider_aws_tpu.state.cluster import ClusterState
        cluster = ClusterState(FakeClock())
        cluster.add_node(Node(
            name="n0", provider_id="aws:///us-west-2a/i-0",
            labels={wk.LABEL_INSTANCE_TYPE: "m5.4xlarge",
                    wk.LABEL_ZONE: "us-west-2a",
                    wk.LABEL_CAPACITY_TYPE: "on-demand"}, ready=True))
        cluster.add_pvc(PersistentVolumeClaim(name="shared", storage_class="gp3",
                                              bound_zone="us-west-2a"))
        cluster.add_storage_class(StorageClass(name="gp3"))
        for i in range(2):
            p = vol_pod(f"r{i}", ["shared"])
            p.node_name = "n0"
            cluster.add_pod(p)
        bins = cluster.existing_bins(lattice)
        assert bins[0].used[axis("attachable-volumes")] == 1

    def test_serde_roundtrips_nan_override_and_provisioner(self, lattice):
        """NaN override axes ride the JSON wire as nulls (RFC 8259: no NaN
        token) and StorageClass.provisioner survives the round trip."""
        import json
        import numpy as np
        from karpenter_provider_aws_tpu.apis import serde
        from karpenter_provider_aws_tpu.apis.resources import R, canonical_to_vec
        from karpenter_provider_aws_tpu.solver.problem import ExistingBin
        ov = canonical_to_vec({"cpu": 1000.0}, missing=np.nan)
        b = ExistingBin(name="n0", node_pool="p", instance_type="m5.xlarge",
                        zone="us-west-2a", capacity_type="spot",
                        used=np.zeros((R,), np.float32), alloc_override=ov)
        wire = json.dumps(serde.existing_bin_to_dict(b))
        assert "NaN" not in wire
        back = serde.existing_bin_from_dict(json.loads(wire))
        assert np.isnan(back.alloc_override).sum() == R - 1
        assert back.alloc_override[0] == 1000.0

        sc = StorageClass(name="gp2", provisioner="kubernetes.io/aws-ebs")
        back_sc = serde.storage_class_from_dict(
            json.loads(json.dumps(serde.storage_class_to_dict(sc))))
        assert back_sc.provisioner == "kubernetes.io/aws-ebs"

    def test_metal_counts_as_nitro(self):
        from karpenter_provider_aws_tpu.lattice.overhead import ebs_attach_limit
        assert ebs_attach_limit("", 15) == 28 - 15 - 1
        assert ebs_attach_limit("xen", 8) == 39
        assert ebs_attach_limit("nitro", 4) == 23


class TestAdvisorR3Regressions:
    def test_daemonset_volume_claims_charge_attach_slots(self, lattice):
        """A daemonset mounting CSI PVCs consumes an attach slot on EVERY
        node of the pool: its ds_overhead vector must carry the
        attachable-volumes charge like pending groups do (advisor r3 #1)."""
        from karpenter_provider_aws_tpu.apis.resources import axis
        ds = Pod(name="csi-agent", is_daemonset=True,
                 requests={"cpu": "100m", "memory": "128Mi"},
                 volume_claims=["ds-cache"])
        pvcs = {"ds-cache": PersistentVolumeClaim(
            name="ds-cache", storage_class="gp3")}
        scs = {"gp3": StorageClass(name="gp3")}
        problem = build_problem(
            [vol_pod("p0", [])], [NodePool(name="default")], lattice,
            daemonset_pods=[ds], pvcs=pvcs, storage_classes=scs)
        assert problem.ds_overhead[0, axis("attachable-volumes")] == 1
